// Native volume-server data plane (reads + plain writes).
//
// The reference's data plane is Go: goroutine-per-connection HTTP serving
// needle reads straight off the volume files (reference
// weed/server/volume_server_handlers_read.go) and plain needle writes
// appended under a per-volume lock (volume_server_handlers_write.go:18,
// topology/store_replicate.go:20-83). The Python server keeps full
// semantics but is GIL-bound (~2.7k reads/s, ~0.9k writes/s per
// process); this library is the native equivalent of the reference's hot
// loops: a thread-per-connection keep-alive HTTP/1.1 server that parses
// `GET|POST /<vid>,<fid>`, serves reads from an in-process index mirror
// (synced from Python over ctypes), and — for volumes Python has handed
// the write lease to — parses multipart uploads, builds the needle
// record, appends .dat + .idx under a per-volume mutex, and updates the
// mirror, all without Python in the loop.
//
// WRITE OWNERSHIP. While a volume's writer is enabled, this library is
// the SINGLE writer of that volume's .dat and .idx tails: Python's own
// write/delete paths delegate their appends through swhp_append (the
// same mutex), and structural operations (compaction commit, copy,
// tail-receive) first disable the writer — a mutex-barrier handback —
// then reload their needle map from the .idx this library kept
// authoritative. The index mirror is therefore exact (not best-effort)
// in writer mode, and Python consults it as the source of truth.
//
// Scope is the FAST PATH only. Anything with semantics beyond a plain
// stored needle — gzip-stored payloads, chunk manifests, Seaweed-* pair
// headers, image resize queries, EC volumes, remote volumes, query
// params (?ttl, ?cm, ?ts, replication hops), JWT-guarded or replicated
// writes — is answered with a 307 redirect to the Python server
// (`fallback`), which remains the source of truth. Correctness parity
// for the served cases is pinned by tests/test_native_plane.py and
// tests/test_native_write_plane.py against the Python responses.
//
// Needle layout parsed here == storage/needle.py (byte-compatible with
// reference weed/storage/needle/needle_read_write.go):
//   header: Cookie(4) Id(8) Size(4) big-endian
//   v2/v3 body: DataSize(4) Data Flags(1) [Name] [Mime] [LastModified(5)]
//               [TTL(2)] [PairsSize(2) Pairs] CRC(4) [AppendAtNs(8)] pad8
// CRC is masked Castagnoli over Data (reference crc.go:25).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <list>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>

namespace {

// ---------------------------------------------------------------- crc32c
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    const uint32_t poly = 0x82F63B78u;
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
      t[0][i] = c;
    }
    for (int j = 1; j < 8; j++)
      for (uint32_t i = 0; i < 256; i++)
        t[j][i] = t[j - 1][i] >> 8 ^ t[0][t[j - 1][i] & 0xFF];
  }
};
const CrcTables g_crc;

uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = ~0u;
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    crc ^= static_cast<uint32_t>(data[i]) |
           (static_cast<uint32_t>(data[i + 1]) << 8) |
           (static_cast<uint32_t>(data[i + 2]) << 16) |
           (static_cast<uint32_t>(data[i + 3]) << 24);
    crc = g_crc.t[7][crc & 0xFF] ^ g_crc.t[6][(crc >> 8) & 0xFF] ^
          g_crc.t[5][(crc >> 16) & 0xFF] ^ g_crc.t[4][crc >> 24] ^
          g_crc.t[3][data[i + 4]] ^ g_crc.t[2][data[i + 5]] ^
          g_crc.t[1][data[i + 6]] ^ g_crc.t[0][data[i + 7]];
  }
  for (; i < n; i++) crc = g_crc.t[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

uint32_t masked_crc(uint32_t crc) {  // reference crc.go:25
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// --------------------------------------------------------------- needles
constexpr int kHeaderSize = 16;
constexpr int kChecksumSize = 4;
constexpr int kTimestampSize = 8;
constexpr int kPaddingSize = 8;
constexpr uint32_t kTombstoneSize = 0xFFFFFFFFu;

constexpr uint8_t kFlagGzip = 0x01;
constexpr uint8_t kFlagHasName = 0x02;
constexpr uint8_t kFlagHasMime = 0x04;
constexpr uint8_t kFlagHasLastModified = 0x08;
constexpr uint8_t kFlagHasTtl = 0x10;
constexpr uint8_t kFlagHasPairs = 0x20;
constexpr uint8_t kFlagChunkManifest = 0x80;

uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = v << 8 | p[i];
  return v;
}
uint32_t be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}

int64_t actual_size(uint32_t size, int version) {
  int64_t base = kHeaderSize + static_cast<int64_t>(size) + kChecksumSize;
  if (version == 3) base += kTimestampSize;
  // reference PaddingLength never returns 0 (needle_read_write.go:287)
  return base + (kPaddingSize - base % kPaddingSize);
}

// minutes per TTL unit (storage/types.py _UNIT_MINUTES)
int64_t ttl_minutes(uint8_t count, uint8_t unit) {
  static const int64_t per[] = {0, 1, 60, 1440, 10080, 44640, 525600};
  return unit < 7 ? count * per[unit] : 0;
}

struct ParsedNeedle {
  uint32_t cookie = 0;
  uint64_t id = 0;
  uint32_t size = 0;
  const uint8_t* data = nullptr;  // into the read buffer
  uint32_t data_size = 0;
  uint8_t flags = 0;
  std::string name, mime;
  int64_t last_modified = 0;  // unix seconds
  uint8_t ttl_count = 0, ttl_unit = 0;
  uint32_t checksum = 0;  // stored masked crc
};

// Returns 0 ok, -1 corrupt.
int parse_needle(const uint8_t* blob, size_t len, int version,
                 ParsedNeedle* out) {
  if (len < kHeaderSize) return -1;
  out->cookie = be32(blob);
  out->id = be64(blob + 4);
  out->size = be32(blob + 12);
  size_t size = out->size;
  if (kHeaderSize + size + kChecksumSize > len) return -1;
  const uint8_t* b = blob + kHeaderSize;
  if (version == 1) {
    out->data = b;
    out->data_size = out->size;
    out->flags = 0;
  } else {
    // v2/v3 body of `size` bytes
    size_t idx = 0;
    if (size > 0) {
      if (idx + 4 > size) return -1;
      out->data_size = be32(b + idx);
      idx += 4;
      if (idx + out->data_size >= size) return -1;  // flags byte must follow
      out->data = b + idx;
      idx += out->data_size;
      out->flags = b[idx++];
    }
    if (idx < size && (out->flags & kFlagHasName)) {
      uint8_t n = b[idx++];
      if (idx + n > size) return -1;
      out->name.assign(reinterpret_cast<const char*>(b + idx), n);
      idx += n;
    }
    if (idx < size && (out->flags & kFlagHasMime)) {
      uint8_t n = b[idx++];
      if (idx + n > size) return -1;
      out->mime.assign(reinterpret_cast<const char*>(b + idx), n);
      idx += n;
    }
    if (idx < size && (out->flags & kFlagHasLastModified)) {
      if (idx + 5 > size) return -1;
      int64_t v = 0;
      for (int i = 0; i < 5; i++) v = v << 8 | b[idx + i];
      out->last_modified = v;
      idx += 5;
    }
    if (idx < size && (out->flags & kFlagHasTtl)) {
      if (idx + 2 > size) return -1;
      out->ttl_count = b[idx];
      out->ttl_unit = b[idx + 1];
      idx += 2;
    }
  }
  out->checksum = be32(b + size);
  return 0;
}

// ---------------------------------------------------------------- server
struct Server;

// One group-commit rider whose HTTP ack the committer sends after the
// covering fdatasync: holds a dup of the connection fd (owned — closed
// on destruction), the pre-built 200 response, and a 307 fallback for
// the poison path. t0_us/bytes/target feed per-request telemetry; t0_us
// is 0 when stats were off at request start (clock-free discipline).
struct DeferredAck {
  int fd = -1;
  uint64_t seq = 0;
  std::string resp;      // full HTTP bytes of the success ack
  std::string fallback;  // full HTTP bytes of the 307 poison redirect
  uint64_t t0_us = 0;
  uint64_t bytes = 0;
  std::string target;
  DeferredAck() = default;
  DeferredAck(const DeferredAck&) = delete;
  DeferredAck& operator=(const DeferredAck&) = delete;
  DeferredAck(DeferredAck&& o) noexcept { *this = std::move(o); }
  DeferredAck& operator=(DeferredAck&& o) noexcept {
    if (this == &o) return *this;
    if (fd >= 0) close(fd);
    fd = o.fd;
    o.fd = -1;
    seq = o.seq;
    resp = std::move(o.resp);
    fallback = std::move(o.fallback);
    t0_us = o.t0_us;
    bytes = o.bytes;
    target = std::move(o.target);
    return *this;
  }
  ~DeferredAck() {
    if (fd >= 0) close(fd);
  }
};

// Write lease for one volume: fds + append offset + counter deltas.
// While enabled, every .dat/.idx append (fast-path POSTs AND Python's
// delegated writes via swhp_append) serializes on `mu`; disabling takes
// `mu`, so after swhp_disable_writer returns no append is in flight.
struct Writer {
  int fd = -1;      // O_RDWR on the .dat (appends via pwrite at tail)
  int idx_fd = -1;  // O_APPEND on the .idx
  std::mutex mu;
  std::atomic<bool> accept_posts{false};  // fast-path POSTs allowed
  // tail is written under mu; atomic so counter reads stay lock-free
  std::atomic<int64_t> tail{0};
  int64_t idx_tail = 0;     // .idx size (for torn-entry truncation)
  int offset_width = 4;     // 4 (32GB) or 5 (8TB) — .idx record width
  int64_t max_size = 0;     // addressing ceiling for this offset width
  int64_t file_size_limit = 0;  // per-upload data cap (0 = unlimited)
  // counter deltas since enable, mirroring NeedleMap._apply
  // (storage/needle_map.py:85): Python adds these to its (frozen)
  // needle-map counters for heartbeats while the lease is out
  std::atomic<uint64_t> puts{0}, put_bytes{0};
  std::atomic<uint64_t> deletes{0}, deleted_bytes{0};
  std::atomic<uint64_t> max_key{0};

  // -- group-commit durability (SW_PLANE_FSYNC_MODE). In group mode a
  // dedicated committer amortizes ONE fdatasync over every append that
  // landed inside the commit window; an append is acked only after the
  // fdatasync covering its sequence number returned. `sync_mu` is the
  // INNER lock (taken with `mu` held to publish a sequence, and alone
  // by the committer/waiters — the committer never takes `mu`).
  int sync_mode = 0;        // 0 off, 1 group, 2 always; frozen at enable
  uint64_t batch_us = 2000;     // commit window (SW_PLANE_FSYNC_BATCH_US)
  uint64_t max_pending = 512;   // riders forcing an early commit
  Server* srv = nullptr;        // telemetry sink (server-global counters)
  int sync_dat_fd = -1, sync_idx_fd = -1;  // committer's dup'd fds
  std::mutex sync_mu;
  std::condition_variable sync_cv;  // wakes the committer
  // riders wait on the cv matching their batch's parity, so a commit
  // wakes only its own cohort — one shared cv would spuriously wake
  // (and context-switch) every rider of the batch still accumulating
  std::condition_variable ack_cv[2];
  // Deferred acks: the common-case rider doesn't block at all — it
  // leaves a pre-built response (and a poison fallback) with the
  // committer, which sends it once the covering fdatasync returns.
  // Owns a dup of the connection fd so the conn thread's own
  // lifecycle (close on hangup/non-keepalive) can't race the send.
  std::deque<DeferredAck> deferred;  // seq-ordered, under sync_mu
  uint64_t sync_gen = 0;     // open commit generation (under sync_mu)
  uint64_t append_seq = 0;   // last sequence appended (under mu+sync_mu)
  uint64_t synced_seq = 0;   // last sequence covered by an fdatasync
  bool sync_failed = false;  // poisoned: an fdatasync failed — fail-stop
  bool committer_stop = false;
  std::thread committer;

  // Idempotent committer teardown: the committer drains every pending
  // sequence with a FINAL fdatasync before exiting, so appends enqueued
  // before the stop get durable acks rather than hanging; appends that
  // arrive after see committer_stop and poison themselves (-5).
  void stop_committer() {
    {
      std::lock_guard<std::mutex> sg(sync_mu);
      committer_stop = true;
      sync_cv.notify_all();
    }
    if (committer.joinable()) committer.join();
  }

  ~Writer() {
    stop_committer();
    // the committer closes its dups at loop exit; these remain only
    // when enable failed before the thread spawned
    if (sync_dat_fd >= 0) close(sync_dat_fd);
    if (sync_idx_fd >= 0) close(sync_idx_fd);
    if (fd >= 0) close(fd);
    if (idx_fd >= 0) close(idx_fd);
  }
};

struct VolumeRec {
  int fd = -1;
  int version = 3;
  std::string dat_path;
  std::unordered_map<uint64_t, std::pair<uint64_t, uint32_t>> index;
  std::shared_ptr<Writer> writer;  // guarded by mu (shared: read lock)
  mutable std::shared_mutex mu;
  ~VolumeRec() {
    if (fd >= 0) close(fd);
  }
  std::shared_ptr<Writer> get_writer() const {
    std::shared_lock<std::shared_mutex> l(mu);
    return writer;
  }
};

// ------------------------------------------------------------ EC volumes
// Mirror of an EC-mounted volume: the .ecx needle index (key ->
// (dat offset, size)) plus the striping geometry (ec/locate.py) so a
// needle's logical .dat range maps to (shard id, offset in shard file)
// without Python in the loop. Locally-held data shards are read straight
// from their files; a lost shard's bytes come from the reconstructed-slab
// cache below — if every covering slab is resident the GET never leaves
// the plane.
constexpr int kDataShards = 10;   // ec/constants.py DATA_SHARDS
constexpr int kMaxEcShards = 32;  // data+parity ceiling (codec max)

struct EcVolumeRec {
  int version = 3;
  int64_t dat_size = 0;  // original .dat size (drives the row split)
  int64_t large_block = 0, small_block = 0;
  int64_t slab_bytes = 0;  // cache slab size (SW_EC_DEGRADED_SLAB_BYTES)
  int shard_fds[kMaxEcShards];  // -1 = shard not local (lost or remote)
  std::unordered_map<uint64_t, std::pair<uint64_t, uint32_t>> index;
  mutable std::shared_mutex mu;  // guards index + shard_fds
  EcVolumeRec() {
    for (int i = 0; i < kMaxEcShards; i++) shard_fds[i] = -1;
  }
  ~EcVolumeRec() {
    for (int i = 0; i < kMaxEcShards; i++)
      if (shard_fds[i] >= 0) close(shard_fds[i]);
  }
};

// encoder-exact large-row count (ec/locate.py n_large_rows_for)
int64_t ec_n_large_rows(int64_t dat_size, int64_t large_block) {
  if (dat_size <= 0) return 0;
  return (dat_size - 1) / (large_block * kDataShards);
}

// ------------------------------------------------------------ slab cache
// Byte-budgeted LRU of reconstructed slabs, keyed (vid, sid, slab index),
// fed from Python (swhp_cache_put publishes what DegradedReadEngine just
// reconstructed) and invalidated on mount/rebuild. One plain mutex guards
// the map, the recency list AND the counters: the counters must be exact
// (tests hammer put/invalidate under concurrent reads and assert totals),
// and the critical sections are tiny — values are shared_ptrs, so readers
// copy outside the lock and an invalidate can never tear an in-flight
// read.
struct SlabKey {
  uint64_t vs;  // vid << 32 | sid
  uint64_t idx;
  bool operator==(const SlabKey& o) const {
    return vs == o.vs && idx == o.idx;
  }
};
struct SlabKeyHash {
  size_t operator()(const SlabKey& k) const {
    uint64_t x = (k.vs ^ (k.idx * 0x9E3779B97F4A7C15ull)) + k.idx;
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDull;
    x ^= x >> 33;
    return static_cast<size_t>(x);
  }
};

struct SlabCache {
  mutable std::mutex mu;
  using Entry = std::pair<SlabKey, std::shared_ptr<std::vector<uint8_t>>>;
  std::list<Entry> lru;  // MRU at front
  std::unordered_map<SlabKey, std::list<Entry>::iterator, SlabKeyHash> map;
  uint64_t max_bytes = 0;  // 0 = cache disabled
  uint64_t bytes = 0;
  uint64_t puts = 0, put_bytes = 0, hits = 0, misses = 0, evictions = 0,
           invalidated = 0;

  // callers hold mu
  void evict_to_budget() {
    while (bytes > max_bytes && !lru.empty()) {
      Entry& tail = lru.back();
      bytes -= tail.second->size();
      map.erase(tail.first);
      lru.pop_back();
      evictions++;
    }
  }
};

// ------------------------------------------------------------- telemetry
// Request telemetry for the hot path: plain relaxed atomics on the fast
// path (one cache line of fetch_adds per request, no locks), a
// fixed-bucket latency histogram in µs, and a bounded slow-request ring
// whose mutex is taken only when a request crosses the slow threshold.
// The µs bucket bounds must cover both the in-memory hit (~tens of µs)
// and a degraded/redirected tail (seconds); the Python side reads them
// via swhp_lat_bounds so the two never drift.
constexpr uint64_t kLatBoundsUs[] = {50,     100,    250,    500,
                                     1000,   2500,   5000,   10000,
                                     25000,  50000,  100000, 250000,
                                     1000000, 5000000};
constexpr int kLatBuckets =
    static_cast<int>(sizeof(kLatBoundsUs) / sizeof(kLatBoundsUs[0]));
constexpr int kSlowRing = 64;

struct SlowEntry {
  char method[8] = {0};
  char target[96] = {0};
  int status = 0;
  uint64_t bytes = 0;
  uint64_t micros = 0;
  uint64_t unix_ms = 0;
};

struct PlaneStats {
  std::atomic<bool> enabled{true};
  std::atomic<uint64_t> slow_us{10000};
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> by_class[6] = {};  // [1..5] = 1xx..5xx
  std::atomic<uint64_t> bytes_sent{0};
  std::atomic<uint64_t> index_misses{0};
  std::atomic<uint64_t> lat_count{0};
  std::atomic<uint64_t> lat_sum_us{0};
  std::atomic<uint64_t> lat_buckets[kLatBuckets + 1] = {};  // +1: overflow
  std::mutex slow_mu;
  SlowEntry slow[kSlowRing];
  uint64_t slow_seq = 0;  // total slow entries ever; guarded by slow_mu
};

// Handlers funnel their response through respond_simple (or write the
// 200/206 head themselves); these thread-locals carry status+payload
// size back to handle_conn's per-request record without threading an
// out-param through every serve_* signature. Thread-per-connection
// makes them race-free.
thread_local int tl_status = 0;
thread_local uint64_t tl_bytes = 0;
// group-commit deferral: serve_write sets tl_deferred when it handed
// its ack to the committer (handle_conn must not record telemetry —
// the committer records the full request latency at send time); tl_t0
// carries the request clock start into the deferred entry (0 when the
// stats were off at request start)
thread_local bool tl_deferred = false;
thread_local uint64_t tl_t0 = 0;

uint64_t mono_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000ull +
         static_cast<uint64_t>(ts.tv_nsec) / 1000;
}

uint64_t wall_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000ull +
         static_cast<uint64_t>(ts.tv_nsec) / 1000000;
}

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  std::string fallback;  // host:port of the Python server
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> served{0}, redirected{0}, errors{0};
  std::atomic<uint64_t> written{0};  // fast-path POSTs appended here
  std::atomic<int> live{0};
  int max_conns = 1024;
  int64_t max_fastpath_bytes = 64ll << 20;
  std::thread acceptor;
  std::unordered_map<uint32_t, std::shared_ptr<VolumeRec>> vols;
  mutable std::shared_mutex vols_mu;
  PlaneStats stats;
  std::unordered_map<uint32_t, std::shared_ptr<EcVolumeRec>> ec_vols;
  mutable std::shared_mutex ec_mu;
  SlabCache cache;
  // EC serving outcomes, bumped BEFORE the response bytes leave (same
  // rule as `served`): degraded = at least one lost-shard byte came from
  // the slab cache; local = all shards were local files.
  std::atomic<uint64_t> ec_degraded_served{0};
  std::atomic<uint64_t> ec_degraded_redirected{0};
  std::atomic<uint64_t> ec_local_served{0};

  // group-commit durability config (swhp_set_sync_mode; applied to
  // writers at enable time so a live lease's mode never mutates under
  // in-flight appends) + server-global telemetry across all writers.
  // The fsync µs histogram reuses kLatBoundsUs and is populated only
  // while stats are enabled (SW_PLANE_STATS=0 keeps the committer
  // clock-free too).
  std::atomic<int> sync_mode{0};
  std::atomic<uint64_t> sync_batch_us{2000};
  std::atomic<uint64_t> sync_max_pending{512};
  std::atomic<uint64_t> fsync_batches{0};
  std::atomic<uint64_t> fsync_riders{0};
  std::atomic<uint64_t> fsync_failures{0};
  std::atomic<uint64_t> fsync_pending{0};
  std::atomic<uint64_t> fsync_us_sum{0};
  std::atomic<uint64_t> fsync_buckets[kLatBuckets + 1] = {};

  std::shared_ptr<VolumeRec> find(uint32_t vid) const {
    std::shared_lock<std::shared_mutex> l(vols_mu);
    auto it = vols.find(vid);
    return it == vols.end() ? nullptr : it->second;
  }
  std::shared_ptr<EcVolumeRec> find_ec(uint32_t vid) const {
    std::shared_lock<std::shared_mutex> l(ec_mu);
    auto it = ec_vols.find(vid);
    return it == ec_vols.end() ? nullptr : it->second;
  }
};

// ----------------------------------------------------- group commit
// One committed batch's telemetry. The µs histogram (kLatBoundsUs) and
// sum are skipped when the batch wasn't timed — SW_PLANE_STATS=0 keeps
// even the committer clock-free; batch/rider counts are plain
// fetch_adds and always flow.
void record_fsync(Server* s, uint64_t riders, uint64_t us, bool timed) {
  s->fsync_batches.fetch_add(1, std::memory_order_relaxed);
  s->fsync_riders.fetch_add(riders, std::memory_order_relaxed);
  if (!timed) return;
  s->fsync_us_sum.fetch_add(us, std::memory_order_relaxed);
  int b = 0;
  while (b < kLatBuckets && us > kLatBoundsUs[b]) b++;
  s->fsync_buckets[b].fetch_add(1, std::memory_order_relaxed);
}

// Flush deferred group-commit acks outside any writer lock: a clean
// commit sends each rider its pre-built 200; a poison/teardown sends
// the 307 fallback (durability unknown — the record was never acked,
// so the client's retry through Python is a harmless duplicate).
// Defined after record_request; used by the committer and poison.
void send_deferred(Server* s, std::vector<DeferredAck> acks, bool ok);

// Fail-stop a writer after an fdatasync error: acking a write whose
// durability is unknown is the one unforgivable ambiguity, so the whole
// batch poisons (-5 to every waiter) and the writer dies like the
// torn-.idx path in do_append — Python demotes to its own append path
// and the next lease cycle resumes from the consistent prefix. Caller
// must hold NEITHER w->mu nor w->sync_mu.
void poison_writer(Writer* w) {
  std::vector<DeferredAck> orphans;
  {
    std::lock_guard<std::mutex> sg(w->sync_mu);
    w->sync_failed = true;
    w->ack_cv[0].notify_all();
    w->ack_cv[1].notify_all();
    while (!w->deferred.empty()) {
      orphans.push_back(std::move(w->deferred.front()));
      w->deferred.pop_front();
    }
  }
  if (!orphans.empty())
    send_deferred(w->srv, std::move(orphans), false);
  w->accept_posts.store(false, std::memory_order_release);
  std::lock_guard<std::mutex> g(w->mu);
  if (w->fd >= 0) close(w->fd);
  if (w->idx_fd >= 0) close(w->idx_fd);
  w->fd = w->idx_fd = -1;
}

// The group-commit committer: waits for the first rider, lets the
// commit window (batch_us) fill — or max_pending riders force an early
// close — then issues ONE fdatasync pair (.dat then .idx) covering
// every sequence appended before the sync started, advances synced_seq
// and wakes the batch. The fds are private dups, so a concurrent
// fail-stop closing the writer's fds can't invalidate an in-flight
// fdatasync; appends racing in DURING the sync simply ride the next
// batch (fdatasync may flush their bytes early — never the reverse).
void committer_loop(Server* s, Writer* w) {
  // every in-flight durable write waits on this thread: under load the
  // committer competes with hundreds of runnable conn threads for the
  // CPU, and each scheduling delay stretches the commit cycle for the
  // whole batch — ask for priority (best-effort: may need privileges)
  setpriority(PRIO_PROCESS,
              static_cast<id_t>(syscall(SYS_gettid)), -10);
  std::unique_lock<std::mutex> sl(w->sync_mu);
  for (;;) {
    w->sync_cv.wait(sl, [&] {
      return w->committer_stop ||
             (w->append_seq > w->synced_seq && !w->sync_failed);
    });
    if (w->committer_stop &&
        (w->append_seq == w->synced_seq || w->sync_failed))
      break;
    uint64_t first = w->synced_seq;
    if (!w->committer_stop && w->batch_us > 0)
      w->sync_cv.wait_for(
          sl, std::chrono::microseconds(w->batch_us), [&] {
            return w->committer_stop ||
                   w->append_seq - first >= w->max_pending;
          });
    uint64_t upto = w->append_seq;
    // close the open batch: riders that enqueued while sync_gen == gen
    // are exactly the sequences <= upto (both read under sync_mu)
    uint64_t gen = w->sync_gen++;
    sl.unlock();
    bool timed = s->stats.enabled.load(std::memory_order_relaxed);
    uint64_t t0 = timed ? mono_us() : 0;
    // sync .dat and .idx concurrently: issued back-to-back each forces
    // its own journal commit; in flight together the jbd2 layer merges
    // them into one transaction, roughly halving the commit window
    bool idx_ok = false;
    std::thread idx_sync(
        [&] { idx_ok = fdatasync(w->sync_idx_fd) == 0; });
    bool dat_ok = fdatasync(w->sync_dat_fd) == 0;
    idx_sync.join();
    bool ok = dat_ok && idx_ok;
    uint64_t us = timed ? mono_us() - t0 : 0;
    if (ok) {
      record_fsync(s, upto - first, us, timed);
      sl.lock();
      w->synced_seq = upto;
      w->ack_cv[gen & 1].notify_all();
      if (!w->deferred.empty() && w->deferred.front().seq <= upto) {
        std::vector<DeferredAck> acks;
        while (!w->deferred.empty() && w->deferred.front().seq <= upto) {
          acks.push_back(std::move(w->deferred.front()));
          w->deferred.pop_front();
        }
        sl.unlock();  // sends must not block riders enqueueing
        send_deferred(s, std::move(acks), true);
        sl.lock();
      }
    } else {
      s->fsync_failures.fetch_add(1, std::memory_order_relaxed);
      poison_writer(w);
      sl.lock();
    }
  }
  // belt-and-braces: a rider enqueued after sync_failed is rejected
  // with -5 before it defers, and poison flushed the queue — but a
  // deferred ack must never be silently dropped, so fall back loudly
  std::vector<DeferredAck> leftover;
  while (!w->deferred.empty()) {
    leftover.push_back(std::move(w->deferred.front()));
    w->deferred.pop_front();
  }
  sl.unlock();
  if (!leftover.empty())
    send_deferred(s, std::move(leftover), false);
  close(w->sync_dat_fd);
  close(w->sync_idx_fd);
  w->sync_dat_fd = w->sync_idx_fd = -1;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// header+body in one syscall (syscalls dominate small-needle serving)
bool send_two(int fd, const void* a, size_t an, const void* b, size_t bn) {
  struct iovec iov[2] = {{const_cast<void*>(a), an},
                         {const_cast<void*>(b), bn}};
  size_t idx = 0;
  while (idx < 2) {
    ssize_t w = writev(fd, iov + idx, static_cast<int>(2 - idx));
    if (w <= 0) return false;
    size_t done = static_cast<size_t>(w);
    while (idx < 2 && done >= iov[idx].iov_len) {
      done -= iov[idx].iov_len;
      idx++;
    }
    if (idx < 2 && done > 0) {
      iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + done;
      iov[idx].iov_len -= done;
    }
  }
  return true;
}

struct Request {
  std::string method, target;
  bool keepalive = true;
  bool http10 = false;
  std::string if_none_match, range, if_modified_since;
  std::string content_type;
  int64_t content_length = 0;
  bool chunked = false;
  bool has_pair_headers = false;  // any Seaweed-* header present
};

void record_request(Server* s, const Request& req, int status,
                    uint64_t bytes, uint64_t us) {
  PlaneStats& st = s->stats;
  st.requests.fetch_add(1, std::memory_order_relaxed);
  int cls = status / 100;
  if (cls >= 1 && cls <= 5)
    st.by_class[cls].fetch_add(1, std::memory_order_relaxed);
  st.bytes_sent.fetch_add(bytes, std::memory_order_relaxed);
  st.lat_count.fetch_add(1, std::memory_order_relaxed);
  st.lat_sum_us.fetch_add(us, std::memory_order_relaxed);
  int b = 0;
  while (b < kLatBuckets && us > kLatBoundsUs[b]) b++;
  st.lat_buckets[b].fetch_add(1, std::memory_order_relaxed);
  if (us >= st.slow_us.load(std::memory_order_relaxed)) {
    std::lock_guard<std::mutex> g(st.slow_mu);
    SlowEntry& e = st.slow[st.slow_seq % kSlowRing];
    snprintf(e.method, sizeof e.method, "%s", req.method.c_str());
    snprintf(e.target, sizeof e.target, "%s", req.target.c_str());
    e.status = status;
    e.bytes = bytes;
    e.micros = us;
    e.unix_ms = wall_ms();
    st.slow_seq++;
  }
}

void send_deferred(Server* s, std::vector<DeferredAck> acks, bool ok) {
  for (auto& a : acks) {
    const std::string& out = ok ? a.resp : a.fallback;
    send_all(a.fd, out.data(), out.size());
    close(a.fd);
    a.fd = -1;
    if (s) {
      if (!ok) s->redirected++;
      if (a.t0_us) {  // stats were on when the request started
        Request rq;
        rq.method = "POST";
        rq.target = a.target;
        record_request(s, rq, ok ? 200 : 307, ok ? a.bytes : 0,
                       mono_us() - a.t0_us);
      }
    }
  }
  if (s && !acks.empty())
    s->fsync_pending.fetch_sub(acks.size(), std::memory_order_relaxed);
}

// Reads one request off the socket (blocking). Returns 1 ok, 0 clean EOF,
// -1 error/overflow.
int read_request(int fd, std::string* acc, Request* out) {
  // acc may already hold pipelined bytes from the previous read
  size_t scanned = 0;
  for (;;) {
    size_t pos = acc->find("\r\n\r\n", scanned > 3 ? scanned - 3 : 0);
    if (pos != std::string::npos) {
      std::string head = acc->substr(0, pos);
      acc->erase(0, pos + 4);
      // request line
      size_t sp1 = head.find(' ');
      size_t sp2 = head.find(' ', sp1 + 1);
      size_t eol = head.find("\r\n");
      if (sp1 == std::string::npos || sp2 == std::string::npos ||
          sp2 > (eol == std::string::npos ? head.size() : eol))
        return -1;
      out->method = head.substr(0, sp1);
      out->target = head.substr(sp1 + 1, sp2 - sp1 - 1);
      out->http10 = head.compare(sp2 + 1, 8, "HTTP/1.0") == 0;
      out->keepalive = !out->http10;
      // headers we care about
      size_t ls = (eol == std::string::npos) ? head.size() : eol + 2;
      while (ls < head.size()) {
        size_t le = head.find("\r\n", ls);
        if (le == std::string::npos) le = head.size();
        size_t colon = head.find(':', ls);
        if (colon != std::string::npos && colon < le) {
          std::string k = head.substr(ls, colon - ls);
          size_t vs = colon + 1;
          while (vs < le && head[vs] == ' ') vs++;
          std::string v = head.substr(vs, le - vs);
          for (auto& c : k)
            c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
          if (k == "connection") {
            std::string lv = v;
            for (auto& c : lv)
              c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
            if (lv.find("close") != std::string::npos) out->keepalive = false;
            if (out->http10 && lv.find("keep-alive") != std::string::npos)
              out->keepalive = true;
          } else if (k == "if-none-match") {
            out->if_none_match = v;
          } else if (k == "if-modified-since") {
            out->if_modified_since = v;
          } else if (k == "range") {
            out->range = v;
          } else if (k == "content-type") {
            out->content_type = v;
          } else if (k == "content-length") {
            // trim trailing whitespace, then demand a clean all-DIGIT
            // parse (RFC 9110): strtoll alone would accept "+10" or
            // "\t10", whose framing an intermediary may read
            // differently — treat those as unreadable and sever
            while (!v.empty() && (v.back() == ' ' || v.back() == '\t'))
              v.pop_back();
            char* end = nullptr;
            out->content_length =
                (!v.empty() && isdigit(static_cast<unsigned char>(v[0])))
                    ? strtoll(v.c_str(), &end, 10)
                    : -1;
            if (v.empty() || out->content_length < 0 ||
                (end && *end != '\0')) {
              out->content_length = 0;
              out->keepalive = false;
            }
          } else if (k == "transfer-encoding") {
            out->chunked = true;  // no body framing here: close after
          } else if (k.compare(0, 8, "seaweed-") == 0) {
            out->has_pair_headers = true;
          }
        }
        ls = le + 2;
      }
      return 1;
    }
    if (acc->size() > 16384) return -1;  // header cap
    scanned = acc->size();
    char buf[4096];
    ssize_t r = recv(fd, buf, sizeof buf, 0);
    if (r == 0) return acc->empty() ? 0 : -1;
    if (r < 0) return -1;
    acc->append(buf, static_cast<size_t>(r));
  }
}

std::string format_head(int code, const char* reason, size_t body_len,
                        bool keepalive,
                        const std::string& extra_headers,
                        const char* ctype) {
  return "HTTP/1.1 " + std::to_string(code) + " " + reason +
         "\r\nContent-Length: " + std::to_string(body_len) +
         "\r\nContent-Type: " + ctype + "\r\n" + extra_headers +
         "Connection: " + (keepalive ? "keep-alive" : "close") +
         "\r\n\r\n";
}

// full response bytes in one buffer, for acks sent later by a thread
// that isn't the connection's own (group-commit deferred acks)
std::string format_response(int code, const char* reason,
                            const std::string& body, bool keepalive,
                            const std::string& extra_headers = "",
                            const char* ctype = "text/plain") {
  std::string out =
      format_head(code, reason, body.size(), keepalive, extra_headers,
                  ctype);
  out += body;
  return out;
}

void respond_simple(int fd, int code, const char* reason,
                    const std::string& body, bool keepalive,
                    const std::string& extra_headers = "",
                    const char* ctype = "text/plain") {
  tl_status = code;
  tl_bytes += body.size();
  std::string head = format_head(code, reason, body.size(), keepalive,
                                 extra_headers, ctype);
  if (body.empty())
    send_all(fd, head.data(), head.size());
  else
    send_two(fd, head.data(), head.size(), body.data(), body.size());
}

void redirect_to_fallback(Server* s, int fd, const Request& req) {
  s->redirected++;
  std::string loc = "http://" + s->fallback + req.target;
  std::string hdr = "Location: " + loc + "\r\n";
  // 307 preserves method+body; our fallback is the authoritative server
  respond_simple(fd, 307, "Temporary Redirect", "", req.keepalive, hdr);
}

// `%xx` unescape for the path (fids are plain hex, but be tolerant)
std::string unescape(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); i++) {
    if (in[i] == '%' && i + 2 < in.size() &&
        isxdigit(static_cast<unsigned char>(in[i + 1])) &&
        isxdigit(static_cast<unsigned char>(in[i + 2]))) {
      out.push_back(static_cast<char>(
          strtol(in.substr(i + 1, 2).c_str(), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(in[i]);
    }
  }
  return out;
}

bool all_digits(const std::string& s) {
  if (s.empty()) return false;
  for (char c : s)
    if (!isdigit(static_cast<unsigned char>(c))) return false;
  return true;
}

// Parse "/<vid>,<keyhex><cookie8>[_<n>]" (also '/' separator). The _n
// suffix is the batch-assign convention (reference common.go parses
// "fid_i" as key+i for ?count= assigns; storage/types.py mirrors it).
// Returns false if the target is not a plain fid path (query string,
// extension, etc).
bool parse_fid_path(const std::string& target, uint32_t* vid, uint64_t* key,
                    uint32_t* cookie) {
  if (target.empty() || target[0] != '/') return false;
  if (target.find('?') != std::string::npos) return false;
  std::string p = unescape(target.substr(1));
  size_t sep = p.find(',');
  if (sep == std::string::npos) sep = p.find('/');
  if (sep == std::string::npos || sep == 0) return false;
  uint64_t v = 0;
  for (size_t i = 0; i < sep; i++) {
    if (!isdigit(static_cast<unsigned char>(p[i]))) return false;
    v = v * 10 + static_cast<uint64_t>(p[i] - '0');
    if (v > 0xFFFFFFFFull) return false;
  }
  std::string kh = p.substr(sep + 1);
  uint64_t delta = 0;
  size_t us = kh.find('_');
  if (us != std::string::npos) {
    std::string d = kh.substr(us + 1);
    if (!all_digits(d) || d.size() > 18) return false;
    delta = strtoull(d.c_str(), nullptr, 10);
    kh = kh.substr(0, us);
  }
  // mirror storage/types.py parse_key_hash: 8 < len <= 24, last 8 hex
  // chars are the cookie
  if (kh.size() <= 8 || kh.size() > 24) return false;
  for (char c : kh)
    if (!isxdigit(static_cast<unsigned char>(c))) return false;
  if (kh.size() % 2) kh = "0" + kh;
  uint64_t k = 0;
  for (size_t i = 0; i + 8 < kh.size(); i++)
    k = k << 4 | static_cast<uint64_t>(strtol(kh.substr(i, 1).c_str(),
                                              nullptr, 16));
  uint32_t ck = static_cast<uint32_t>(
      strtoul(kh.substr(kh.size() - 8).c_str(), nullptr, 16));
  *vid = static_cast<uint32_t>(v);
  *key = k + delta;
  *cookie = ck;
  return true;
}

// Single-range parse: "bytes=a-b" / "bytes=a-" / "bytes=-n" (mirrors
// server/http_util.parse_range; multi-range -> not handled -> full body)
bool parse_range_header(const std::string& r, int64_t total, int64_t* start,
                        int64_t* length) {
  if (r.compare(0, 6, "bytes=") != 0) return false;
  std::string spec = r.substr(6);
  if (spec.find(',') != std::string::npos) return false;
  size_t dash = spec.find('-');
  if (dash == std::string::npos) return false;
  std::string a = spec.substr(0, dash), b = spec.substr(dash + 1);
  if (a.empty() && b.empty()) return false;
  if ((!a.empty() && !all_digits(a)) || (!b.empty() && !all_digits(b)))
    return false;  // malformed bounds -> not parseable (Python: 416)
  if (a.empty()) {  // suffix: last n bytes
    int64_t n = strtoll(b.c_str(), nullptr, 10);
    if (n <= 0) return false;
    if (n > total) n = total;
    *start = total - n;
    *length = n;
    return true;
  }
  int64_t s = strtoll(a.c_str(), nullptr, 10);
  if (s >= total) return false;
  int64_t e = b.empty() ? total - 1 : strtoll(b.c_str(), nullptr, 10);
  if (e >= total) e = total - 1;
  if (e < s) return false;
  *start = s;
  *length = e - s + 1;
  return true;
}

void quote_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    if (c == '\\' || c == '"') out->push_back('\\');
    out->push_back(c);
  }
}

// Shared response tail for the plain and EC fast paths: parse + validate
// the raw needle record and emit the HTTP response. Returns false when
// the request must be redirected to Python instead (semantics beyond the
// fast path; in `lenient` mode also any corruption/crc failure — the EC
// path assembles bytes from cached reconstructions, so Python, not a
// 500, stays authoritative when they don't check out). `also`, when
// non-null, is bumped alongside `served` before every send so EC
// outcome counters keep the same observer guarantee.
bool respond_needle_blob(Server* s, int fd, const Request& req,
                         uint32_t cookie, const uint8_t* blob, size_t blen,
                         int version, uint32_t size, bool lenient,
                         std::atomic<uint64_t>* also) {
  ParsedNeedle n;
  if (parse_needle(blob, blen, version, &n) != 0 || n.size != size) {
    if (lenient) return false;
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "corrupt needle",
                   req.keepalive);
    return true;
  }
  if (n.cookie != cookie) {
    respond_simple(fd, 404, "Not Found", "cookie mismatch", req.keepalive);
    return true;
  }
  if (size > 0 && masked_crc(crc32c(n.data, n.data_size)) != n.checksum) {
    if (lenient) return false;
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "crc mismatch",
                   req.keepalive);
    return true;
  }
  // TTL expiry (volume.read_needle)
  if ((n.flags & kFlagHasTtl) && (n.flags & kFlagHasLastModified)) {
    int64_t mins = ttl_minutes(n.ttl_count, n.ttl_unit);
    if (mins > 0 &&
        time(nullptr) - n.last_modified > mins * 60) {
      respond_simple(fd, 404, "Not Found", "needle expired", req.keepalive);
      return true;
    }
  }
  // semantics beyond the fast path live in Python
  if (n.flags & (kFlagGzip | kFlagChunkManifest | kFlagHasPairs))
    return false;
  char etag[16];
  snprintf(etag, sizeof etag, "%02x%02x%02x%02x", n.checksum >> 24 & 0xFF,
           n.checksum >> 16 & 0xFF, n.checksum >> 8 & 0xFF,
           n.checksum & 0xFF);
  // Last-Modified + If-Modified-Since, checked before the etag
  // (reference volume_server_handlers_read.go:99-109)
  std::string lm_header;
  if ((n.flags & kFlagHasLastModified) && n.last_modified > 0) {
    char buf[64];
    time_t t = static_cast<time_t>(n.last_modified);
    struct tm tmv;
    gmtime_r(&t, &tmv);
    strftime(buf, sizeof buf, "%a, %d %b %Y %H:%M:%S GMT", &tmv);
    lm_header = buf;
    if (!req.if_modified_since.empty()) {
      struct tm ims{};
      if (strptime(req.if_modified_since.c_str(),
                   "%a, %d %b %Y %H:%M:%S GMT", &ims) != nullptr) {
        if (timegm(&ims) >= n.last_modified) {
          std::string hdr = "Last-Modified: " + lm_header +
                            "\r\nEtag: \"" + etag + "\"\r\n";
          // counters bump BEFORE the response bytes leave: an observer
          // that has received the response must see the count (a
          // post-send bump races clients on a loaded single-core host)
          s->served++;
          if (also) (*also)++;
          respond_simple(fd, 304, "Not Modified", "", req.keepalive, hdr,
                         "application/octet-stream");
          return true;
        }
      }
    }
  }
  // conditional GET (RFC7232 comma list, weak validators, "*")
  if (!req.if_none_match.empty()) {
    std::string quoted = std::string("\"") + etag + "\"";
    std::string inm = req.if_none_match;
    bool match = false;
    size_t pos = 0;
    while (pos <= inm.size()) {
      size_t comma = inm.find(',', pos);
      std::string c = inm.substr(
          pos, comma == std::string::npos ? std::string::npos : comma - pos);
      // trim + strip weak prefix
      size_t b = c.find_first_not_of(" \t");
      size_t e = c.find_last_not_of(" \t");
      if (b != std::string::npos) {
        c = c.substr(b, e - b + 1);
        if (c.compare(0, 2, "W/") == 0) c = c.substr(2);
        if (c == "*" || c == quoted) {
          match = true;
          break;
        }
      }
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
    if (match) {
      // header set mirrors the Python 304 (Etag + default octet-stream)
      std::string hdr = "Etag: " + quoted + "\r\n";
      s->served++;  // before the send — see the IMS 304 comment
      if (also) (*also)++;
      respond_simple(fd, 304, "Not Modified", "", req.keepalive, hdr,
                     "application/octet-stream");
      return true;
    }
  }
  const char* ctype = "application/octet-stream";
  std::string mime_hold;
  if ((n.flags & kFlagHasMime) && !n.mime.empty()) {
    mime_hold = n.mime;
    ctype = mime_hold.c_str();
  }
  // image resize queries never reach here (any '?' redirects), so a
  // plain GET of an image serves stored bytes — same as Python with no
  // width/height args.
  const uint8_t* body = n.data;
  int64_t total = n.data_size;
  int64_t start = 0, length = total;
  bool ranged = false;
  if (!req.range.empty()) {
    if (parse_range_header(req.range, total, &start, &length)) {
      ranged = true;
    } else if (req.range.compare(0, 6, "bytes=") == 0) {
      // unsatisfiable/multi range: Python answers 416 for bad single
      // ranges; multi-ranges fall through to full body there. Redirect
      // so every edge keeps one source of truth.
      return false;
    }
  }
  std::string head;
  head.reserve(512);
  head += ranged ? "HTTP/1.1 206 Partial Content\r\n" : "HTTP/1.1 200 OK\r\n";
  head += "Content-Length: " + std::to_string(length) + "\r\n";
  head += "Content-Type: ";
  head += ctype;
  head += "\r\nEtag: \"";
  head += etag;
  head += "\"\r\nAccept-Ranges: bytes\r\n";
  if (!lm_header.empty())
    head += "Last-Modified: " + lm_header + "\r\n";
  if (n.flags & kFlagHasName) {
    std::string esc;
    quote_escape(n.name, &esc);
    head += "Content-Disposition: inline; filename=\"" + esc + "\"\r\n";
  }
  if (ranged)
    head += "Content-Range: bytes " + std::to_string(start) + "-" +
            std::to_string(start + length - 1) + "/" +
            std::to_string(total) + "\r\n";
  head += req.keepalive ? "Connection: keep-alive\r\n\r\n"
                        : "Connection: close\r\n\r\n";
  s->served++;  // before the send — see the IMS 304 comment
  if (also) (*also)++;
  tl_status = ranged ? 206 : 200;
  if (req.method == "HEAD") {
    send_all(fd, head.data(), head.size());
  } else {
    tl_bytes += static_cast<uint64_t>(length);
    send_two(fd, head.data(), head.size(), body + start,
             static_cast<size_t>(length));
  }
  return true;
}

// Copies [shard_off, shard_off+take) of a LOST shard's byte stream out of
// the slab cache into dst. Every covering slab must be resident; a slab
// shorter than the logical slab size (shard tail) leaves dst's zero-fill
// in place, mirroring the Python engine's zero-padding. Hit/miss counts
// are per-slab-lookup and exact (under the cache mutex).
bool copy_from_cache(Server* s, uint32_t vid, int sid, int64_t slab,
                     int64_t shard_off, int64_t take, uint8_t* dst) {
  if (slab <= 0) return false;
  uint64_t vs = static_cast<uint64_t>(vid) << 32 |
                static_cast<uint32_t>(sid);
  int64_t lo = shard_off, hi = shard_off + take;
  for (int64_t idx = lo / slab; idx * slab < hi; idx++) {
    std::shared_ptr<std::vector<uint8_t>> data;
    {
      std::lock_guard<std::mutex> g(s->cache.mu);
      auto it = s->cache.map.find(
          SlabKey{vs, static_cast<uint64_t>(idx)});
      if (it == s->cache.map.end()) {
        s->cache.misses++;
        return false;
      }
      s->cache.hits++;
      s->cache.lru.splice(s->cache.lru.begin(), s->cache.lru, it->second);
      data = it->second->second;
    }
    int64_t s_lo = std::max(lo, idx * slab);
    int64_t s_hi = std::min(hi, (idx + 1) * slab);
    int64_t in_lo = s_lo - idx * slab;
    int64_t in_hi = s_hi - idx * slab;
    int64_t avail = std::min<int64_t>(
        in_hi, static_cast<int64_t>(data->size()));
    if (avail > in_lo)
      memcpy(dst + (s_lo - lo), data->data() + in_lo,
             static_cast<size_t>(avail - in_lo));
  }
  return true;
}

// In-plane EC needle GET. Walks the needle's logical .dat range through
// the striping math (exact mirror of ec/locate.py: encoder-derived large
// row count, row-major block walk, large->small rollover), reading local
// shards via pread and lost shards from the slab cache. Any gap — index
// miss, unregistered shard with no resident slabs, oversize, validation
// failure — redirects to Python exactly as before this path existed.
// Adds NO clock reads: timing stays in handle_conn behind the stats
// gate.
void serve_ec_needle(Server* s, int fd, const Request& req,
                     const std::shared_ptr<EcVolumeRec>& ev, uint32_t vid,
                     uint64_t key, uint32_t cookie) {
  uint64_t offset;
  uint32_t size;
  {
    std::shared_lock<std::shared_mutex> l(ev->mu);
    auto it = ev->index.find(key);
    if (it == ev->index.end() || it->second.second == kTombstoneSize) {
      // mirror semantics match the plain path: Python's .ecx is
      // authoritative for misses/tombstones (404 vs re-sync window)
      l.unlock();
      s->stats.index_misses.fetch_add(1, std::memory_order_relaxed);
      redirect_to_fallback(s, fd, req);
      return;
    }
    offset = it->second.first;
    size = it->second.second;
  }
  int64_t want = actual_size(size, ev->version);
  if (want > s->max_fastpath_bytes ||
      static_cast<int64_t>(offset) + want > ev->dat_size) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  std::vector<uint8_t> blob(static_cast<size_t>(want), 0);
  int64_t large_row = ev->large_block * kDataShards;
  int64_t n_large = ec_n_large_rows(ev->dat_size, ev->large_block);
  int64_t block_index, inner;
  bool is_large;
  if (static_cast<int64_t>(offset) < n_large * large_row) {
    block_index = static_cast<int64_t>(offset) / ev->large_block;
    is_large = true;
    inner = static_cast<int64_t>(offset) % ev->large_block;
  } else {
    int64_t off2 = static_cast<int64_t>(offset) - n_large * large_row;
    block_index = off2 / ev->small_block;
    is_large = false;
    inner = off2 % ev->small_block;
  }
  bool used_cache = false;
  bool cache_gap = false;  // lost shard whose slabs weren't resident
  bool ok = true;
  int64_t pos = 0, remaining = want;
  {
    // shared lock across the assembly: swhp_ec_set_shard swaps fds under
    // the unique lock, so no pread can race a close
    std::shared_lock<std::shared_mutex> l(ev->mu);
    while (remaining > 0) {
      int64_t blk = is_large ? ev->large_block : ev->small_block;
      int64_t take = std::min(remaining, blk - inner);
      int sid = static_cast<int>(block_index % kDataShards);
      int64_t row = block_index / kDataShards;
      int64_t shard_off =
          inner + (is_large ? row * ev->large_block
                            : n_large * ev->large_block +
                                  row * ev->small_block);
      int sfd = ev->shard_fds[sid];
      if (sfd >= 0) {
        // a short read past the shard tail leaves the zero-fill, same
        // as the engine's zero-padded slab pieces
        if (pread(sfd, blob.data() + pos, static_cast<size_t>(take),
                  static_cast<off_t>(shard_off)) < 0) {
          ok = false;
          break;
        }
      } else {
        if (!copy_from_cache(s, vid, sid, ev->slab_bytes, shard_off, take,
                             blob.data() + pos)) {
          ok = false;
          cache_gap = true;
          break;
        }
        used_cache = true;
      }
      pos += take;
      remaining -= take;
      if (remaining <= 0) break;
      block_index++;
      if (is_large && block_index == n_large * kDataShards) {
        is_large = false;
        block_index = 0;
      }
      inner = 0;
    }
  }
  if (!ok) {
    if (cache_gap)
      s->ec_degraded_redirected.fetch_add(1, std::memory_order_relaxed);
    redirect_to_fallback(s, fd, req);
    return;
  }
  std::atomic<uint64_t>* outcome =
      used_cache ? &s->ec_degraded_served : &s->ec_local_served;
  if (!respond_needle_blob(s, fd, req, cookie, blob.data(), blob.size(),
                           ev->version, size, /*lenient=*/true, outcome)) {
    if (used_cache)
      s->ec_degraded_redirected.fetch_add(1, std::memory_order_relaxed);
    redirect_to_fallback(s, fd, req);
  }
}

void serve_needle(Server* s, int fd, const Request& req, uint32_t vid,
                  uint64_t key, uint32_t cookie) {
  auto vol = s->find(vid);
  if (!vol) {
    auto ev = s->find_ec(vid);
    if (ev) {
      serve_ec_needle(s, fd, req, ev, vid, key, cookie);
      return;
    }
    redirect_to_fallback(s, fd, req);  // remote / replica logic
    return;
  }
  uint64_t offset;
  uint32_t size;
  {
    std::shared_lock<std::shared_mutex> l(vol->mu);
    auto it = vol->index.find(key);
    if (it == vol->index.end() || it->second.first == 0 ||
        it->second.second == kTombstoneSize) {
      // The index here is only a MIRROR: during a re-sync window
      // (compaction commit, volume copy, tail receive) or after a
      // put/delete reorder it can transiently miss live needles. A
      // miss therefore redirects to the authoritative Python server —
      // a true miss still ends as its 404, a windowed miss is served.
      l.unlock();
      s->stats.index_misses.fetch_add(1, std::memory_order_relaxed);
      redirect_to_fallback(s, fd, req);
      return;
    }
    offset = it->second.first;
    size = it->second.second;
  }
  int64_t want = actual_size(size, vol->version);
  if (want > s->max_fastpath_bytes) {  // huge blob: let Python stream it
    redirect_to_fallback(s, fd, req);
    return;
  }
  std::vector<uint8_t> blob(static_cast<size_t>(want));
  ssize_t got = pread(vol->fd, blob.data(), blob.size(),
                      static_cast<off_t>(offset));
  if (got < want) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "short read",
                   req.keepalive);
    return;
  }
  if (!respond_needle_blob(s, fd, req, cookie, blob.data(), blob.size(),
                           vol->version, size, /*lenient=*/false,
                           nullptr))
    redirect_to_fallback(s, fd, req);
}

// ----------------------------------------------------------------- write
bool pwrite_all(int fd, const uint8_t* buf, size_t n, int64_t off) {
  while (n > 0) {
    ssize_t w = pwrite(fd, buf, n, static_cast<off_t>(off));
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    buf += w;
    off += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool write_all_fd(int fd, const uint8_t* buf, size_t n) {
  while (n > 0) {
    ssize_t w = write(fd, buf, n);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    buf += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

void be32_store(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v >> 24);
  p[1] = static_cast<uint8_t>(v >> 16);
  p[2] = static_cast<uint8_t>(v >> 8);
  p[3] = static_cast<uint8_t>(v);
}

void be64_store(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++)
    p[i] = static_cast<uint8_t>(v >> (8 * (7 - i)));
}

// The append core: .dat record + .idx entry + mirror + counter deltas.
// Caller holds w->mu (do_append below — the only caller — takes it).
// size_field==kTombstoneSize marks a delete (blob is the tombstone
// record; the .idx entry gets offset 0 + tombstone size, mirroring
// NeedleMap.delete).
// check_cookie: re-verify the overwrite/delete cookie against the
// STORED needle under the mutex — the caller's pre-check raced with
// other appends (Python's write_needle holds volume.lock across
// check+append; the mutex is this plane's equivalent).
// Returns the append offset, or -1 writer gone, -2 addressing ceiling,
// -3 I/O error (tails truncated back; an untruncatable torn .idx
// fail-stops the writer rather than misalign every later record),
// -4 cookie mismatch.
int64_t do_append_locked(VolumeRec* vol, Writer* w, const uint8_t* blob,
                         int64_t len, uint64_t key, uint32_t size_field,
                         bool check_cookie, uint32_t cookie,
                         int64_t* freed_out) {
  if (w->fd < 0) return -1;
  int64_t tail = w->tail.load(std::memory_order_relaxed);
  if (tail + len > w->max_size) return -2;
  if (check_cookie) {
    uint64_t old_off = 0;
    bool have_old = false;
    {
      std::shared_lock<std::shared_mutex> l(vol->mu);
      auto it = vol->index.find(key);
      if (it != vol->index.end() && it->second.first != 0 &&
          it->second.second != kTombstoneSize) {
        old_off = it->second.first;
        have_old = true;
      }
    }
    if (have_old) {
      uint8_t hdr[4];
      if (pread(vol->fd, hdr, 4, static_cast<off_t>(old_off)) == 4 &&
          be32(hdr) != cookie)
        return -4;
    }
  }
  if (!pwrite_all(w->fd, blob, static_cast<size_t>(len), tail)) {
    int e1 = ftruncate(w->fd, static_cast<off_t>(tail));
    (void)e1;
    return -3;
  }
  uint8_t e[17];
  int ew = 8 + w->offset_width + 4;
  be64_store(e, key);
  uint64_t stored = size_field == kTombstoneSize
                        ? 0
                        : static_cast<uint64_t>(tail) / 8;
  for (int i = 0; i < w->offset_width; i++)
    e[8 + i] = static_cast<uint8_t>(stored >> (8 * (w->offset_width - 1 - i)));
  be32_store(e + 8 + w->offset_width, size_field);
  if (!write_all_fd(w->idx_fd, e, static_cast<size_t>(ew))) {
    // a PARTIAL idx entry would misalign every later record: truncate
    // it back; if even that fails, fail-stop this writer (Python's
    // next lease cycle resumes from the consistent prefix)
    int e2 = ftruncate(w->fd, static_cast<off_t>(tail));
    (void)e2;
    if (ftruncate(w->idx_fd, static_cast<off_t>(w->idx_tail)) != 0) {
      w->accept_posts.store(false, std::memory_order_release);
      close(w->fd);
      close(w->idx_fd);
      w->fd = w->idx_fd = -1;
    }
    return -3;
  }
  w->idx_tail += ew;
  int64_t off = tail;
  w->tail.store(tail + len, std::memory_order_relaxed);
  {
    std::unique_lock<std::shared_mutex> l(vol->mu);
    auto it = vol->index.find(key);
    bool had = it != vol->index.end();
    uint32_t old_size = had ? it->second.second : 0;
    if (size_field == kTombstoneSize) {
      if (had) {
        vol->index.erase(it);
        w->deletes++;
        w->deleted_bytes += old_size;
        if (freed_out) *freed_out = old_size;
      }
    } else {
      vol->index[key] = {static_cast<uint64_t>(off), size_field};
      w->puts++;
      w->put_bytes += size_field;
      if (had) {  // overwrite: old record becomes garbage
        w->deletes++;
        w->deleted_bytes += old_size;
      }
    }
    uint64_t mk = w->max_key.load(std::memory_order_relaxed);
    while (key > mk &&
           !w->max_key.compare_exchange_weak(mk, key)) {
    }
  }
  return off;
}

// Append + durability, per the writer's frozen sync mode. Off: ack
// straight from the page cache (pre-durability behavior). Always: one
// inline fdatasync pair per append under the mutex — the measured
// baseline group mode is judged against. Group: publish a sequence
// number to the committer, RELEASE the append mutex (later appends must
// batch up behind this one, not serialize on its fsync), and wait until
// one fdatasync covers the sequence. Adds -5 to the error codes above:
// durability was lost before the ack (fsync error poisoned the batch,
// or the lease was torn down mid-batch) — the record may or may not be
// on disk, so the caller must NOT ack; Python stays authoritative and a
// client retry lands as a harmless duplicate whose index entry wins.
// do_append also accepts a prepared DeferredAck (`defer`): in group
// mode the rider then doesn't block on the commit at all — its ack is
// queued with the committer (consuming `defer`) and kAckDeferred is
// returned so the caller sends nothing. Blocking-rider and always-mode
// semantics are unchanged when defer is null or unarmed (fd < 0).
constexpr int64_t kAckDeferred = -6;

int64_t do_append(VolumeRec* vol, Writer* w, const uint8_t* blob,
                  int64_t len, uint64_t key, uint32_t size_field,
                  bool check_cookie, uint32_t cookie,
                  int64_t* freed_out = nullptr,
                  DeferredAck* defer = nullptr) {
  uint64_t my_seq = 0;
  uint64_t my_gen = 0;
  bool group_wait = false;
  bool ack_deferred = false;
  int64_t off;
  {
    std::lock_guard<std::mutex> g(w->mu);
    off = do_append_locked(vol, w, blob, len, key, size_field,
                           check_cookie, cookie, freed_out);
    if (off >= 0 && w->sync_mode == 2) {
      bool timed = w->srv && w->srv->stats.enabled.load(
                                 std::memory_order_relaxed);
      uint64_t t0 = timed ? mono_us() : 0;
      if (fdatasync(w->fd) != 0 || fdatasync(w->idx_fd) != 0) {
        // inline fail-stop (poison_writer would re-lock w->mu)
        if (w->srv)
          w->srv->fsync_failures.fetch_add(1, std::memory_order_relaxed);
        {
          std::lock_guard<std::mutex> sg(w->sync_mu);
          w->sync_failed = true;
        }
        w->accept_posts.store(false, std::memory_order_release);
        close(w->fd);
        close(w->idx_fd);
        w->fd = w->idx_fd = -1;
        return -5;
      }
      if (w->srv)
        record_fsync(w->srv, 1, timed ? mono_us() - t0 : 0, timed);
    } else if (off >= 0 && w->sync_mode == 1) {
      std::lock_guard<std::mutex> sg(w->sync_mu);
      if (w->committer_stop || w->sync_failed) return -5;
      my_seq = ++w->append_seq;
      my_gen = w->sync_gen;  // the commit that will cover my_seq
      if (w->srv)
        w->srv->fsync_pending.fetch_add(1, std::memory_order_relaxed);
      if (defer && defer->fd >= 0) {
        defer->seq = my_seq;
        w->deferred.push_back(std::move(*defer));
        ack_deferred = true;
      } else {
        group_wait = true;
      }
      w->sync_cv.notify_one();
    }
  }
  if (ack_deferred) return kAckDeferred;
  if (group_wait) {
    std::unique_lock<std::mutex> sl(w->sync_mu);
    w->ack_cv[my_gen & 1].wait(sl, [&] {
      return w->synced_seq >= my_seq || w->sync_failed;
    });
    if (w->srv)
      w->srv->fsync_pending.fetch_sub(1, std::memory_order_relaxed);
    if (w->synced_seq < my_seq) return -5;
  }
  return off;
}

// First file part of a multipart/form-data body, mirroring
// http_util.Request.multipart_file: boundary split, one CRLF stripped
// per side, filename= part wins. Returns false when no file part.
bool parse_multipart(const std::string& ctype, const std::string& body,
                     std::string* filename, std::string* part_ctype,
                     const char** data, size_t* data_len) {
  if (ctype.compare(0, 19, "multipart/form-data") != 0) return false;
  size_t bpos = ctype.find("boundary=");
  if (bpos == std::string::npos) return false;
  std::string boundary = ctype.substr(bpos + 9);
  size_t send = boundary.find(';');
  if (send != std::string::npos) boundary = boundary.substr(0, send);
  if (!boundary.empty() && boundary.front() == '"') {
    size_t endq = boundary.find('"', 1);
    if (endq == std::string::npos) return false;
    boundary = boundary.substr(1, endq - 1);
  }
  if (boundary.empty()) return false;
  std::string delim = "--" + boundary;
  size_t pos = 0;
  while (pos != std::string::npos && pos < body.size()) {
    size_t start = body.find(delim, pos);
    if (start == std::string::npos) break;
    start += delim.size();
    size_t stop = body.find(delim, start);
    size_t part_end = stop == std::string::npos ? body.size() : stop;
    pos = stop;
    // part is body[start, part_end); strip exactly one CRLF per side
    size_t b = start, e = part_end;
    if (e - b >= 2 && body.compare(b, 2, "\r\n") == 0) b += 2;
    if (e - b >= 2 && body.compare(e - 2, 2, "\r\n") == 0) e -= 2;
    if (e <= b) continue;
    size_t hdr_end = body.find("\r\n\r\n", b);
    if (hdr_end == std::string::npos || hdr_end + 4 > e) continue;
    std::string head = body.substr(b, hdr_end - b);
    std::string lower = head;
    for (auto& c : lower)
      c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
    size_t fpos = lower.find("filename=\"");
    if (fpos == std::string::npos) continue;
    // filename value with \" and \\ unescaped (Python regex
    // filename="((?:[^"\\]|\\.)*)")
    std::string fn;
    size_t i = fpos + 10;
    bool closed = false;
    while (i < head.size()) {
      char c = head[i];
      if (c == '\\' && i + 1 < head.size()) {
        fn.push_back(head[i + 1]);
        i += 2;
        continue;
      }
      if (c == '"') {
        closed = true;
        break;
      }
      fn.push_back(c);
      i++;
    }
    if (!closed) continue;
    std::string pct;
    size_t cpos = lower.find("content-type:");
    if (cpos != std::string::npos) {
      size_t vs = cpos + 13;
      while (vs < head.size() && head[vs] == ' ') vs++;
      size_t ve = head.find("\r\n", vs);
      if (ve == std::string::npos || ve > hdr_end) ve = hdr_end;
      pct = head.substr(vs, ve - vs);
      while (!pct.empty() && (pct.back() == ' ' || pct.back() == '\r'))
        pct.pop_back();
    }
    *filename = fn;
    *part_ctype = pct;
    *data = body.data() + hdr_end + 4;
    *data_len = e - (hdr_end + 4);
    return true;
  }
  return false;
}

// Build a v2/v3 needle record the way storage/needle.py to_bytes does
// for the plain-upload shape: data + optional name/mime +
// last-modified(now). Returns the full padded record; *size_out gets
// the header Size field, *crc_out the masked checksum.
std::vector<uint8_t> build_needle(uint32_t cookie, uint64_t key,
                                  const uint8_t* data, size_t data_len,
                                  const std::string& name,
                                  const std::string& mime, int version,
                                  uint32_t* size_out, uint32_t* crc_out) {
  uint8_t flags = kFlagHasLastModified;  // Python always stamps mtime
  std::string nm = name.substr(0, 255);
  std::string mm = mime.substr(0, 255);
  if (!nm.empty()) flags |= kFlagHasName;
  if (!mm.empty()) flags |= kFlagHasMime;
  size_t body = 4 + data_len + 1;
  if (flags & kFlagHasName) body += 1 + nm.size();
  if (flags & kFlagHasMime) body += 1 + mm.size();
  body += 5;  // last-modified
  size_t base = kHeaderSize + body + kChecksumSize +
                (version == 3 ? kTimestampSize : 0);
  size_t pad = kPaddingSize - base % kPaddingSize;  // never 0
  std::vector<uint8_t> out(base + pad, 0);
  uint8_t* p = out.data();
  be32_store(p, cookie);
  be64_store(p + 4, key);
  be32_store(p + 12, static_cast<uint32_t>(body));
  p += kHeaderSize;
  be32_store(p, static_cast<uint32_t>(data_len));
  p += 4;
  memcpy(p, data, data_len);
  p += data_len;
  *p++ = flags;
  if (flags & kFlagHasName) {
    *p++ = static_cast<uint8_t>(nm.size());
    memcpy(p, nm.data(), nm.size());
    p += nm.size();
  }
  if (flags & kFlagHasMime) {
    *p++ = static_cast<uint8_t>(mm.size());
    memcpy(p, mm.data(), mm.size());
    p += mm.size();
  }
  int64_t now_s = time(nullptr);
  for (int i = 0; i < 5; i++)
    *p++ = static_cast<uint8_t>(now_s >> (8 * (4 - i)));
  uint32_t crc = masked_crc(crc32c(data, data_len));
  be32_store(p, crc);
  p += 4;
  if (version == 3) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    be64_store(p, static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
                      static_cast<uint64_t>(ts.tv_nsec));
  }
  *size_out = static_cast<uint32_t>(body);
  *crc_out = crc;
  return out;
}

// JSON string escape for the upload response's "name" (quotes,
// backslashes, control chars; non-ASCII redirects before we get here).
void json_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    unsigned char u = static_cast<unsigned char>(c);
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if (u < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", u);
      *out += buf;
    } else {
      out->push_back(c);
    }
  }
}

// Plain needle POST on the fast path. The body has already been read.
// Anything off the fast path redirects to Python (which delegates its
// append back through swhp_append — same mutex, same tail).
void serve_write(Server* s, int fd, const Request& req,
                 const std::string& body, uint32_t vid, uint64_t key,
                 uint32_t cookie, bool pipelined) {
  auto vol = s->find(vid);
  if (!vol) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  auto w = vol->get_writer();
  if (!w || !w->accept_posts.load(std::memory_order_acquire) ||
      vol->version == 1 || req.has_pair_headers) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  std::string filename, part_ctype;
  const char* data = nullptr;
  size_t data_len = 0;
  if (!parse_multipart(req.content_type, body, &filename, &part_ctype,
                       &data, &data_len)) {
    // raw-body uploads and exotic envelopes keep one source of truth
    redirect_to_fallback(s, fd, req);
    return;
  }
  // Python guesses a mime from the filename extension (mimetypes reads
  // /etc/mime.types) and escapes non-ASCII names into \uXXXX JSON —
  // both are Python-owned behaviors, so those shapes redirect.
  for (char c : filename) {
    unsigned char u = static_cast<unsigned char>(c);
    if (u < 0x20 || u > 0x7E) {
      redirect_to_fallback(s, fd, req);
      return;
    }
  }
  std::string mime = part_ctype;
  if (mime.empty() && filename.find('.') != std::string::npos) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  if (mime == "application/octet-stream") mime.clear();  // not stored
  if (data_len == 0) {
    // zero-size records are tombstones on disk; Python rejects these
    // loudly (storage/volume.py _reject_empty) — match its 500
    respond_simple(fd, 500, "Internal Server Error",
                   "{\"error\": \"needle " + std::to_string(key) +
                       ": empty data \\u2014 zero-size records are "
                       "tombstones; store empty objects at the filer "
                       "layer (an entry with no chunks)\"}",
                   req.keepalive, "", "application/json");
    return;
  }
  if (w->file_size_limit > 0 &&
      static_cast<int64_t>(data_len) > w->file_size_limit) {
    respond_simple(fd, 413, "Payload Too Large",
                   "{\"error\": \"file over the size limit\"}",
                   req.keepalive, "", "application/json");
    return;
  }
  uint32_t size_field = 0, crc = 0;
  std::vector<uint8_t> blob = build_needle(
      cookie, key, reinterpret_cast<const uint8_t*>(data), data_len,
      filename, mime, vol->version, &size_field, &crc);
  // the success ack depends only on request-side facts, so in group
  // mode it is pre-built and handed to the committer: the rider never
  // blocks on the commit — the committer sends the ack the moment the
  // covering fdatasync returns. Pipelined clients (rare: bytes of the
  // NEXT request already buffered) keep the blocking path so responses
  // cannot reorder with inline-served requests on the same connection.
  char etag[16];
  snprintf(etag, sizeof etag, "%02x%02x%02x%02x", crc >> 24 & 0xFF,
           crc >> 16 & 0xFF, crc >> 8 & 0xFF, crc & 0xFF);
  std::string resp = "{\"name\": \"";
  json_escape(filename, &resp);
  resp += "\", \"size\": " + std::to_string(data_len) +
          ", \"eTag\": \"" + etag + "\"}";
  DeferredAck da;
  if (w->sync_mode == 1 && !pipelined) {
    da.fd = dup(fd);  // dup: the conn thread's close can't race us
    if (da.fd >= 0) {
      da.resp = format_response(200, "OK", resp, req.keepalive, "",
                                "application/json");
      da.fallback = format_response(
          307, "Temporary Redirect", "", req.keepalive,
          "Location: http://" + s->fallback + req.target + "\r\n");
      da.bytes = resp.size();
      da.t0_us = tl_t0;
      da.target = req.target;
    }
  }
  // overwrite-cookie verification happens INSIDE do_append, under the
  // writer mutex (storage/volume.py holds volume.lock across
  // check+append; reference volume_read_write.go reads the stored
  // header's cookie)
  int64_t off = do_append(vol.get(), w.get(), blob.data(),
                          static_cast<int64_t>(blob.size()), key,
                          size_field, /*check_cookie=*/true, cookie,
                          nullptr, &da);
  if (off == kAckDeferred) {
    s->written++;
    tl_deferred = true;
    return;
  }
  if (off == -4) {
    respond_simple(fd, 500, "Internal Server Error",
                   "{\"error\": \"needle " + std::to_string(key) +
                       ": mismatching cookie on overwrite\"}",
                   req.keepalive, "", "application/json");
    return;
  }
  if (off == -2 || off == -1 || off == -5) {
    // addressing ceiling, the lease revoked between the accept_posts
    // check and the append (vacuum/readonly toggle), or durability lost
    // mid-batch (-5: fsync poison / lease teardown — the record was NOT
    // acked, so the client's retry through Python is a harmless
    // duplicate): Python is the authority in every case
    redirect_to_fallback(s, fd, req);
    return;
  }
  if (off < 0) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error",
                   "{\"error\": \"write failed\"}", req.keepalive, "",
                   "application/json");
    return;
  }
  s->written++;  // before the send — see the IMS 304 comment
  respond_simple(fd, 200, "OK", resp, req.keepalive, "",
                 "application/json");
}

// Plain needle DELETE on the fast path: tombstone append under the
// same write lease (storage/volume.py delete_needle; reference
// volume_server_handlers_write.go DeleteHandler). Chunk-manifest
// needles redirect — the cascade to chunk needles is Python's.
void serve_delete(Server* s, int fd, const Request& req, uint32_t vid,
                  uint64_t key, uint32_t cookie) {
  auto vol = s->find(vid);
  if (!vol) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  auto w = vol->get_writer();
  if (!w || !w->accept_posts.load(std::memory_order_acquire) ||
      vol->version == 1) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  uint64_t off = 0;
  uint32_t size = 0;
  {
    std::shared_lock<std::shared_mutex> l(vol->mu);
    auto it = vol->index.find(key);
    if (it != vol->index.end()) {
      off = it->second.first;
      size = it->second.second;
    }
  }
  if (off == 0 || size == kTombstoneSize) {
    // already gone: Python answers freed=0 (goal state, not an error)
    respond_simple(fd, 200, "OK", "{\"size\": 0}", req.keepalive, "",
                   "application/json");
    return;
  }
  if (size > 0) {
    // manifest probe via two tiny preads (volume.read_needle_flags)
    uint8_t ds_raw[4];
    if (pread(vol->fd, ds_raw, 4, static_cast<off_t>(off + 16)) == 4) {
      uint32_t ds = be32(ds_raw);
      uint8_t flags = 0;
      if (ds < size &&
          pread(vol->fd, &flags, 1,
                static_cast<off_t>(off + 16 + 4 + ds)) == 1 &&
          (flags & kFlagChunkManifest)) {
        redirect_to_fallback(s, fd, req);
        return;
      }
    }
  }
  // tombstone record: empty body, crc of empty data, now-stamped
  size_t len = vol->version == 3 ? 32 : 24;
  uint8_t blob[32] = {0};
  be32_store(blob, cookie);
  be64_store(blob + 4, key);
  be32_store(blob + 12, 0);
  be32_store(blob + 16, masked_crc(crc32c(nullptr, 0)));
  if (vol->version == 3) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    be64_store(blob + 20, static_cast<uint64_t>(ts.tv_sec) *
                              1000000000ull +
                          static_cast<uint64_t>(ts.tv_nsec));
  }
  int64_t freed = 0;
  int64_t rc = do_append(vol.get(), w.get(), blob,
                         static_cast<int64_t>(len), key, kTombstoneSize,
                         /*check_cookie=*/true, cookie, &freed);
  if (rc == -4) {
    respond_simple(fd, 500, "Internal Server Error",
                   "{\"error\": \"needle " + std::to_string(key) +
                       ": mismatching cookie on delete\"}",
                   req.keepalive, "", "application/json");
    return;
  }
  if (rc == -2 || rc == -1 || rc == -5) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  if (rc < 0) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error",
                   "{\"error\": \"delete failed\"}", req.keepalive, "",
                   "application/json");
    return;
  }
  s->written++;  // before the send — see the IMS 304 comment
  respond_simple(fd, 200, "OK",
                 "{\"size\": " + std::to_string(freed) + "}",
                 req.keepalive, "", "application/json");
}

void handle_conn(Server* s, int fd) {
  struct timeval tv = {30, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  std::string acc;
  while (!s->stop.load(std::memory_order_relaxed)) {
    Request req;
    int r = read_request(fd, &acc, &req);
    if (r <= 0) break;
    // time from request-parsed to response handed to the kernel; the
    // enabled check keeps the counters-off path clock-free
    bool stats_on = s->stats.enabled.load(std::memory_order_relaxed);
    uint64_t t0 = stats_on ? mono_us() : 0;
    tl_status = 0;
    tl_bytes = 0;
    tl_deferred = false;
    tl_t0 = t0;
    if (req.chunked) req.keepalive = false;  // body framing not parsed
    uint32_t vid = 0, cookie = 0;
    uint64_t key = 0;
    bool fid_ok = parse_fid_path(req.target, &vid, &key, &cookie);
    bool is_write = (req.method == "POST" || req.method == "PUT") &&
                    fid_ok && !req.chunked && req.content_length > 0 &&
                    req.content_length <= s->max_fastpath_bytes;
    if (is_write) {
      // cheap pre-check BEFORE buffering the body: a cluster whose
      // volumes hold no lease (JWT/replicated/TTL'd) must not pay
      // 64MB of buffering per redirect — those drain + 307 below
      auto vol = s->find(vid);
      auto w = vol ? vol->get_writer() : nullptr;
      if (!w || !w->accept_posts.load(std::memory_order_acquire))
        is_write = false;
    }
    if (is_write) {
      // buffer the full multipart body (bounded by max_fastpath_bytes;
      // anything bigger goes to Python via the else-branch drain)
      std::string body;
      body.reserve(static_cast<size_t>(req.content_length));
      int64_t from_acc = std::min<int64_t>(
          req.content_length, static_cast<int64_t>(acc.size()));
      body.append(acc, 0, static_cast<size_t>(from_acc));
      acc.erase(0, static_cast<size_t>(from_acc));
      bool short_read = false;
      char buf[16384];
      while (static_cast<int64_t>(body.size()) < req.content_length) {
        int64_t want = std::min<int64_t>(
            req.content_length - static_cast<int64_t>(body.size()),
            static_cast<int64_t>(sizeof buf));
        ssize_t got = recv(fd, buf, static_cast<size_t>(want), 0);
        if (got <= 0) {
          short_read = true;
          break;
        }
        body.append(buf, static_cast<size_t>(got));
      }
      if (short_read) break;  // torn upload: nothing was appended
      // leftover buffered bytes = the client pipelined the next
      // request; deferring this ack could then reorder responses
      serve_write(s, fd, req, body, vid, key, cookie, !acc.empty());
      if (stats_on && !tl_deferred)
        record_request(s, req, tl_status, tl_bytes, mono_us() - t0);
      if (!req.keepalive) break;
      continue;
    }
    // drain any request body so leftover bytes can't desync the next
    // keep-alive request (redirected POST/PUT carry Content-Length)
    if (req.content_length > 0) {
      int64_t remaining = req.content_length;
      int64_t from_acc =
          std::min<int64_t>(remaining, static_cast<int64_t>(acc.size()));
      acc.erase(0, static_cast<size_t>(from_acc));
      remaining -= from_acc;
      char sink[8192];
      while (remaining > 0) {
        ssize_t got2 = recv(fd, sink,
                            std::min<int64_t>(remaining,
                                              static_cast<int64_t>(
                                                  sizeof sink)),
                            0);
        if (got2 <= 0) {
          req.keepalive = false;
          break;
        }
        remaining -= got2;
      }
    }
    if (req.method == "GET" || req.method == "HEAD") {
      if (fid_ok) {
        serve_needle(s, fd, req, vid, key, cookie);
      } else {
        redirect_to_fallback(s, fd, req);
      }
    } else if (req.method == "DELETE" && fid_ok) {
      serve_delete(s, fd, req, vid, key, cookie);
    } else {
      redirect_to_fallback(s, fd, req);
    }
    if (stats_on)
      record_request(s, req, tl_status, tl_bytes, mono_us() - t0);
    if (!req.keepalive) break;
  }
  close(fd);
  s->live--;
}

void accept_loop(Server* s) {
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      usleep(10000);  // EMFILE/transient: don't busy-spin a core
      continue;
    }
    if (s->stop.load()) {
      close(fd);
      return;
    }
    if (s->live.load() >= s->max_conns) {
      // bounded send: a client that opens excess connections and never
      // reads must not wedge the single acceptor thread
      struct timeval tv = {2, 0};
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
      respond_simple(fd, 503, "Service Unavailable", "too many connections",
                     false);
      close(fd);
      continue;
    }
    s->live++;
    std::thread(handle_conn, s, fd).detach();
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle (nullptr on failure). `fallback` is the
// host:port of the owning Python volume server (redirect target).
void* swhp_start(const char* host, uint16_t port, const char* fallback,
                 int max_conns) {
  auto s = std::make_unique<Server>();
  s->fallback = fallback ? fallback : "";
  if (max_conns > 0) s->max_conns = max_conns;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr =
      host && *host ? inet_addr(host) : htonl(INADDR_LOOPBACK);
  if (addr.sin_addr.s_addr == INADDR_NONE ||
      bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0 ||
      listen(fd, 256) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->listen_fd = fd;
  Server* raw = s.release();
  raw->acceptor = std::thread(accept_loop, raw);
  return raw;
}

uint16_t swhp_port(void* h) { return static_cast<Server*>(h)->port; }

// Registers (or re-registers, e.g. after compaction) a volume. Opens its
// own fd on the .dat; the index starts empty — push entries with
// swhp_put/swhp_put_bulk. Returns 0 ok, -1 open failure.
int swhp_add_volume(void* h, uint32_t vid, const char* dat_path,
                    int version) {
  Server* s = static_cast<Server*>(h);
  int fd = open(dat_path, O_RDONLY);
  if (fd < 0) return -1;
  auto rec = std::make_shared<VolumeRec>();
  rec->fd = fd;
  rec->version = version;
  rec->dat_path = dat_path;
  std::unique_lock<std::shared_mutex> l(s->vols_mu);
  s->vols[vid] = std::move(rec);
  return 0;
}

// Hands this library the volume's write lease: O_RDWR on the .dat
// (appends at `tail`), O_APPEND on the .idx. While enabled, Python
// routes every append through swhp_append and treats the mirror index
// as authoritative. accept_posts additionally opens the fast-path POST
// handler (off for replicated/TTL'd/JWT-guarded volumes — those write
// shapes stay with Python, which still delegates the final append).
int swhp_enable_writer(void* h, uint32_t vid, const char* idx_path,
                       int offset_width, int64_t tail, int64_t max_size,
                       int64_t file_size_limit, int accept_posts) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol || tail % 8 != 0) return -1;
  auto w = std::make_shared<Writer>();
  w->fd = open(vol->dat_path.c_str(), O_RDWR);
  if (w->fd < 0) return -1;
  w->idx_fd = open(idx_path, O_WRONLY | O_APPEND);
  if (w->idx_fd < 0) return -1;
  w->offset_width = offset_width;
  w->tail.store(tail);
  w->idx_tail = lseek(w->idx_fd, 0, SEEK_END);
  w->max_size = max_size;
  w->file_size_limit = file_size_limit;
  w->accept_posts.store(accept_posts != 0, std::memory_order_release);
  // freeze the server's configured durability mode into this lease
  // (a live lease's mode never mutates under in-flight appends). The
  // committer gets private dup'd fds so a fail-stop closing the
  // writer's fds can't invalidate an in-flight fdatasync.
  w->srv = s;
  w->sync_mode = s->sync_mode.load();
  w->batch_us = s->sync_batch_us.load();
  uint64_t mp = s->sync_max_pending.load();
  w->max_pending = mp ? mp : 1;
  if (w->sync_mode == 1) {
    w->sync_dat_fd = dup(w->fd);
    w->sync_idx_fd = dup(w->idx_fd);
    if (w->sync_dat_fd < 0 || w->sync_idx_fd < 0) return -1;
    w->committer = std::thread(committer_loop, s, w.get());
  }
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->writer = std::move(w);
  return 0;
}

// Takes the lease back. Acquiring the writer mutex before closing the
// fds is the barrier: once this returns, no append is in flight and
// none can start, so Python may reload its needle map from the .idx
// and resume its own appends. Returns the final tail (-1: no writer).
int64_t swhp_disable_writer(void* h, uint32_t vid) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::shared_ptr<Writer> w;
  {
    std::unique_lock<std::shared_mutex> l(vol->mu);
    w = std::move(vol->writer);
    vol->writer.reset();
  }
  if (!w) return -1;
  w->accept_posts.store(false, std::memory_order_release);
  // committer teardown FIRST: its final fdatasync drains every pending
  // sequence, so appends enqueued before the stop get their durable
  // acks (a lease handback must never leak an acked-but-unsynced
  // window); an append racing in after the stop poisons itself to -5
  // instead of enqueueing. Only then does taking `mu` below become the
  // usual no-append-in-flight barrier.
  w->stop_committer();
  std::lock_guard<std::mutex> g(w->mu);
  int64_t tail = w->tail.load();
  if (w->fd >= 0) close(w->fd);
  if (w->idx_fd >= 0) close(w->idx_fd);
  w->fd = w->idx_fd = -1;
  return tail;
}

int swhp_set_accept_posts(void* h, uint32_t vid, int on) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  auto w = vol->get_writer();
  if (!w) return -1;
  w->accept_posts.store(on != 0, std::memory_order_release);
  return 0;
}

// Python's delegated append (write_needle / delete_needle build the
// record — TTLs, pairs, manifests and all — and hand the bytes here so
// the volume keeps exactly one tail writer). size_field is the header
// Size (kTombstoneSize for deletes). check_cookie re-verifies the
// overwrite/delete cookie against the stored needle under the append
// mutex (Python's own pre-check races with fast-path POSTs).
// Returns the offset or the do_append error code.
int64_t swhp_append(void* h, uint32_t vid, const uint8_t* blob,
                    int64_t len, uint64_t key, uint32_t size_field,
                    int check_cookie, uint32_t cookie) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  auto w = vol->get_writer();
  if (!w) return -1;
  return do_append(vol.get(), w.get(), blob, len, key, size_field,
                   check_cookie != 0, cookie);
}

// Mirror-index probe (1 found, 0 absent). In writer mode the mirror is
// exact, so Python's read/delete/overwrite paths use this instead of
// their (frozen) needle map.
int swhp_lookup(void* h, uint32_t vid, uint64_t key, uint64_t* offset,
                uint32_t* size) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return 0;
  std::shared_lock<std::shared_mutex> l(vol->mu);
  auto it = vol->index.find(key);
  if (it == vol->index.end()) return 0;
  *offset = it->second.first;
  *size = it->second.second;
  return 1;
}

// Counter deltas since enable: puts, put_bytes, deletes, deleted_bytes,
// max_key, tail (in that order). Python adds them to its needle-map
// counters for heartbeats/vacuum decisions while the lease is out.
int swhp_writer_counters(void* h, uint32_t vid, uint64_t out[6]) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  auto w = vol->get_writer();
  if (!w) return -1;
  out[0] = w->puts.load();
  out[1] = w->put_bytes.load();
  out[2] = w->deletes.load();
  out[3] = w->deleted_bytes.load();
  out[4] = w->max_key.load();
  // lock-free: heartbeats read counters five times per volume and must
  // not contend with in-flight appends
  out[5] = static_cast<uint64_t>(w->tail.load());
  return 0;
}

int swhp_remove_volume(void* h, uint32_t vid) {
  Server* s = static_cast<Server*>(h);
  swhp_disable_writer(h, vid);  // mutex barrier before the rec can die
  std::unique_lock<std::shared_mutex> l(s->vols_mu);
  return s->vols.erase(vid) ? 0 : -1;
}

int swhp_put(void* h, uint32_t vid, uint64_t key, uint64_t offset,
             uint32_t size) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index[key] = {offset, size};
  return 0;
}

// Bulk load: parallel arrays (numpy-friendly). Insert-only: a key that
// raced in via swhp_put between Python's needle-map snapshot and this
// load is FRESHER than the snapshot — overwriting it would serve the
// pre-overwrite offset until that key's next write.
int swhp_put_bulk(void* h, uint32_t vid, const uint64_t* keys,
                  const uint64_t* offsets, const uint32_t* sizes,
                  int64_t count) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index.reserve(vol->index.size() + static_cast<size_t>(count));
  for (int64_t i = 0; i < count; i++)
    vol->index.emplace(keys[i], std::make_pair(offsets[i], sizes[i]));
  return 0;
}

int swhp_delete(void* h, uint32_t vid, uint64_t key) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index.erase(key);
  return 0;
}

uint64_t swhp_served(void* h) { return static_cast<Server*>(h)->served; }
uint64_t swhp_redirected(void* h) {
  return static_cast<Server*>(h)->redirected;
}
uint64_t swhp_written(void* h) { return static_cast<Server*>(h)->written; }

// ---- hot-path telemetry ------------------------------------------------

// Flat snapshot of the plane's request telemetry (one relaxed load per
// slot — values from concurrent requests may be mutually torn, which is
// fine for monotonic counters). Layout, all uint64:
//   [0] requests_total          [1..5] status classes 1xx..5xx
//   [6] bytes_sent              [7] redirects_to_python
//   [8] index_misses            [9] latency observation count
//   [10] latency sum (µs)       [11..] per-bucket counts, last = +Inf
// Returns the number of values written, -1 if `out` is too small
// (size with swhp_stats_len()).
int swhp_stats_len() { return 11 + kLatBuckets + 1; }

int swhp_stats(void* h, uint64_t* out, int n) {
  if (!h || n < 11 + kLatBuckets + 1) return -1;
  Server* s = static_cast<Server*>(h);
  PlaneStats& st = s->stats;
  out[0] = st.requests.load(std::memory_order_relaxed);
  for (int c = 1; c <= 5; c++)
    out[c] = st.by_class[c].load(std::memory_order_relaxed);
  out[6] = st.bytes_sent.load(std::memory_order_relaxed);
  out[7] = s->redirected.load(std::memory_order_relaxed);
  out[8] = st.index_misses.load(std::memory_order_relaxed);
  out[9] = st.lat_count.load(std::memory_order_relaxed);
  out[10] = st.lat_sum_us.load(std::memory_order_relaxed);
  for (int b = 0; b <= kLatBuckets; b++)
    out[11 + b] = st.lat_buckets[b].load(std::memory_order_relaxed);
  return 11 + kLatBuckets + 1;
}

// µs upper bounds of the latency buckets (the +Inf bucket is implicit).
int swhp_lat_bounds(uint64_t* out, int n) {
  if (!out || n < kLatBuckets) return -1;
  for (int b = 0; b < kLatBuckets; b++) out[b] = kLatBoundsUs[b];
  return kLatBuckets;
}

void swhp_set_stats_enabled(void* h, int on) {
  static_cast<Server*>(h)->stats.enabled.store(
      on != 0, std::memory_order_relaxed);
}

void swhp_set_slow_us(void* h, uint64_t us) {
  static_cast<Server*>(h)->stats.slow_us.store(
      us, std::memory_order_relaxed);
}

// Newest-first JSON array of the slow-request ring. Writes at most
// buflen-1 bytes plus a NUL; returns the body length, or -1 when the
// buffer cannot hold the whole ring (callers pass 64 KB — 64 entries
// at ~300 bytes each always fit).
int swhp_slow_ring(void* h, char* buf, int buflen) {
  if (!h || !buf || buflen < 3) return -1;
  PlaneStats& st = static_cast<Server*>(h)->stats;
  auto jsonable = [](const char* in) {
    // targets/methods are raw wire bytes: escape quotes/backslashes and
    // blank out control chars so the ring always parses as JSON
    std::string out;
    for (const char* p = in; *p; p++) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"' || c == '\\') {
        out.push_back('\\');
        out.push_back(*p);
      } else if (c < 0x20) {
        out.push_back('?');
      } else {
        out.push_back(*p);
      }
    }
    return out;
  };
  std::string out = "[";
  {
    std::lock_guard<std::mutex> g(st.slow_mu);
    uint64_t have = std::min<uint64_t>(st.slow_seq, kSlowRing);
    for (uint64_t i = 0; i < have; i++) {
      const SlowEntry& e = st.slow[(st.slow_seq - 1 - i) % kSlowRing];
      char item[320];
      snprintf(item, sizeof item,
               "%s{\"method\": \"%s\", \"target\": \"%s\", "
               "\"status\": %d, \"bytes\": %llu, \"micros\": %llu, "
               "\"unix_ms\": %llu}",
               i ? ", " : "", jsonable(e.method).c_str(),
               jsonable(e.target).c_str(), e.status,
               static_cast<unsigned long long>(e.bytes),
               static_cast<unsigned long long>(e.micros),
               static_cast<unsigned long long>(e.unix_ms));
      out += item;
    }
  }
  out += "]";
  if (out.size() + 1 > static_cast<size_t>(buflen)) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// ---- group-commit durability -------------------------------------------

// Configures the durability mode applied to writers at enable time
// (SW_PLANE_FSYNC_MODE): 0 = off (ack from the page cache — the
// pre-durability behavior), 1 = group (a committer amortizes ONE
// fdatasync per commit window over every rider), 2 = always (fdatasync
// per append — the baseline group mode is measured against). batch_us
// is the commit window, max_pending the rider count forcing an early
// commit. Live leases keep the mode they were enabled with; Python
// cycles the lease to apply a change. Returns 0, -1 on a bad mode.
int swhp_set_sync_mode(void* h, int mode, uint64_t batch_us,
                       uint64_t max_pending) {
  if (!h || mode < 0 || mode > 2) return -1;
  Server* s = static_cast<Server*>(h);
  s->sync_mode.store(mode);
  s->sync_batch_us.store(batch_us);
  s->sync_max_pending.store(max_pending ? max_pending : 1);
  return 0;
}

// Flat snapshot of the durability telemetry, all uint64:
//   [0] mode        [1] batch_us     [2] max_pending
//   [3] batches     [4] riders       [5] fsync_failures
//   [6] pending     [7] fsync µs sum
//   [8..] per-bucket fsync µs counts (bounds = swhp_lat_bounds, last =
//         +Inf); the µs sum and buckets flow only while stats are
//         enabled — SW_PLANE_STATS=0 keeps the committer clock-free.
int swhp_sync_stats_len() { return 8 + kLatBuckets + 1; }

int swhp_sync_stats(void* h, uint64_t* out, int n) {
  if (!h || n < 8 + kLatBuckets + 1) return -1;
  Server* s = static_cast<Server*>(h);
  out[0] = static_cast<uint64_t>(s->sync_mode.load());
  out[1] = s->sync_batch_us.load();
  out[2] = s->sync_max_pending.load();
  out[3] = s->fsync_batches.load(std::memory_order_relaxed);
  out[4] = s->fsync_riders.load(std::memory_order_relaxed);
  out[5] = s->fsync_failures.load(std::memory_order_relaxed);
  out[6] = s->fsync_pending.load(std::memory_order_relaxed);
  out[7] = s->fsync_us_sum.load(std::memory_order_relaxed);
  for (int b = 0; b <= kLatBuckets; b++)
    out[8 + b] = s->fsync_buckets[b].load(std::memory_order_relaxed);
  return 8 + kLatBuckets + 1;
}

// ---- EC volumes + reconstructed-slab cache -----------------------------

// Registers (or re-registers after a mount change) an EC volume's
// striping geometry. The index starts empty — push .ecx entries with
// swhp_ec_put_bulk, attach local shard files with swhp_ec_set_shard.
// dat_size is the ORIGINAL .dat size (drives the encoder-exact
// large/small row split); slab_bytes must equal the Python engine's
// SW_EC_DEGRADED_SLAB_BYTES or cached slabs will be mis-addressed.
int swhp_ec_register(void* h, uint32_t vid, int version, int64_t dat_size,
                     int64_t large_block, int64_t small_block,
                     int64_t slab_bytes) {
  if (!h || dat_size <= 0 || large_block <= 0 || small_block <= 0 ||
      slab_bytes <= 0)
    return -1;
  Server* s = static_cast<Server*>(h);
  auto rec = std::make_shared<EcVolumeRec>();
  rec->version = version;
  rec->dat_size = dat_size;
  rec->large_block = large_block;
  rec->small_block = small_block;
  rec->slab_bytes = slab_bytes;
  std::unique_lock<std::shared_mutex> l(s->ec_mu);
  s->ec_vols[vid] = std::move(rec);
  return 0;
}

// Attaches (path non-empty) or detaches (path null/empty) a local shard
// file. A detached data shard is "lost" from the plane's viewpoint: its
// bytes must come from the slab cache or the request redirects.
int swhp_ec_set_shard(void* h, uint32_t vid, int sid,
                      const char* shard_path) {
  if (sid < 0 || sid >= kMaxEcShards) return -1;
  Server* s = static_cast<Server*>(h);
  auto ev = s->find_ec(vid);
  if (!ev) return -1;
  int fd = -1;
  if (shard_path && *shard_path) {
    fd = open(shard_path, O_RDONLY);
    if (fd < 0) return -1;
  }
  std::unique_lock<std::shared_mutex> l(ev->mu);
  if (ev->shard_fds[sid] >= 0) close(ev->shard_fds[sid]);
  ev->shard_fds[sid] = fd;
  return 0;
}

// Bulk .ecx index push: parallel arrays of key / BYTE offset in the
// logical .dat / size. Assign (not insert-only): the EC index mirrors a
// point-in-time .ecx snapshot taken under Python's ecx lock, and
// tombstones are pushed as kTombstoneSize entries rather than omitted.
int swhp_ec_put_bulk(void* h, uint32_t vid, const uint64_t* keys,
                     const uint64_t* offsets, const uint32_t* sizes,
                     int64_t count) {
  Server* s = static_cast<Server*>(h);
  auto ev = s->find_ec(vid);
  if (!ev) return -1;
  std::unique_lock<std::shared_mutex> l(ev->mu);
  ev->index.reserve(ev->index.size() + static_cast<size_t>(count));
  for (int64_t i = 0; i < count; i++)
    ev->index[keys[i]] = {offsets[i], sizes[i]};
  return 0;
}

// Mirrors an EC delete: tombstone (not erase), matching the in-place
// .ecx tombstone Python just wrote.
int swhp_ec_delete(void* h, uint32_t vid, uint64_t key) {
  Server* s = static_cast<Server*>(h);
  auto ev = s->find_ec(vid);
  if (!ev) return -1;
  std::unique_lock<std::shared_mutex> l(ev->mu);
  auto it = ev->index.find(key);
  if (it != ev->index.end()) it->second.second = kTombstoneSize;
  return 0;
}

uint64_t swhp_cache_invalidate(void* h, uint32_t vid, int sid);

int swhp_ec_unregister(void* h, uint32_t vid) {
  Server* s = static_cast<Server*>(h);
  {
    std::unique_lock<std::shared_mutex> l(s->ec_mu);
    if (!s->ec_vols.erase(vid)) return -1;
  }
  // defense in depth: Python invalidates explicitly on mount/rebuild,
  // but a dropped registration must never strand stale slabs either
  swhp_cache_invalidate(h, vid, -1);
  return 0;
}

// Sets the cache byte budget (SW_PLANE_CACHE_BYTES); shrinking evicts
// down immediately. 0 disables the cache (and with it the in-plane
// degraded path — every lost-shard read misses and redirects).
void swhp_cache_configure(void* h, uint64_t max_bytes) {
  SlabCache& c = static_cast<Server*>(h)->cache;
  std::lock_guard<std::mutex> g(c.mu);
  c.max_bytes = max_bytes;
  c.evict_to_budget();
}

// Publishes one reconstructed slab (overwriting any prior entry). len 0
// is valid — a past-tail slab cached as "known empty" so reads covering
// it stay in-plane. Returns 0 ok, -1 rejected (cache disabled or the
// slab alone exceeds the whole budget).
int swhp_cache_put(void* h, uint32_t vid, int sid, uint64_t idx,
                   const uint8_t* data, uint64_t len) {
  if (sid < 0 || sid >= kMaxEcShards || (len > 0 && !data)) return -1;
  SlabCache& c = static_cast<Server*>(h)->cache;
  auto blob = std::make_shared<std::vector<uint8_t>>(data, data + len);
  SlabKey k{static_cast<uint64_t>(vid) << 32 | static_cast<uint32_t>(sid),
            idx};
  std::lock_guard<std::mutex> g(c.mu);
  if (c.max_bytes == 0 || len > c.max_bytes) return -1;
  auto it = c.map.find(k);
  if (it != c.map.end()) {
    c.bytes -= it->second->second->size();
    c.lru.erase(it->second);
    c.map.erase(it);
  }
  c.lru.emplace_front(k, std::move(blob));
  c.map[k] = c.lru.begin();
  c.bytes += len;
  c.puts++;
  c.put_bytes += len;
  c.evict_to_budget();
  return 0;
}

// Drops every slab of (vid, sid), or of the whole vid when sid < 0.
// Returns the number of entries removed. In-flight reads that already
// grabbed a slab's shared_ptr finish with the bytes they started with —
// callers serialize rebuild-then-invalidate-then-serve ordering above.
uint64_t swhp_cache_invalidate(void* h, uint32_t vid, int sid) {
  SlabCache& c = static_cast<Server*>(h)->cache;
  uint64_t vs = static_cast<uint64_t>(vid) << 32 |
                static_cast<uint32_t>(sid < 0 ? 0 : sid);
  uint64_t removed = 0;
  std::lock_guard<std::mutex> g(c.mu);
  for (auto it = c.lru.begin(); it != c.lru.end();) {
    bool match = sid < 0 ? (it->first.vs >> 32) == vid : it->first.vs == vs;
    if (match) {
      c.bytes -= it->second->size();
      c.map.erase(it->first);
      it = c.lru.erase(it);
      removed++;
    } else {
      ++it;
    }
  }
  c.invalidated += removed;
  return removed;
}

// Flat snapshot of the slab cache + EC serving outcomes, all uint64:
//   [0] puts        [1] put_bytes   [2] hits         [3] misses
//   [4] evictions   [5] invalidated [6] entries      [7] bytes
//   [8] max_bytes   [9] degraded_served (in-plane, cache-fed)
//   [10] degraded_redirected (lost shard, slabs absent or bad)
//   [11] ec_local_served (all shards local)
// The first nine are one consistent snapshot (taken under the cache
// mutex — exact, not torn); the last three are relaxed atomics.
int swhp_cache_stats_len() { return 12; }

int swhp_cache_stats(void* h, uint64_t* out, int n) {
  if (!h || n < 12) return -1;
  Server* s = static_cast<Server*>(h);
  SlabCache& c = s->cache;
  {
    std::lock_guard<std::mutex> g(c.mu);
    out[0] = c.puts;
    out[1] = c.put_bytes;
    out[2] = c.hits;
    out[3] = c.misses;
    out[4] = c.evictions;
    out[5] = c.invalidated;
    out[6] = c.map.size();
    out[7] = c.bytes;
    out[8] = c.max_bytes;
  }
  out[9] = s->ec_degraded_served.load(std::memory_order_relaxed);
  out[10] = s->ec_degraded_redirected.load(std::memory_order_relaxed);
  out[11] = s->ec_local_served.load(std::memory_order_relaxed);
  return 12;
}

void swhp_stop(void* h) {
  Server* s = static_cast<Server*>(h);
  s->stop = true;
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  if (s->acceptor.joinable()) s->acceptor.join();
  // give in-flight connection threads a beat to observe stop and finish
  for (int i = 0; i < 200 && s->live.load() > 0; i++)
    usleep(10000);
  // Leak s if connections are stuck: a crash on a wedged shutdown is
  // worse than 1KB at process exit.
  if (s->live.load() == 0) delete s;
}

}  // extern "C"
