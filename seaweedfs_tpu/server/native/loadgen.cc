// Minimal keep-alive HTTP load generator (benchmark client).
//
// The Python benchmark client tops out around ~350 req/s/process on
// this kernel (syscall + interpreter overhead), which cannot exercise
// the native data plane. This tool is the measuring instrument: N
// threads, each with one keep-alive connection, issuing requests for a
// fixed duration and validating status codes.
//
//   ./loadgen <host> <port> <seconds> <threads> <path-file> [post <size>]
//
// path-file: newline-separated request paths (e.g. /3,01637037d6);
// each thread cycles through them starting at a random offset.
// With `post <size>`, each request is a multipart upload of <size>
// random-ish bytes to the path (the write-plane drill; use a batch
// assign's fid_0..fid_N paths so every write is a fresh needle).
// Prints one line: total requests, elapsed seconds, req/s, errors.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::atomic<uint64_t> g_requests{0}, g_errors{0};
std::atomic<bool> g_stop{false};

int dial(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(static_cast<uint16_t>(port));
  a.sin_addr.s_addr = inet_addr(host);
  if (connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof a) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  return fd;
}

// Reads one HTTP response off the socket; returns status or -1.
// Handles Content-Length framing only (both our planes always send it).
int read_response(int fd, std::string* buf) {
  size_t header_end;
  for (;;) {
    header_end = buf->find("\r\n\r\n");
    if (header_end != std::string::npos) break;
    char tmp[8192];
    ssize_t r = recv(fd, tmp, sizeof tmp, 0);
    if (r <= 0) return -1;
    buf->append(tmp, static_cast<size_t>(r));
  }
  int status = -1;
  if (buf->size() > 12) status = atoi(buf->c_str() + 9);
  int64_t clen = 0;
  // case-insensitive content-length scan within the header block
  for (size_t pos = 0; pos < header_end;) {
    size_t eol = buf->find("\r\n", pos);
    if (eol == std::string::npos || eol > header_end) break;
    if (strncasecmp(buf->c_str() + pos, "content-length:", 15) == 0)
      clen = atoll(buf->c_str() + pos + 15);
    pos = eol + 2;
  }
  size_t need = header_end + 4 + static_cast<size_t>(clen);
  while (buf->size() < need) {
    char tmp[16384];
    ssize_t r = recv(fd, tmp, sizeof tmp, 0);
    if (r <= 0) return -1;
    buf->append(tmp, static_cast<size_t>(r));
  }
  buf->erase(0, need);
  return status;
}

// 0 = GET mode; >0 = multipart POST mode with this payload size.
int g_post_size = 0;

std::string make_post_body(int size, unsigned seed) {
  const char* b = "ldgenboundary7f3a";
  std::string payload(static_cast<size_t>(size), 'x');
  for (size_t j = 0; j < payload.size(); j++)
    payload[j] = static_cast<char>('a' + ((seed + j * 2654435761u) % 26));
  return std::string("--") + b +
         "\r\nContent-Disposition: form-data; name=\"file\"; "
         "filename=\"ldgen\"\r\n"
         "Content-Type: application/octet-stream\r\n\r\n" +
         payload + "\r\n--" + b + "--\r\n";
}

void run(const char* host, int port, const std::vector<std::string>* paths,
         size_t start) {
  int fd = dial(host, port);
  std::string buf;
  size_t i = start;
  std::string body;
  if (g_post_size > 0)
    body = make_post_body(g_post_size, static_cast<unsigned>(start));
  while (!g_stop.load(std::memory_order_relaxed)) {
    if (fd < 0) {
      fd = dial(host, port);
      if (fd < 0) {
        g_errors++;
        usleep(10000);
        continue;
      }
      buf.clear();
    }
    const std::string& p = (*paths)[i++ % paths->size()];
    std::string req;
    if (g_post_size > 0) {
      req = "POST " + p +
            " HTTP/1.1\r\nHost: x\r\nContent-Type: multipart/form-data; "
            "boundary=ldgenboundary7f3a\r\nContent-Length: " +
            std::to_string(body.size()) + "\r\n\r\n" + body;
    } else {
      req = "GET " + p + " HTTP/1.1\r\nHost: x\r\n\r\n";
    }
    if (send(fd, req.data(), req.size(), MSG_NOSIGNAL) !=
        static_cast<ssize_t>(req.size())) {
      close(fd);
      fd = -1;
      continue;
    }
    int status = read_response(fd, &buf);
    if (status == 200) {
      g_requests++;
    } else if (status < 0) {
      close(fd);
      fd = -1;
    } else {
      g_errors++;
      g_requests++;
    }
  }
  if (fd >= 0) close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 6 && !(argc == 8 && strcmp(argv[6], "post") == 0)) {
    fprintf(stderr,
            "usage: %s <host> <port> <seconds> <threads> <path-file> "
            "[post <size>]\n",
            argv[0]);
    return 2;
  }
  if (argc == 8) g_post_size = atoi(argv[7]);
  const char* host = argv[1];
  int port = atoi(argv[2]);
  double seconds = atof(argv[3]);
  int nthreads = atoi(argv[4]);
  std::vector<std::string> paths;
  std::ifstream f(argv[5]);
  for (std::string line; std::getline(f, line);)
    if (!line.empty()) paths.push_back(line);
  if (paths.empty()) {
    fprintf(stderr, "no paths\n");
    return 2;
  }
  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> ts;
  for (int i = 0; i < nthreads; i++)
    ts.emplace_back(run, host, port, &paths,
                    static_cast<size_t>(i) * 7919);
  std::this_thread::sleep_for(
      std::chrono::milliseconds(static_cast<int>(seconds * 1000)));
  g_stop = true;
  for (auto& t : ts) t.join();
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
  printf("{\"requests\": %llu, \"seconds\": %.3f, \"rps\": %.1f, "
         "\"errors\": %llu}\n",
         static_cast<unsigned long long>(g_requests.load()), dt,
         g_requests.load() / dt,
         static_cast<unsigned long long>(g_errors.load()));
  return 0;
}
