#!/bin/sh
# Build the native volume-server data plane (thread-per-connection HTTP
# server serving needle reads AND plain needle writes without the
# Python GIL in the loop) and the keep-alive load generator used to
# measure it (GET mode + multipart POST mode).
set -e
cd "$(dirname "$0")"
g++ -O2 -std=c++17 -fPIC -shared -pthread -o libseaweed_http.so http_plane.cc
g++ -O2 -std=c++17 -pthread -o loadgen loadgen.cc
echo "built $(pwd)/libseaweed_http.so and loadgen"
