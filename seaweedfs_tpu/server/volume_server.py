"""VolumeServer — the data plane.

Reference weed/server/volume_server.go + handlers: public HTTP needle
read/write/delete with synchronous replica fan-out
(topology/store_replicate.go), heartbeat client loop
(volume_grpc_client_to_master.go), admin ops (allocate/delete/vacuum), and
the EC lifecycle + degraded read (store_ec.go): local shard -> remote
shard over HTTP -> reconstruct-on-read from >=10 sibling intervals.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..ec.constants import DATA_SHARDS, TOTAL_SHARDS, to_ext
from ..ops.codec import get_codec
from ..util import tracing
from ..storage.needle import Needle
from ..storage.store import Store
from ..storage.types import parse_file_id
from ..storage.volume import NotFound, VolumeError, volume_file_prefix
from .http_util import (HttpError, HttpServer, Request, Response, Router,
                        get_json, http_call, post_json, profile_handler,
                        traces_export_handler, traces_handler)


class VolumeServer:
    def __init__(self, port: int = 8080, host: str = "127.0.0.1",
                 directories=None, master_url: str = "127.0.0.1:9333",
                 data_center: str = "", rack: str = "",
                 max_volume_counts=None, pulse_seconds: float = None,
                 public_url: str = "", read_redirect: bool = True,
                 ec_backend: str = "auto", jwt_signing_key: str = "",
                 whitelist=(), index_kind: str = "memory",
                 compaction_mbps: int = 0, fast_port: int = 0,
                 file_size_limit_mb: int = 256):
        router = Router()
        router.add("*", "/status", self.status)
        router.add("POST", "/admin/assign_volume", self.admin_assign_volume)
        router.add("POST", "/admin/delete_volume", self.admin_delete_volume)
        router.add("POST", "/admin/volume/readonly", self.admin_readonly)
        router.add("POST", "/admin/volume/configure_replication",
                   self.admin_configure_replication)
        router.add("POST", "/admin/volume/mount", self.admin_volume_mount)
        router.add("POST", "/admin/volume/unmount",
                   self.admin_volume_unmount)
        router.add("POST", "/admin/vacuum/check", self.admin_vacuum_check)
        router.add("POST", "/admin/vacuum/compact", self.admin_vacuum_compact)
        router.add("POST", "/admin/vacuum/commit", self.admin_vacuum_commit)
        router.add("POST", "/admin/ec/generate", self.admin_ec_generate)
        router.add("POST", "/admin/ec/mount", self.admin_ec_mount)
        router.add("POST", "/admin/ec/unmount", self.admin_ec_unmount)
        router.add("POST", "/admin/ec/rebuild", self.admin_ec_rebuild)
        router.add("POST", "/admin/ec/copy", self.admin_ec_copy)
        router.add("POST", "/admin/ec/delete_shards",
                   self.admin_ec_delete_shards)
        router.add("POST", "/admin/ec/shard_write",
                   self.admin_ec_shard_write)
        router.add("POST", "/admin/volume/copy", self.admin_volume_copy)
        router.add("POST", "/admin/volume/verify", self.admin_volume_verify)
        router.add("POST", "/admin/ec/to_volume", self.admin_ec_to_volume)
        router.add("GET", "/admin/ec/shard_read", self.admin_ec_shard_read)
        router.add("POST", "/admin/ec/shard_repair_read",
                   self.admin_ec_shard_repair_read)
        router.add("POST", "/admin/ec/shard_plane_read",
                   self.admin_ec_shard_plane_read)
        router.add("POST", "/admin/ec/scrub", self.admin_ec_scrub)
        router.add("GET", "/admin/ec/scrub_status",
                   self.admin_ec_scrub_status)
        router.add("POST", "/admin/ec/scrub_repair",
                   self.admin_ec_scrub_repair)
        router.add("GET", "/admin/file", self.admin_file)
        router.add("POST", "/admin/volume/tier_upload",
                   self.admin_tier_upload)
        router.add("POST", "/admin/volume/tier_download",
                   self.admin_tier_download)
        router.add("GET", "/admin/volume/sync_status",
                   self.admin_volume_sync_status)
        router.add("GET", "/admin/volume/tail", self.admin_volume_tail)
        router.add("POST", "/admin/volume/tail_receive",
                   self.admin_volume_tail_receive)
        router.add("GET", "/metrics", self.metrics_handler)
        router.add("GET", "/admin/traces", traces_handler)
        router.add("GET", "/admin/traces/export", traces_export_handler)
        router.add("GET", "/admin/plane/slow", self.admin_plane_slow)
        router.add("GET", "/admin/plane/cache", self.admin_plane_cache)
        router.add("GET", "/admin/plane/durability",
                   self.admin_plane_durability)
        router.add("GET", "/admin/devices", self.admin_devices)
        router.add("POST", "/admin/profile", profile_handler)
        router.add("GET", "/stats/disk", self.stats_disk)
        router.add("GET", "/stats/memory", self.stats_memory)
        router.add("GET", "/ui", self.ui_handler)
        router.add("POST", "/query", self.query_handler)
        router.set_fallback(self.data_handler)
        router.before = self._guard_check
        from ..stats.metrics import (VOLUME_REQUEST_COUNTER,
                                     VOLUME_REQUEST_HISTOGRAM)

        def observe(label, seconds, ok):
            VOLUME_REQUEST_COUNTER.inc(label if ok else label + " error")
            # the router's server span is still current here, so the
            # bucket this lands in carries its trace id as an exemplar
            VOLUME_REQUEST_HISTOGRAM.observe(
                seconds, label, trace_id=tracing.current_trace_id())
        router.observe = observe

        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        self.host = host
        router.node = f"{host}:{self.port}"
        # master_url may list several seed masters; heartbeats follow
        # the leader hint and rotate seeds on failure (reference
        # volume_grpc_client_to_master.go:25-55)
        self._seed_masters = [m.strip() for m in master_url.split(",")
                              if m.strip()]
        self.master_url = self._seed_masters[0]
        self._seed_i = 0
        from ..util import config as _config
        self.pulse_seconds = _config.env_float("SW_PULSE_S") \
            if pulse_seconds is None else pulse_seconds
        self.read_redirect = read_redirect
        codec = get_codec(DATA_SHARDS, 4, backend=ec_backend) \
            if ec_backend != "auto" else None
        self.store = Store(
            directories or ["./data"],
            max_volume_counts=max_volume_counts,
            ip=host, port=self.port,
            public_url=public_url or f"{host}:{self.port}",
            data_center=data_center, rack=rack, codec=codec,
            index_kind=index_kind)
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        # upload size cap (reference -fileSizeLimitMB: "limit file size
        # to avoid out of memory"); 0 (or negative) disables
        self.file_size_limit = max(0, int(file_size_limit_mb)) << 20
        # compaction write throttle (reference -compactionMBps)
        self.compaction_bps = int(compaction_mbps) << 20
        self.jwt_signing_key = jwt_signing_key
        from ..security.guard import Guard
        self.guard = Guard(whitelist)
        self._lookup_cache: Dict[int, tuple] = {}
        from ..client.vid_map import shared_vid_map
        self._vid_map = shared_vid_map(self.master_url)
        from ..ec.shard_cache import EcShardLocationCache
        self._ec_loc_cache = EcShardLocationCache(
            self._fetch_ec_shard_locations)
        # batched degraded-read serving tier: reconstruct-on-read with
        # request coalescing, exactly-k survivor gather and a
        # reconstructed-slab LRU (ec/degraded.py)
        from ..ec.degraded import DegradedReadEngine
        from ..stats.metrics import DEGRADED_READ_HISTOGRAM
        self.degraded = DegradedReadEngine(
            store=self.store,
            locations=self._ec_shard_locations,
            codec=lambda: self.store.codec or get_codec(DATA_SHARDS, 4),
            loc_cache=self._ec_loc_cache,
            self_url=lambda: self.url,
            on_read=lambda s: DEGRADED_READ_HISTOGRAM.observe(
                s, trace_id=tracing.current_trace_id()),
            on_slabs=self._publish_slabs)
        # a shard (re-)registered after rebuild must win over cached
        # reconstructions immediately — in the engine's LRU AND in the
        # native plane's slab cache (_on_ec_mount re-syncs the plane's
        # shard set first, then invalidates both)
        self.store.on_ec_mount = self._on_ec_mount
        # background integrity scrub: paced H·x=0 syndrome verification
        # of every local EC volume, findings pushed to the master's
        # repair queue (ec/scrub.py)
        from ..ec.scrub import ScrubEngine
        self.scrub = ScrubEngine(
            store=self.store,
            locations=self._ec_shard_locations,
            codec=lambda: self.store.codec or get_codec(DATA_SHARDS, 4),
            self_url=lambda: self.url,
            on_finding=self._report_scrub_finding)
        self._stop = threading.Event()
        # immediate delta-push (reference store.go:40-64 change channels,
        # consumed by volume_grpc_client_to_master.go:57-185): volume
        # create/delete and EC shard mount/unmount wake the heartbeat
        # loop so the master learns within milliseconds, not a pulse.
        self._hb_wake = threading.Event()
        self.store.on_change = self._hb_wake.set
        # native read plane (reference: the Go data plane itself; here
        # a C++ thread-per-connection server on a second advertised
        # port, serving plain needle GETs without the GIL — anything
        # non-trivial 307s back to this Python server). Gated off when
        # read auth or TLS is configured: the plane speaks open HTTP.
        self.fast_plane = None
        from .http_util import tls_enabled
        if fast_port >= 0 and not whitelist and not tls_enabled():
            try:
                from .native_plane import NativeReadPlane
                self.fast_plane = NativeReadPlane(
                    host, fast_port,
                    public_url or f"{host}:{self.port}")
                for loc in self.store.locations:
                    for v in loc.volumes.values():
                        with v.lock:
                            self.fast_plane.register_volume(v)
                            self._writer_acquire(v)
                for loc in self.store.locations:
                    for vid in list(loc.ec_volumes):
                        self._fast_ec_sync(vid)
            except Exception as e:  # noqa: BLE001 - plane is optional
                from ..util import config as _config
                if _config.env_is_set("SW_HTTP_PLANE_LIB"):
                    raise   # explicit lib override must fail loudly
                from ..util import glog
                glog.V(0).infof("native read plane unavailable: %s", e)
                self.fast_plane = None
        # delta-heartbeat state: last volume set acked, and by whom
        self._hb_acked_master = None
        self._hb_acked_volumes = None
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name="volume-heartbeat")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.server.start()
        try:
            self.heartbeat_once()
        except HttpError as e:
            # no master reachable yet — serve anyway; the heartbeat
            # loop keeps retrying (reference volume servers outlive
            # master outages the same way)
            from ..util import glog
            glog.V(0).infof("initial heartbeat failed: %s", e)
        self._hb_thread.start()
        self.scrub.start()
        return self

    def stop(self):
        self._stop.set()
        self._hb_wake.set()
        self.scrub.stop()
        try:
            # clean shutdown: tell the master now so watch subscribers
            # reroute immediately instead of after heartbeat expiry
            post_json(f"http://{self.master_url}/cluster/goodbye",
                      {"url": self.url}, timeout=2)
        except Exception:  # noqa: BLE001 - master may already be gone
            pass
        if self.fast_plane is not None:
            self.fast_plane.stop()
        push = getattr(self, "_metrics_push", None)
        if push is not None:
            push.stop_event.set()
        self.server.stop()
        self.store.close()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def fast_url(self) -> str:
        return f"{self.host}:{self.fast_plane.port}" \
            if self.fast_plane else ""

    # -- native-plane index mirror + write lease ---------------------------
    def _writer_acquire(self, v):
        """Hand the volume's write lease to the native plane (caller
        holds v.lock; the mirror must have just been registered from
        the CURRENT needle map). Only volumes whose plain-POST shape
        the plane can serve exactly get a lease: unreplicated,
        un-TTL'd, v2/v3, no JWT — everything else keeps the round-3
        Python write path with best-effort mirror updates."""
        if self.fast_plane is None or v.fast_writer is not None:
            return
        if v.readonly or v.version < 2 or self.jwt_signing_key or \
                v.super_block.ttl.to_uint32() or \
                v.super_block.replica_placement.copy_count != 1:
            return
        v.fast_writer = self.fast_plane.enable_writer(
            v, self.file_size_limit, accept_posts=True)

    def _writer_release(self, v, reload: bool = True):
        """Take the write lease back. The C++ disable is a mutex
        barrier — after it returns no native append is in flight — so
        the needle map can be reloaded from the .idx the plane kept
        authoritative and Python-owned appends can resume."""
        if self.fast_plane is None:
            return
        with v.lock:
            if v.fast_writer is None:
                return
            v.fast_writer = None
            self.fast_plane.disable_writer(v.id)
            if reload:
                v.reload_nm()

    def _fast_put(self, vid: int, nid: int):
        if self.fast_plane is None:
            return
        v = self.store.find_volume(vid)
        if v is None or v.fast_writer is not None:
            # in writer mode the append already updated the mirror
            return
        nv = v.nm.get(nid)
        if nv is not None:
            self.fast_plane.put(vid, nid, nv.offset, nv.size)

    def _fast_delete(self, vid: int, nid: int):
        if self.fast_plane is None:
            return
        v = self.store.find_volume(vid)
        if v is not None and v.fast_writer is not None:
            return
        self.fast_plane.delete(vid, nid)

    def _fast_sync(self, vid: int):
        """Re-register a volume after a structural change (create,
        mount, compaction commit, copy, tail-receive, EC decode,
        readonly/replication toggle) or unregister it when it's gone.
        Re-establishes the write lease when the volume qualifies."""
        if self.fast_plane is None:
            return
        v = self.store.find_volume(vid)
        if v is None:
            self.fast_plane.unregister_volume(vid)
            return
        with v.lock:
            self._writer_release(v)  # reloads nm if a lease was out
            self.fast_plane.register_volume(v)
            self._writer_acquire(v)

    def _fast_unregister(self, vid: int):
        if self.fast_plane is None:
            return
        v = self.store.find_volume(vid)
        if v is not None:
            self._writer_release(v)
        self.fast_plane.unregister_volume(vid)

    # -- native-plane EC mirror + slab cache -------------------------------
    def _fast_ec_sync(self, vid: int):
        """Re-register an EC volume's geometry, local shard set and
        .ecx mirror in the plane (or unregister it when it's gone).
        Runs after every mount/unmount/rebuild: the plane must learn a
        rebuilt shard is local BEFORE the stale cached slabs for it are
        invalidated, or a read in the window would re-miss to Python."""
        if self.fast_plane is None:
            return
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            self.fast_plane.unregister_ec_volume(vid)
            return
        try:
            self.fast_plane.register_ec_volume(ev, self.degraded.slab)
        except Exception:  # noqa: BLE001 - mirror is optional
            self.fast_plane.unregister_ec_volume(vid)

    def _fast_ec_delete(self, vid: int, nid: int):
        if self.fast_plane is not None:
            self.fast_plane.ec_delete(vid, nid)

    def _publish_slabs(self, vid: int, sid: int, slabs: dict):
        """DegradedReadEngine on_slabs hook: push freshly reconstructed
        slabs into the plane cache so the next read of these bytes is
        served in-plane with zero redirects."""
        if self.fast_plane is None:
            return
        for idx, data in slabs.items():
            self.fast_plane.cache_put(vid, sid, int(idx), data)

    def _invalidate_reconstructions(self, vid: int, shard_ids):
        """Drop every cached reconstruction of these shards — the
        plane's slab cache AND the engine's LRU — after a mount or
        rebuild made them stale. Ordering matters: re-sync the plane's
        shard set FIRST, then drop the plane's slabs, then the
        engine's. A reader in the window sees either fresh local bytes
        or a miss (redirect to Python, which reconstructs from the
        fresh shards), never stale data."""
        self._fast_ec_sync(vid)
        if self.fast_plane is not None:
            for sid in shard_ids:
                self.fast_plane.cache_invalidate(vid, sid)
        self.degraded.invalidate(vid, shard_ids)

    def _on_ec_mount(self, vid: int, shard_ids):
        """store.on_ec_mount: a (re-)mounted shard must win over every
        cached reconstruction immediately."""
        self._invalidate_reconstructions(vid, shard_ids)

    def _heartbeat_loop(self):
        from ..util import glog
        while True:
            self._hb_wake.wait(self.pulse_seconds)
            self._hb_wake.clear()
            if self._stop.is_set():
                return
            try:
                self.heartbeat_once()
                glog.V(4).infof("heartbeat to %s ok", self.master_url)
            except HttpError as e:
                # heartbeat_once already rotated through every seed
                glog.V(0).infof("no master reachable: %s", e)

    def _heartbeat_payload(self, hb: dict, target: str) -> dict:
        """Full heartbeat, or a volume DELTA against the state the
        target master last acknowledged (reference incremental
        heartbeats, master_grpc_server.go:94-152): unchanged volumes
        stay home, only new/changed/deleted ride the wire."""
        if target != self._hb_acked_master or self._hb_acked_volumes is None:
            return hb
        current = {v["id"]: v for v in hb["volumes"]}
        previous = self._hb_acked_volumes
        delta = dict(hb)
        del delta["volumes"]
        delta["delta"] = True
        delta["new_volumes"] = [v for vid, v in current.items()
                                if previous.get(vid) != v]
        delta["deleted_volumes"] = [vid for vid in previous
                                    if vid not in current]
        return delta

    def _post_heartbeat(self, hb: dict, target: str) -> dict:
        resp = post_json(f"http://{target}/cluster/heartbeat",
                         self._heartbeat_payload(hb, target), timeout=10)
        if resp.get("resync"):
            # the master lost (or never had) our registration: replay
            # the full state immediately
            resp = post_json(f"http://{target}/cluster/heartbeat", hb,
                             timeout=10)
        if not resp.get("not_leader"):
            self._hb_acked_master = target
            self._hb_acked_volumes = {v["id"]: v for v in hb["volumes"]}
        return resp

    def heartbeat_once(self):
        """Heartbeat the current master, trying every seed before
        giving up — startup must not die because the first listed seed
        happens to be the down one."""
        hb = self.store.collect_heartbeat()
        if self.fast_plane is not None:
            hb["fast_url"] = self.fast_url
        last = None
        for _ in range(len(self._seed_masters)):
            try:
                resp = self._post_heartbeat(hb, self.master_url)
                break
            except HttpError as e:
                last = e
                self._seed_i = (self._seed_i + 1) % \
                    len(self._seed_masters)
                self.master_url = self._seed_masters[self._seed_i]
        else:
            raise last
        if resp.get("volume_size_limit"):
            self.volume_size_limit = resp["volume_size_limit"]
        self._maybe_start_metrics_push(resp)
        # follow the leader hint: a follower master does not register
        # us, so re-send the heartbeat there right away
        leader = resp.get("leader")
        if leader and leader != self.master_url:
            self.master_url = leader
            if resp.get("not_leader"):
                resp = self._post_heartbeat(hb, self.master_url)
                if resp.get("volume_size_limit"):
                    self.volume_size_limit = resp["volume_size_limit"]

    def _maybe_start_metrics_push(self, resp: dict):
        """The master broadcasts the push-gateway address and interval
        in heartbeat responses (reference LoopPushingMetric,
        metrics.go:109-137 + master_grpc_server.go:75-77); start one
        push loop when it first appears."""
        addr = resp.get("metrics_address")
        if not addr or getattr(self, "_metrics_push", None) is not None:
            return
        from ..stats.metrics import VOLUME_SERVER_GATHER, start_push_loop
        if "://" not in addr:   # the master broadcasts a bare host:port
            addr = "http://" + addr
        self._metrics_push = start_push_loop(
            VOLUME_SERVER_GATHER, addr,
            job=f"volume_{self.host}_{self.port}",
            interval_s=max(1.0, float(
                resp.get("metrics_interval_seconds", 15) or 15)))

    # -- admin -------------------------------------------------------------
    def stats_disk(self, req: Request):
        """Per-directory disk usage (reference statsDiskHandler,
        volume_server.go:83)."""
        import shutil
        out = []
        for loc in self.store.locations:
            try:
                u = shutil.disk_usage(loc.directory)
                out.append({"dir": loc.directory, "all": u.total,
                            "used": u.used, "free": u.free})
            except OSError as e:
                out.append({"dir": loc.directory, "error": str(e)})
        return {"DiskStatuses": out}

    def stats_memory(self, req: Request):
        from .http_util import process_memory_stats
        return process_memory_stats()

    def status(self, req: Request):
        out = self.store.status()
        out["ec_degraded"] = self.degraded.snapshot()
        out["ec_scrub"] = self.scrub.snapshot()
        if self.fast_plane is not None:
            out["fast_plane"] = {
                "url": self.fast_url,
                "served": self.fast_plane.served,
                "redirected": self.fast_plane.redirected,
            }
        return out

    def query_handler(self, req: Request):
        """S3-Select-ish query over JSON needles (reference Query RPC,
        volume_grpc_query.go:12 + query/json/query_json.go:17). Body:
        {"fids": [...], "sql": "SELECT ... WHERE ..."}; rows stream
        back as JSON lines."""
        import json as _json
        from ..query import QueryError, query_json_lines
        body = _json.loads(req.body or b"{}")
        sql = body.get("sql", "")
        fids = body.get("fids", [])
        if not sql or not fids:
            raise HttpError(400, "need sql and fids")
        limit = int(body.get("limit", 0))
        rows: List[dict] = []
        for fid in fids:
            try:
                vid, key, cookie = parse_file_id(fid)
            except ValueError:
                raise HttpError(400, f"bad fid {fid!r}")
            got = self._read_needle_local(vid, key, cookie, fid)
            try:
                rows.extend(query_json_lines(
                    got.data, sql,
                    limit=(limit - len(rows)) if limit else 0))
            except QueryError as e:
                raise HttpError(400, str(e))
            if limit and len(rows) >= limit:
                break
        out = "\n".join(_json.dumps(r, separators=(",", ":"))
                        for r in rows)
        return Response((out + "\n").encode() if out else b"",
                        content_type="application/jsonl")

    def _read_needle_local(self, vid: int, key: int, cookie: int,
                           fid: str) -> Needle:
        """Needle from a local normal OR ec volume (the query path must
        keep working after ec.encode, like the public read path)."""
        v = self.store.find_volume(vid)
        if v is not None:
            try:
                return self.store.read_needle(
                    vid, Needle(cookie=cookie, id=key))
            except NotFound:
                raise HttpError(404, f"{fid} not found")
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            raise HttpError(404, f"volume {vid} not local")
        from ..ec.ec_volume import EcShardNotFound
        try:
            blob = ev.read_needle_blob(
                key,
                remote_fetch=self._read_shard_from_holders,
                reconstruct_fetch=self._reconstruct_shard_range)
        except KeyError:
            raise HttpError(404, f"{fid} not found") from None
        except EcShardNotFound as e:
            raise HttpError(503, f"ec volume {vid}: {e}") from None
        got = Needle.from_bytes(blob, ev.version)
        if got.id != key:
            # the blob parsed as a VALID needle but not the requested
            # one: the interval assembly went to the wrong place —
            # surface it, never serve another needle's bytes (cookies
            # alone don't disambiguate; they can collide)
            raise HttpError(
                500, f"ec read of {fid} assembled needle {got.id:x}")
        if got.cookie != cookie:
            raise HttpError(404, "cookie mismatch")
        return got

    def ui_handler(self, req: Request):
        """HTML status dashboard (reference volume_server_ui/)."""
        from .status_ui import volume_status_page
        return Response(volume_status_page(self),
                        content_type="text/html; charset=utf-8")

    def metrics_handler(self, req: Request):
        """Prometheus text exposition; volume/disk gauges refresh from
        the store on scrape (the reference sets them during heartbeat
        collection, store.go:232)."""
        from ..stats.metrics import (FAST_PLANE_COUNTER,
                                     VOLUME_COUNT_GAUGE,
                                     VOLUME_DISK_GAUGE,
                                     VOLUME_SERVER_GATHER)
        # aggregate across ALL locations before setting, and zero out
        # series for collections that disappeared so a scrape never
        # shows one directory's numbers or a stale collection
        by_coll: Dict[str, list] = {}
        ec_by_coll: Dict[str, int] = {}
        for loc in self.store.locations:
            for v in loc.volumes.values():
                agg = by_coll.setdefault(v.collection, [0, 0])
                agg[0] += 1
                agg[1] += v.size()
            for ev in loc.ec_volumes.values():
                ec_by_coll[ev.collection] = \
                    ec_by_coll.get(ev.collection, 0) + len(ev.shards)
        seen_count, seen_disk = set(), set()
        for coll, (count, size) in by_coll.items():
            VOLUME_COUNT_GAUGE.set(count, coll, "normal")
            VOLUME_DISK_GAUGE.set(size, coll, "normal")
            seen_count.add((coll, "normal"))
            seen_disk.add((coll, "normal"))
        for coll, count in ec_by_coll.items():
            VOLUME_COUNT_GAUGE.set(count, coll, "ec")
            seen_count.add((coll, "ec"))
        # zero each gauge's own vanished series — never mint a series
        # in a gauge that never carried it
        for stale in getattr(self, "_count_series", set()) - seen_count:
            VOLUME_COUNT_GAUGE.set(0, *stale)
        for stale in getattr(self, "_disk_series", set()) - seen_disk:
            VOLUME_DISK_GAUGE.set(0, *stale)
        self._count_series = seen_count
        self._disk_series = seen_disk
        if self.fast_plane is not None:
            FAST_PLANE_COUNTER.set_total(self.fast_plane.served, "served")
            FAST_PLANE_COUNTER.set_total(self.fast_plane.redirected,
                                         "redirected")
            FAST_PLANE_COUNTER.set_total(self.fast_plane.written,
                                         "written")
        # native-plane telemetry (in-plane counters + latency buckets,
        # mirrored so /cluster/metrics sums them fleet-wide)
        from . import native_plane as _np
        from ..stats.metrics import observe_plane
        if self.fast_plane is not None:
            observe_plane(self.fast_plane.stats(),
                          len(self.fast_plane.slow_requests()),
                          _np.build_failed())
        else:
            observe_plane(None, 0, _np.build_failed())
        # in-plane degraded serving + slab-cache counters (same mirror
        # pattern; None when the plane is off or predates the cache ABI)
        from ..stats.metrics import observe_plane_cache
        observe_plane_cache(self.fast_plane.cache_stats()
                            if self.fast_plane is not None else None)
        # group-commit durability counters (same mirror pattern; None
        # when the plane is off or predates the durability ABI)
        from ..stats.metrics import observe_plane_sync
        observe_plane_sync(self.fast_plane.sync_stats()
                           if self.fast_plane is not None else None)
        # device-codec telemetry (process-global monotonic counters)
        # mirrors onto the scrape so dispatches / bitmat uploads / host
        # fallbacks are visible without running a rebuild through bench
        from ..ops import telemetry
        from ..stats.metrics import (DEVICE_TELEMETRY_COUNTER,
                                     HTTP_POOL_CHURN_COUNTER)
        for kind, total in telemetry.STATS.snapshot().items():
            # the per-device mesh byte map exports via its own labeled
            # family (observe_mesh), not the flat kind counter
            if isinstance(total, (int, float)):
                DEVICE_TELEMETRY_COUNTER.set_total(total, kind)
        # connection-pool churn (process-global, same mirror pattern)
        from .http_util import pool_stats_snapshot
        for event, total in pool_stats_snapshot().items():
            HTTP_POOL_CHURN_COUNTER.set_total(total, event)
        # device-runtime plane: compile/recompile accounting, sampled
        # device time, const-cache + jit-factory occupancy. The
        # inventory is only exported when jax is already initialized —
        # a scrape must never be the thing that boots a backend.
        from ..ops import device_stats as _ds
        from ..stats.metrics import observe_device_stats
        observe_device_stats(_ds.DEVICE_STATS.snapshot(),
                             _ds.jit_factory_snapshot(),
                             _ds.device_inventory())
        # EC plan caches (repair/piggyback schemes, process-global
        # LRUs in ops/codec) — same monotonic mirror pattern
        from ..stats.metrics import observe_plan_cache
        observe_plan_cache()
        # degraded-read engine counters (engine-global, same mirror
        # pattern; the per-read latency histogram streams in live via
        # the engine's on_read hook)
        from ..stats.metrics import observe_degraded, observe_scrub
        observe_degraded(self.degraded.snapshot())
        # integrity-scrub engine counters (same mirror pattern)
        observe_scrub(self.scrub.snapshot())
        # per-holder health scoreboard (process-global EWMAs fed by the
        # gather/repair/degraded readers) — fresh scores on every scrape
        # so the master's aggregator and /cluster/health see them
        from ..stats.health import export_board
        export_board()
        return Response(VOLUME_SERVER_GATHER.render().encode(),
                        content_type="text/plain; version=0.0.4")

    def admin_plane_slow(self, req: Request):
        """Newest-first contents of the native plane's slow-request ring
        (requests that took >= SW_PLANE_SLOW_US, bounded at 64 entries)
        plus the stats snapshot the ring indexes into."""
        if self.fast_plane is None:
            return {"plane": False, "slow": []}
        return {"plane": True,
                "slow": self.fast_plane.slow_requests(),
                "stats": self.fast_plane.stats()}

    def admin_devices(self, req: Request):
        """Device-runtime snapshot (ops/device_stats): per-entry-point
        compile/recompile/dispatch counters with the latched recompile
        sentinel, sampled device seconds, jit-factory cache_info,
        const-cache occupancy, and the device inventory incl.
        memory_stats(). Forces backend init — this endpoint exists to
        answer questions about devices."""
        from ..ops import device_stats as _ds
        return _ds.admin_snapshot()

    def admin_plane_cache(self, req: Request):
        """Native-plane reconstructed-slab cache counters + EC serving
        outcomes (swhp_cache_stats), for the degraded fast-path debug
        loop: did the read hit the plane cache or redirect to Python?"""
        if self.fast_plane is None:
            return {"plane": False, "cache": None}
        return {"plane": True, "cache": self.fast_plane.cache_stats()}

    def admin_plane_durability(self, req: Request):
        """Group-commit durability config + telemetry (swhp_sync_stats):
        mode/window/rider-cap, batches vs riders (the amortization
        ratio), fsync µs histogram, pending-queue depth, and failures —
        a failure means a batch poisoned and its writer fail-stopped."""
        if self.fast_plane is None:
            return {"plane": False, "durability": None}
        return {"plane": True,
                "durability": self.fast_plane.sync_stats()}

    def admin_assign_volume(self, req: Request):
        vid = int(req.query["volume"])
        self.store.add_volume(vid, req.query.get("collection", ""),
                              req.query.get("replication", "000"),
                              req.query.get("ttl", ""))
        self._fast_sync(vid)
        self.heartbeat_once()
        return {"volume": vid}

    def admin_delete_volume(self, req: Request):
        vid = int(req.query["volume"])
        # plane offline BEFORE the unlink: a fast-path POST landing in
        # the gap would append to a deleted inode and ack a lost write
        self._fast_unregister(vid)
        if not self.store.delete_volume(vid):
            self._fast_sync(vid)   # nothing deleted; resume serving
            raise HttpError(404, f"volume {vid} not found")
        self._lookup_cache.pop(vid, None)
        self.heartbeat_once()
        return {"deleted": vid}

    def admin_readonly(self, req: Request):
        vid = int(req.query["volume"])
        readonly = req.query.get("readonly", "true") == "true"
        was = self.store.mark_volume_readonly(vid, readonly)
        if was is None:
            raise HttpError(404, f"volume {vid} not found")
        # was_readonly lets orchestrators (volume.copy/move/tier.upload
        # freeze) restore exactly the prior state instead of trusting
        # the master's heartbeat-delayed view
        if was != readonly:
            # the write lease follows writability: frozen volumes hand
            # it back (EC encode reads the .idx next), thawed ones may
            # re-qualify
            self._fast_sync(vid)
        return {"volume": vid, "readonly": readonly,
                "was_readonly": was}

    def admin_configure_replication(self, req: Request):
        """Rewrite a volume's replica placement in its superblock
        (reference volume_grpc_admin.go VolumeConfigure)."""
        from ..storage.types import ReplicaPlacement
        vid = int(req.query["volume"])
        try:
            rp = ReplicaPlacement.parse(req.query.get("replication", ""))
        except (ValueError, KeyError) as e:
            raise HttpError(400, f"bad replication: {e}") from None
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        from ..storage.backend import BackendError
        try:
            v.configure_replication(rp)
        except (VolumeError, BackendError) as e:
            raise HttpError(409, str(e)) from None
        # the lease's no-replica qualification may have flipped
        self._fast_sync(vid)
        return {"volume": vid, "replication": str(rp)}

    def admin_volume_mount(self, req: Request):
        """Load an on-disk volume into serving (reference
        volume_grpc_admin.go VolumeMount)."""
        vid = int(req.query["volume"])
        if self.store.find_volume(vid) is not None:
            return {"volume": vid, "mounted": False}  # already serving
        for loc in self.store.locations:
            if loc.load_volume(vid) is not None:
                self._fast_sync(vid)
                self.heartbeat_once()
                return {"volume": vid, "mounted": True}
        raise HttpError(404, f"volume {vid} files not found")

    def admin_volume_unmount(self, req: Request):
        """Stop serving a volume without deleting its files (reference
        VolumeUnmount)."""
        vid = int(req.query["volume"])
        if self.store.find_volume(vid) is not None:
            # plane offline BEFORE the unload: the fast path must not
            # keep acking writes to an officially unmounted volume
            self._fast_unregister(vid)
        for loc in self.store.locations:
            if loc.unload_volume(vid):
                self.heartbeat_once()
                return {"volume": vid, "unmounted": True}
        self._fast_sync(vid)   # nothing unloaded; resume serving
        raise HttpError(404, f"volume {vid} not mounted")

    def admin_vacuum_check(self, req: Request):
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        return {"volume": vid, "garbage": v.garbage_level()}

    def admin_vacuum_compact(self, req: Request):
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        # per-request override, else the server's configured rate
        bps = int(req.query.get("bytesPerSecond",
                                self.compaction_bps) or 0)
        # hand the write lease back first: compact() snapshots the
        # needle map, which is frozen while the native plane owns the
        # tail — the release reloads it from the authoritative .idx.
        # Writes during the copy go through the (slower) Python path
        # and are replayed by commit's makeup diff.
        self._writer_release(v)
        v.compact(bytes_per_second=bps)
        return {"volume": vid, "compacted": True}

    def admin_vacuum_commit(self, req: Request):
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        # the commit swaps .dat/.idx under the volume: take the plane
        # offline for this vid first so it can't serve old offsets
        # against the new file, then re-sync from the fresh needle map
        self._fast_unregister(vid)
        v.commit_compact()
        self._fast_sync(vid)
        return {"volume": vid, "committed": True}

    # -- EC admin (reference volume_grpc_erasure_coding.go) ----------------
    def admin_ec_generate(self, req: Request):
        """Encode a readonly volume into shard files. Query-only = the
        legacy local flow (all k+m shards land on this disk). When the
        POST body carries ``assignment`` ({shard: holder url}), the
        streaming encode+spread runs instead: each shard's slab ranges
        are pushed to its holder while later slabs encode, and shards
        bound for remote holders never touch this disk."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        try:
            body = req.json()
        except ValueError:
            raise HttpError(400, "bad JSON body") from None
        if isinstance(body, dict) and body.get("assignment"):
            from ..stats.metrics import observe_mesh, observe_spread
            from ..util import tracing
            stats: dict = {}
            base, final = self.store.generate_ec_shards_streaming(
                vid, collection,
                assignment={int(s): u
                            for s, u in body["assignment"].items()},
                spares=body.get("spares") or [],
                window=int(body.get("window") or 0) or None,
                stats=stats,
                rate_mbps=float(body.get("rate_mbps") or 0.0))
            observe_spread(stats)
            observe_mesh(stats)
            return {"volume": vid, "base": os.path.basename(base),
                    "assignment": {str(s): u for s, u in final.items()},
                    "stats": stats,
                    "trace_id": tracing.current_trace_id()}
        base = self.store.generate_ec_shards(vid, collection)
        return {"volume": vid, "base": os.path.basename(base)}

    def _ec_stage_base(self, vid: int, collection: str) -> str:
        """Base path for incoming shard stages: the location already
        holding this volume's EC files if any (staged ranges, finalized
        shards and the later sidecar copy must all land at ONE base or
        the mount won't see them), else a free location."""
        exts = [to_ext(s) for s in range(TOTAL_SHARDS)] + [".ecx"]
        for loc in self.store.locations:
            base = volume_file_prefix(loc.directory, collection, vid)
            if any(os.path.exists(base + e) or
                   os.path.exists(base + e + ".part") for e in exts):
                return base
        loc = self.store.find_free_location()
        if loc is None:
            raise HttpError(507, "no free disk location")
        return volume_file_prefix(loc.directory, collection, vid)

    def admin_ec_shard_write(self, req: Request):
        """Receive one shard's ranges from a streaming encode+spread
        (ec/spread.py): chunked POSTs append at the expected offset into
        ``<shard>.part`` (409 carries the staged size on a mismatch, so
        a sender that lost an ack can tell delivered from diverged);
        ``action=finalize&size=`` verifies the stage and atomically
        renames it into place; ``action=abort`` drops the stages —
        failures never leave partial shard files."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        action = req.query.get("action", "append")
        if action == "abort":
            req.drain()
            removed = []
            for loc in self.store.locations:
                base = volume_file_prefix(loc.directory, collection, vid)
                for sid in range(TOTAL_SHARDS):
                    p = base + to_ext(sid) + ".part"
                    if os.path.exists(p):
                        os.remove(p)
                        removed.append(sid)
            return {"volume": vid, "aborted": removed}
        sid = int(req.query["shard"])
        base = self._ec_stage_base(vid, collection)
        part = base + to_ext(sid) + ".part"
        if action == "finalize":
            req.drain()
            size = int(req.query["size"])
            if not os.path.exists(part):
                raise HttpError(404, f"no staged shard {sid} for "
                                     f"volume {vid}")
            staged = os.path.getsize(part)
            if staged != size:
                raise HttpError(409, f"shard {sid} staged={staged} "
                                     f"expected={size}")
            os.replace(part, base + to_ext(sid))
            return {"volume": vid, "shard": sid, "size": size,
                    "finalized": True}
        off = int(req.query.get("offset", "0"))
        staged = os.path.getsize(part) if os.path.exists(part) else 0
        if off != staged and off != 0:
            # consume the (window-bounded) body so the sender can read
            # this response off a cleanly framed connection — a sender
            # that lost an ack needs the staged size to tell delivered
            # from diverged
            _ = req.body
            raise HttpError(409, f"shard {sid} offset mismatch: "
                                 f"staged={staged} offset={off}")
        data = req.body
        # offset 0 truncates: a replayed first range (failover to this
        # node, or a retry whose original died mid-body) starts clean
        with open(part, "wb" if off == 0 else "ab") as f:
            f.write(data)
            staged = f.tell()
        return {"volume": vid, "shard": sid, "staged": staged}

    def admin_ec_mount(self, req: Request):
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        shard_ids = [int(s) for s in req.query.get("shards", "").split(",")
                     if s != ""]
        mounted = self.store.mount_ec_shards(vid, collection, shard_ids)
        if not mounted and shard_ids:
            # distinguish "already mounted" from "files not found" so a
            # wrong/omitted collection fails loudly instead of no-opping
            ev = self.store.find_ec_volume(vid)
            if ev is None or not set(shard_ids) & set(ev.shards):
                raise HttpError(
                    404, f"no shard files for volume {vid} "
                         f"collection={collection!r} here")
        self.heartbeat_once()
        return {"volume": vid, "mounted": mounted}

    def admin_ec_unmount(self, req: Request):
        vid = int(req.query["volume"])
        shard_ids = [int(s) for s in req.query.get("shards", "").split(",")
                     if s != ""]
        out = self.store.unmount_ec_shards(vid, shard_ids)
        self._fast_ec_sync(vid)  # the plane must stop preading those fds
        self.heartbeat_once()
        return {"volume": vid, "unmounted": out}

    def admin_ec_rebuild(self, req: Request):
        """Local rebuild from whole shard files (legacy, query-only), or
        — when the POST body carries ``sources`` ({shard: [holders]}) —
        the streaming striped gather: survivor ranges are pulled and
        decoded in overlapped slabs, never landing whole on disk."""
        from ..stats.metrics import (observe_gather, observe_mesh,
                                     observe_repair)
        from ..util import tracing
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        try:
            body = req.json()
        except ValueError:
            raise HttpError(400, "bad JSON body") from None
        stats: dict = {}
        if isinstance(body, dict) and body.get("sources"):
            hedge_ms = body.get("hedge_ms")
            rebuilt = self.store.rebuild_ec_shards_streaming(
                vid, collection, sources=body["sources"], stats=stats,
                slab=int(body.get("slab") or 0) or None,
                window=int(body.get("window") or 0) or None,
                hedge_ms=float(hedge_ms) if hedge_ms is not None
                else None,
                repair=str(body.get("repair") or "auto"))
            observe_gather(stats)
            observe_repair(stats)
            observe_mesh(stats)
        else:
            rebuilt = self.store.rebuild_ec_shards(
                vid, collection, stats=stats)
        if rebuilt:
            # rebuilt shards serve from disk now; cached reconstructions
            # of them (engine LRU + plane slabs) are dead weight
            self._invalidate_reconstructions(vid, rebuilt)
        return {"volume": vid, "rebuilt": rebuilt, "stats": stats,
                "trace_id": tracing.current_trace_id()}

    def admin_ec_scrub(self, req: Request):
        """Trigger a synchronous scrub: one volume (?volume=) or a full
        pass over every local EC volume. Manual triggers bypass the
        lowest-shard ownership election — an operator asking this
        server to scrub means this server."""
        vid = req.query.get("volume")
        if vid is not None:
            return self.scrub.scrub_volume(int(vid), force=True)
        return self.scrub.run_pass(force=True)

    def admin_ec_scrub_status(self, req: Request):
        return self.scrub.snapshot()

    def admin_ec_scrub_repair(self, req: Request):
        """Quarantine + rebuild one corrupt shard: drop the poisoned
        file so it cannot serve reads or feed a decode, then stream a
        fresh copy from the surviving k. Driven by the master's repair
        queue when a scrub finding names this holder."""
        from ..stats.metrics import (observe_gather, observe_mesh,
                                     observe_repair)
        from ..util import tracing
        vid = int(req.query["volume"])
        sid = int(req.query["shard"])
        collection = req.query.get("collection", "")
        try:
            body = req.json()
        except ValueError:
            raise HttpError(400, "bad JSON body") from None
        body = body if isinstance(body, dict) else {}
        self.store.unmount_ec_shards(vid, [sid])
        # the plane must drop its fd on the poisoned shard file NOW —
        # an open fd would keep serving the quarantined bytes
        self._fast_ec_sync(vid)
        for loc in self.store.locations:
            base = volume_file_prefix(loc.directory, collection, vid)
            for p in (base + to_ext(sid), base + to_ext(sid) + ".part"):
                if os.path.exists(p):
                    os.remove(p)
        sources = body.get("sources") or self._ec_shard_locations(vid)
        sources = {int(s): [u for u in urls if u != self.url]
                   for s, urls in (sources or {}).items()
                   if int(s) != sid}
        stats: dict = {}
        rebuilt = self.store.rebuild_ec_shards_streaming(
            vid, collection, sources=sources, stats=stats,
            repair=str(body.get("repair") or "auto"))
        observe_gather(stats)
        observe_repair(stats)
        observe_mesh(stats)
        mounted = self.store.mount_ec_shards(vid, collection, rebuilt) \
            if rebuilt else []
        self._invalidate_reconstructions(vid, rebuilt or [sid])
        self.heartbeat_once()
        return {"volume": vid, "shard": sid, "rebuilt": rebuilt,
                "mounted": mounted, "stats": stats,
                "trace_id": tracing.current_trace_id()}

    def _report_scrub_finding(self, finding: dict) -> bool:
        """Push a scrub corruption finding to the master's repair
        queue; True only on an acknowledged report (the engine counts
        failures and the finding stays visible in its snapshot)."""
        try:
            post_json(f"http://{self.master_url}/cluster/scrub_report",
                      finding, timeout=5)
            return True
        except Exception:  # noqa: BLE001 - master may be down
            return False

    def admin_ec_copy(self, req: Request):
        """Pull shard files from a source server (reference
        VolumeEcShardsCopy: the target pulls via CopyFile stream)."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        source = req.query["source"]
        shard_ids = [int(s) for s in req.query.get("shards", "").split(",")
                     if s != ""]
        copy_ecx = req.query.get("copy_ecx", "true") == "true"
        # land next to any EC files this volume already has here (a
        # streamed spread may have staged shards on this server; the
        # sidecar pull must join them at the same base for the mount)
        base = self._ec_stage_base(vid, collection)
        name = os.path.basename(base)
        exts = [to_ext(s) for s in shard_ids]
        optional = []
        if copy_ecx:
            exts.append(".ecx")
            # .vif (volume version + offset width) is written by every
            # encode but can be legitimately gone (operator tooling,
            # pre-fix deployments where deleting the original volume
            # wiped it); .ecj exists only after EC deletes. A 404 on
            # either must not fail the copy — but ONLY a 404: any other
            # status (503 network blip) must propagate, or a silently
            # skipped .vif turns into a wrong offset-width guess on a
            # parity-only holder.
            optional = [".vif", ".ecj"]
        copied = []
        for ext in exts + optional:
            try:
                data = http_call(
                    "GET", f"http://{source}/admin/file?name={name}{ext}",
                    timeout=300)
            except HttpError as e:
                if ext in optional and e.status == 404:
                    continue
                raise
            with open(base + ext, "wb") as f:
                f.write(data)
            copied.append(ext)
        return {"volume": vid, "copied": copied}


    def admin_ec_delete_shards(self, req: Request):
        """Unmount + remove shard files (reference VolumeEcShardsDelete);
        drops .ecx/.ecj/.vif once no shard files remain."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        shard_ids = [int(s) for s in req.query.get("shards", "").split(",")
                     if s != ""]
        self.store.unmount_ec_shards(vid, shard_ids)
        self._fast_ec_sync(vid)
        removed = []
        for loc in self.store.locations:
            base = volume_file_prefix(loc.directory, collection, vid)
            for sid in shard_ids:
                # drop any spread stage alongside the shard — a failed
                # or failed-over stream must not leave .part orphans
                for p in (base + to_ext(sid),
                          base + to_ext(sid) + ".part"):
                    if os.path.exists(p):
                        os.remove(p)
                        if not p.endswith(".part"):
                            removed.append(sid)
            if not any(os.path.exists(base + to_ext(s))
                       for s in range(TOTAL_SHARDS)):
                for ext in (".ecx", ".ecj", ".vif", ".scrub"):
                    if os.path.exists(base + ext):
                        os.remove(base + ext)
        self.heartbeat_once()
        return {"volume": vid, "removed": removed}

    def admin_volume_copy(self, req: Request):
        """Pull a whole volume (.dat/.idx) from a source server and load it
        (reference VolumeCopy: target pulls via CopyFile)."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        source = req.query["source"]
        if self.store.find_volume(vid) is not None:
            raise HttpError(409, f"volume {vid} already here")
        loc = self.store.find_free_location()
        if loc is None:
            raise HttpError(507, "no free disk location")
        base = volume_file_prefix(loc.directory, collection, vid)
        name = os.path.basename(base)
        # .idx before .dat: the .dat is append-only, so an index snapshot
        # taken first can only reference bytes the later .dat snapshot
        # already contains (a torn copy the other way yields index entries
        # past the data end). Extra unindexed .dat tail is harmless.
        for ext in (".idx", ".dat"):
            self._pull_file(source, name + ext, base + ext)
        loc.load_existing_volumes()
        self._fast_sync(vid)
        self.heartbeat_once()
        return {"volume": vid, "copied": True}

    def _pull_file(self, source: str, name: str, dest: str,
                   chunk: int = 64 << 20):
        """Ranged streaming pull — never buffers whole volumes in RAM."""
        stat = get_json(f"http://{source}/admin/file?name={name}&stat=true")
        total = stat["size"]
        with open(dest, "wb") as f:
            off = 0
            while off < total:
                n = min(chunk, total - off)
                data = http_call(
                    "GET", f"http://{source}/admin/file?name={name}"
                           f"&offset={off}&size={n}", timeout=600)
                f.write(data)
                off += len(data)
                if not data:
                    raise HttpError(502, f"short pull of {name} at {off}")

    def admin_volume_verify(self, req: Request):
        """Deep integrity check: walk the volume, CRC-verify every live
        needle against the index (volume.fsck's server side)."""
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        from ..storage.needle import CorruptNeedle
        checked = errors = 0
        from ..storage.compact_map import snapshot_live_items
        with v.lock:
            # offset order: the per-needle reads below then stream the
            # .dat sequentially instead of random-seeking a large volume
            snapshot = snapshot_live_items(v.nm, by_offset=True)
        with snapshot:
            for nid, nv in snapshot:
                checked += 1
                try:
                    # lock per needle, not for the whole scan — a
                    # multi-GB walk must not stall reads/writes on the
                    # volume
                    with v.lock:
                        blob = v._read_blob(nv.offset, nv.size)
                    Needle.from_bytes(blob, v.version,
                                      expected_size=nv.size)
                except (CorruptNeedle, OSError, VolumeError):
                    errors += 1
        return {"volume": vid, "checked": checked, "errors": errors}

    def admin_ec_to_volume(self, req: Request):
        """Decode mounted EC shards back into a normal volume (reference
        VolumeEcShardsToVolume)."""
        from ..ec import decoder as ec_decoder
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            raise HttpError(404, f"ec volume {vid} not mounted")
        if len([s for s in ev.shard_ids() if s < DATA_SHARDS]) < DATA_SHARDS:
            raise HttpError(409, "need all data shards local to decode")
        base = ev.base_name
        dat_size = ec_decoder.find_dat_file_size(base)
        ec_decoder.write_dat_file(base, dat_size)
        ec_decoder.write_idx_file_from_ec_index(base)
        self.store.unmount_ec_shards(vid, list(range(TOTAL_SHARDS)))
        self._fast_ec_sync(vid)  # decoded back to a plain volume
        for loc in self.store.locations:
            if os.path.dirname(base) == loc.directory:
                loc.load_existing_volumes()
        self._fast_sync(vid)
        self.heartbeat_once()
        return {"volume": vid, "dat_size": dat_size}

    def admin_ec_shard_read(self, req: Request):
        """Ranged shard reads for the streaming gather. Two addressing
        forms: ``offset``/``size`` query params (legacy), or a standard
        ``Range: bytes=a-b`` / ``bytes=-N`` header — the header form
        answers 206 with ``Content-Range`` (whose ``/total`` lets the
        rebuilder size a shard via a 1-byte suffix probe)."""
        from .http_util import parse_range
        vid = int(req.query["volume"])
        sid = int(req.query["shard"])
        ev = self.store.find_ec_volume(vid)
        if ev is None or sid not in ev.shards:
            raise HttpError(404, f"shard {vid}.{sid} not here")
        shard = ev.shards[sid]
        total = shard.size
        rng = parse_range(req.headers.get("Range", ""), total)
        if rng is None:
            offset = int(req.query.get("offset", 0))
            size = int(req.query.get("size", 0))
            return Response(shard.read_at(offset, size),
                            headers={"Accept-Ranges": "bytes"})
        offset, length = rng
        if length == 0:
            return Response(b"", headers={"Accept-Ranges": "bytes"})
        return Response(
            shard.read_at(offset, length), status=206,
            headers={
                "Accept-Ranges": "bytes",
                "Content-Range":
                    f"bytes {offset}-{offset + length - 1}/{total}",
            })

    def admin_ec_shard_repair_read(self, req: Request):
        """Projected shard read for single-shard trace repair: read the
        ``offset``/``size`` range of a local shard, apply the caller's
        GF(2^8) trace masks locally (one LUT gather + packbits), and
        return only the packed repair-symbol bit-planes — ``len(masks)``
        planes of ``ceil(size/8)`` bytes each, concatenated. This is
        where the sub-k*slab byte reduction happens: the full range is
        read off disk but never leaves the holder."""
        from ..ops import codec as ops_codec
        vid = int(req.query["volume"])
        sid = int(req.query["shard"])
        ev = self.store.find_ec_volume(vid)
        if ev is None or sid not in ev.shards:
            raise HttpError(404, f"shard {vid}.{sid} not here")
        shard = ev.shards[sid]
        try:
            offset = int(req.query.get("offset", 0))
            size = int(req.query["size"])
            masks = [int(x) for x in req.query["masks"].split(",")]
        except (KeyError, ValueError):
            raise HttpError(400, "need offset/size/masks query params")
        if offset < 0 or size <= 0:
            raise HttpError(400, f"bad range {offset}+{size}")
        if not masks or any(not (0 < x < 256) for x in masks):
            raise HttpError(400, f"masks must be 1..255, got {masks}")
        if offset + size > shard.size:
            raise HttpError(
                416, f"range {offset}+{size} beyond shard size {shard.size}")
        data = np.frombuffer(shard.read_at(offset, size), dtype=np.uint8)
        planes = ops_codec.project_slab(data, masks)
        return Response(
            planes.tobytes(),
            headers={
                "X-Repair-Planes": str(planes.shape[0]),
                "X-Repair-Stride": str(planes.shape[1]),
            })

    def admin_ec_shard_plane_read(self, req: Request):
        """Half-plane shard read for piggyback repair: read the
        window-aligned ``offset``/``size`` range of a local shard and
        return only the sub-chunks of the caller's repair plane
        (ops/codec.pb_plane_slice) — ``size/2`` bytes. This is where
        the (k+1)/2k byte reduction happens: the full range is read off
        disk but only half of it leaves the holder."""
        from ..ops import codec as ops_codec
        vid = int(req.query["volume"])
        sid = int(req.query["shard"])
        ev = self.store.find_ec_volume(vid)
        if ev is None or sid not in ev.shards:
            raise HttpError(404, f"shard {vid}.{sid} not here")
        shard = ev.shards[sid]
        try:
            offset = int(req.query.get("offset", 0))
            size = int(req.query["size"])
            alpha = int(req.query["alpha"])
            window = int(req.query["window"])
            bit = int(req.query["bit"])
            side = int(req.query["side"])
        except (KeyError, ValueError):
            raise HttpError(
                400, "need offset/size/alpha/window/bit/side query params")
        if offset < 0 or size <= 0:
            raise HttpError(400, f"bad range {offset}+{size}")
        if alpha < 2 or alpha & (alpha - 1) or window % alpha:
            raise HttpError(
                400, f"bad sub-chunk geometry alpha={alpha} "
                     f"window={window}")
        if not (0 <= bit < alpha.bit_length() - 1) or side not in (0, 1):
            raise HttpError(400, f"bad plane bit={bit} side={side}")
        if offset % window or size % window:
            raise HttpError(
                400, f"range {offset}+{size} not aligned to "
                     f"window {window}")
        if offset + size > shard.size:
            raise HttpError(
                416, f"range {offset}+{size} beyond shard size {shard.size}")
        data = np.frombuffer(shard.read_at(offset, size), dtype=np.uint8)
        plane = ops_codec.pb_plane_slice(data, alpha, window, bit, side)
        return Response(
            plane.tobytes(),
            headers={
                "X-Plane-Alpha": str(alpha),
                "X-Plane-Window": str(window),
            })

    def admin_tier_upload(self, req: Request):
        """Ship a readonly volume's .dat to a configured backend
        (reference VolumeTierMoveDatToRemote)."""
        from ..storage import volume_tier
        from ..storage.backend import BackendError
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        # plane offline first: once the local .dat is removed its pinned
        # fd would keep serving "local" reads AND hold the inode's disk
        # space — defeating the tiering. The Python server reads via the
        # remote backend from here on.
        self._fast_unregister(vid)
        try:
            info = volume_tier.upload_dat(
                v, req.query["dest"],
                keep_local=req.query.get("keep_local") == "true")
        except (VolumeError, BackendError) as e:
            self._fast_sync(vid)   # nothing moved; resume fast serving
            raise HttpError(400, str(e))
        if req.query.get("keep_local") == "true":
            self._fast_sync(vid)
        self.heartbeat_once()
        return info

    def admin_tier_download(self, req: Request):
        """Bring a remote .dat back to local disk (reference
        VolumeTierMoveDatFromRemote)."""
        from ..storage import volume_tier
        from ..storage.backend import BackendError
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        try:
            out = volume_tier.download_dat(
                v, delete_remote=req.query.get("delete_remote") == "true")
        except (VolumeError, BackendError) as e:
            raise HttpError(400, str(e))
        self._fast_sync(vid)   # fresh local .dat: (re)open + reload
        self.heartbeat_once()
        return out

    def admin_volume_sync_status(self, req: Request):
        """Sync metadata for incremental copy (reference
        volume_server.proto VolumeSyncStatus)."""
        from ..storage import volume_backup
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        try:
            last_ns = volume_backup.last_append_at_ns(v)
        except VolumeError as e:
            raise HttpError(400, str(e))
        return {
            "volume": vid,
            "collection": v.collection,
            "tail_offset": v.size(),
            "compact_revision": v.super_block.compaction_revision,
            "replication": str(v.super_block.replica_placement),
            "ttl": str(v.super_block.ttl),
            "version": v.version,
            "last_append_at_ns": last_ns,
        }

    def admin_volume_tail(self, req: Request):
        """Raw record bytes appended after since_ns (reference
        VolumeIncrementalCopy / VolumeTailSender)."""
        from ..storage import volume_backup
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        since_ns = int(req.query.get("since_ns", 0))
        # always page-capped: a whole-volume delta must not be buffered
        # into one Response body
        max_bytes = int(req.query.get("max_bytes", 0)) \
            or volume_backup.DEFAULT_TAIL_PAGE_BYTES
        try:
            return Response(volume_backup.read_incremental(v, since_ns,
                                                           max_bytes))
        except VolumeError as e:
            raise HttpError(400, str(e))

    def admin_volume_tail_receive(self, req: Request):
        """Apply raw record bytes shipped by a tail sender (reference
        VolumeTailReceiver): follower-side of volume.tail replication."""
        from ..storage import volume_backup
        vid = int(req.query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise HttpError(404, f"volume {vid} not found")
        since = req.query.get("since_ns")
        # raw records land via the volume's own file handles: take the
        # write lease back so the native plane isn't appending the same
        # tail concurrently
        self._writer_release(v)
        try:
            applied, cursor = volume_backup.append_raw_records(
                v, req.body, int(since) if since is not None else None)
        except VolumeError as e:
            self._fast_sync(vid)
            raise HttpError(400, str(e))
        self._fast_sync(vid)
        return {"applied": applied, "cursor_ns": cursor}

    def admin_file(self, req: Request):
        """Serve a raw storage file (EC copy pull path). Restricted to the
        store's own directories and known extensions."""
        name = os.path.basename(req.query.get("name", ""))
        ok_ext = name.endswith((".ecx", ".ecj", ".vif", ".dat", ".idx")) or \
            ".ec" in name
        if not name or not ok_ext:
            raise HttpError(400, "bad file name")
        for loc in self.store.locations:
            path = os.path.join(loc.directory, name)
            if os.path.exists(path):
                if req.query.get("stat"):
                    return {"size": os.path.getsize(path)}
                offset = int(req.query.get("offset", 0))
                size = int(req.query.get("size", 0)) \
                    or os.path.getsize(path) - offset
                return Response(body_path=path, body_range=(offset, size))
        raise HttpError(404, f"{name} not found")

    def _guard_check(self, req: Request):
        """Whitelist applies to every route, admin included (reference
        wraps all handlers in guard.WhiteList). Under mutual TLS the
        admin plane (the reference's gRPC surface) additionally
        demands a CA-verified client certificate; public data routes
        stay server-TLS."""
        if req.path.startswith("/admin/"):
            from .http_util import require_client_cert
            require_client_cert(req)
        if self.guard.enabled and \
                not self.guard.allows(req.handler.client_address[0]):
            raise HttpError(403, "ip not in whitelist")

    # -- data path ---------------------------------------------------------
    def data_handler(self, req: Request):
        if req.path == "/":
            return self.status(req)
        try:
            vid, key, cookie = parse_file_id(req.path.lstrip("/"))
        except ValueError:
            raise HttpError(404, f"invalid fid path {req.path}") from None
        if req.method in ("GET", "HEAD"):
            return self.read_needle(req, vid, key, cookie)
        if req.method in ("POST", "PUT"):
            self._check_write_jwt(req)
            return self.write_needle(req, vid, key, cookie)
        if req.method == "DELETE":
            self._check_write_jwt(req)
            return self.delete_needle(req, vid, key, cookie)
        raise HttpError(405, req.method)

    def _check_write_jwt(self, req: Request):
        """Per-fid write token check (reference
        volume_server_handlers_write.go maybeCheckJwtAuthorization)."""
        if not self.jwt_signing_key:
            return
        from ..security.jwt import (VerifyError, jwt_from_request,
                                    verify_fid_jwt)
        token = jwt_from_request(req.headers, req.query)
        if not token:
            raise HttpError(401, "missing write jwt")
        fid = req.path.lstrip("/")
        try:
            verify_fid_jwt(self.jwt_signing_key, token, fid)
        except VerifyError as e:
            raise HttpError(401, f"jwt rejected: {e}") from None

    def write_needle(self, req: Request, vid, key, cookie):
        # reject oversized uploads BEFORE buffering the body (reference
        # -fileSizeLimitMB); the multipart envelope adds a little, so
        # this is a coarse pre-filter and the post-parse check is exact
        if self.file_size_limit:
            try:
                clen = int(req.headers.get("Content-Length") or 0)
            except ValueError:
                clen = 0
            if clen > self.file_size_limit + 65536:
                raise HttpError(413, "file over the size limit")
        filename, ctype, data = req.upload_payload()
        if self.file_size_limit and len(data) > self.file_size_limit:
            raise HttpError(413, "file over the size limit")
        n = Needle(cookie=cookie, id=key, data=data)
        if filename:
            n.set_name(filename.encode())
        if not ctype:
            # fall back to the filename's extension (reference
            # needle_parse_upload.go keeps only a meaningful mime); an
            # explicit octet-stream is respected — the filer uploads
            # chunk needles that way on purpose
            import mimetypes
            guessed, _ = mimetypes.guess_type(filename or "")
            ctype = guessed or ctype
        if ctype and ctype != "application/octet-stream":
            n.set_mime(ctype.encode())
        # explicit modified-time override (reference
        # needle_parse_upload.go:48 FormValue("ts")); the on-disk field
        # is 5 bytes, so only 0 < ts < 2^40 is honored — anything else
        # falls back to now, like the reference's ParseUint-error path
        ts_raw = req.query.get("ts", "")
        ts_val = int(ts_raw) if ts_raw.isdigit() else 0
        if not 0 < ts_val < 1 << 40:
            ts_val = 0
        n.set_last_modified(ts_val)
        if req.query.get("cm") == "true":
            # payload is a chunk-manifest JSON (reference
            # needle_parse_upload.go: FormValue("cm") sets the flag)
            n.set_is_chunk_manifest()
        # Seaweed-* headers ride with the needle as key/value pairs
        # (reference needle_parse_upload.go parsePairs; the uint16
        # PairsSize field caps them — oversize is an ERROR, silently
        # dropping metadata while returning 200 would lie to the client)
        pairs = {k: v for k, v in req.headers.items()
                 if k.lower().startswith("seaweed-")}
        if pairs:
            import json as _json
            blob = _json.dumps(pairs).encode()
            if len(blob) >= 65536:
                raise HttpError(400, "Seaweed-* pairs exceed 64KB")
            n.set_pairs(blob)
        from ..storage.types import TTL
        ttl = TTL.parse(req.query.get("ttl", ""))
        if ttl.to_uint32():
            n.set_ttl(ttl)
        try:
            self.store.write_needle(vid, n)
            size = len(data)  # reference reports DataSize, not needle Size
        except VolumeError as e:
            raise HttpError(500, str(e)) from None
        self._fast_put(vid, key)
        # synchronous replica fan-out, all-must-succeed (reference
        # store_replicate.go:20-83): attempt every replica, then fail the
        # request if any write is missing so the client knows the needle is
        # under-replicated
        if req.query.get("type") != "replicate":
            from ..security.jwt import jwt_from_request
            from ..util.fanout import fan_out
            from .http_util import post_multipart
            token = jwt_from_request(req.headers, req.query) \
                if self.jwt_signing_key else None
            jwt_q = f"&jwt={token}" if token else ""

            # payload-shaping params must survive the hop: cm marks the
            # manifest flag (a replica missing it would serve raw JSON
            # and never cascade deletes), ttl stamps per-needle expiry,
            # Seaweed-* headers carry the needle's metadata pairs
            extra_q = ""
            if req.query.get("cm") == "true":
                extra_q += "&cm=true"
            if req.query.get("ttl"):
                extra_q += f"&ttl={req.query['ttl']}"
            if ts_val:   # forward only the validated integer form
                extra_q += f"&ts={ts_val}"
            pair_headers = {k: v for k, v in req.headers.items()
                            if k.lower().startswith("seaweed-")} or None

            def replicate(node_url: str):
                post_multipart(
                    f"http://{node_url}{req.path}?type=replicate{jwt_q}"
                    f"{extra_q}",
                    filename, data, ctype or "application/octet-stream",
                    headers=pair_headers)

            failed = [
                f"{node_url}: {exc.message or exc.status}"
                if isinstance(exc, HttpError) else f"{node_url}: {exc}"
                for node_url, _, exc in fan_out(replicate,
                                                self._other_replicas(vid))
                if exc is not None]
            if failed:
                raise HttpError(
                    500, "replication failed on " + "; ".join(failed))
        return {"name": filename, "size": size, "eTag": n.etag}

    def _other_replicas(self, vid: int) -> List[str]:
        # push-updated vid map first (stale-by-at-most-one-pulse;
        # reference vidMap), TTL'd lookup as warm-up/outage fallback
        urls = None
        if self._vid_map is not None:
            urls = self._vid_map.lookup(vid)
        if urls is None:
            cached = self._lookup_cache.get(vid)
            if cached and time.time() - cached[0] < 10:
                urls = cached[1]
            else:
                try:
                    out = get_json(f"http://{self.master_url}/dir/lookup"
                                   f"?volumeId={vid}", timeout=10)
                    urls = [l["url"] for l in out.get("locations", [])]
                except HttpError:
                    urls = []
                self._lookup_cache[vid] = (time.time(), urls)
        return [u for u in urls if u != self.url]

    def read_needle(self, req: Request, vid, key, cookie):
        n = Needle(id=key, cookie=cookie)
        v = self.store.find_volume(vid)
        if v is None:
            ev = self.store.find_ec_volume(vid)
            if ev is not None:
                return self._read_ec_needle(req, ev, vid, key, cookie)
            # not local: redirect to a replica (reference
            # volume_server_handlers_read.go:57-80)
            if self.read_redirect:
                others = self._other_replicas(vid)
                if others:
                    return Response(
                        b"", 301,
                        headers={"Location":
                                 f"http://{others[0]}{req.path}"})
            raise HttpError(404, f"volume {vid} not found")
        try:
            got = self.store.read_needle(vid, n)
        except NotFound as e:
            raise HttpError(404, str(e)) from None
        return self._needle_response(got, req)

    def _needle_response(self, got: Needle,
                         req: Optional[Request] = None) -> Response:
        # chunk-manifest resolution (reference
        # volume_server_handlers_read.go: unless ?cm=false, a flagged
        # needle is resolved to the chunk needles it lists)
        if got.is_chunk_manifest() and (
                req is None or req.query.get("cm") != "false"):
            return self._chunk_manifest_response(got, req)
        ctype = got.mime.decode() if got.has_mime() \
            else "application/octet-stream"
        # Last-Modified + If-Modified-Since (reference
        # volume_server_handlers_read.go:99-109): checked before the
        # etag, like the reference
        lm_header = None
        if got.has_last_modified() and got.last_modified:
            from email.utils import formatdate, parsedate_to_datetime
            lm_header = formatdate(got.last_modified, usegmt=True)
            ims = req.headers.get("If-Modified-Since") \
                if req is not None else None
            if ims:
                try:
                    dt = parsedate_to_datetime(ims)
                    if dt.tzinfo is None:
                        # '-0000' parses naive; it means UTC (RFC5322),
                        # not server-local time
                        from datetime import timezone as _tz
                        dt = dt.replace(tzinfo=_tz.utc)
                    t = dt.timestamp()
                except (TypeError, ValueError):
                    t = None
                if t is not None and t >= got.last_modified:
                    return Response(b"", 304,
                                    headers={"Last-Modified": lm_header,
                                             "Etag": f'"{got.etag}"'})
        # conditional GET (reference volume_server_handlers_read.go
        # If-None-Match vs Etag -> 304): immutable needles make etags
        # exact, so a revalidating client pays zero body bytes.
        # RFC7232: the header is a comma list of (possibly weak)
        # validators, or "*" matching any representation.
        if req is not None:
            inm = (req.headers.get("If-None-Match") or "").strip()
            if inm:
                candidates = {c.strip().removeprefix("W/")
                              for c in inm.split(",")}
                if "*" in candidates or f'"{got.etag}"' in candidates:
                    return Response(b"", 304,
                                    headers={"Etag": f'"{got.etag}"'})
        headers = {"Etag": f'"{got.etag}"',
                   "Accept-Ranges": "bytes"}
        if lm_header:
            headers["Last-Modified"] = lm_header
        if got.has_pairs() and got.pairs:
            # stored Seaweed-* pairs come back as response headers
            # (reference volume_server_handlers_read.go SetEtag + pairs)
            import json as _json
            try:
                for pk, pv in _json.loads(got.pairs.decode()).items():
                    headers[pk] = pv
            except (ValueError, AttributeError):
                pass
        if got.has_name():
            # escape quotes/backslashes: the name is uploader-controlled
            # and lands inside a quoted-string header parameter
            name = got.name.decode("utf-8", "replace") \
                .replace("\\", "\\\\").replace('"', '\\"')
            headers["Content-Disposition"] = \
                f'inline; filename="{name}"'
        body = got.data
        # image ops on read (reference volume_server_handlers_read.go
        # resize-on-GET + images/orientation.go) — ONLY on explicit
        # whole-object resize requests. Range reads (the filer's chunk
        # fetch path) must return stored bytes verbatim: re-encoding
        # before slicing would change lengths and corrupt chunked
        # files' etags/content.
        if req is not None and ctype.startswith("image/") and \
                not req.headers.get("Range"):
            width = int(req.query.get("width", 0) or 0)
            height = int(req.query.get("height", 0) or 0)
            if width or height:
                from ..images import fix_orientation, resize_image
                if ctype == "image/jpeg":
                    body = fix_orientation(body, ctype)
                body, ctype = resize_image(
                    body, ctype, width, height,
                    req.query.get("mode", ""))
        # single-range requests (reference volume_server_handlers_read.go
        # processRangeRequest): the filer fetches chunk slices this way
        from .http_util import parse_range
        rng = req.headers.get("Range") if req is not None else None
        total = len(body)
        parsed = parse_range(rng or "", total)
        if parsed is not None:
            start, length = parsed
            headers["Content-Range"] = \
                f"bytes {start}-{start + length - 1}/{total}"
            return Response(body[start:start + length], 206, ctype,
                            headers)
        return Response(body, 200, ctype, headers)

    def _chunk_manifest_response(self, got: Needle,
                                 req: Optional[Request]) -> Response:
        """Assemble a chunked file window for the reader (reference
        chunked_file.go ChunkedFileReader): chunk slices are fetched in
        parallel with sub-range requests (a 16-byte Range read moves 16
        bytes, not whole chunks), routed through the push-updated vid
        map instead of per-chunk master lookups. A full GET of a file
        bigger than RAM should go through the filer's streaming path;
        like every raw-needle response here, this one is buffered."""
        from ..client.chunked import ChunkManifest
        from ..util.fanout import fan_out
        from .http_util import parse_range
        manifest = ChunkManifest.from_json(got.data)
        ctype = manifest.mime or "application/octet-stream"
        headers = {"Accept-Ranges": "bytes"}
        if manifest.name:
            headers["Content-Disposition"] = \
                f'inline; filename="{manifest.name}"'
        rng = req.headers.get("Range") if req is not None else None
        parsed = parse_range(rng or "", manifest.size)
        want_start, want_len = (parsed if parsed is not None
                                else (0, manifest.size))
        jobs = []
        for c in manifest.chunks:
            lo = max(c.offset, want_start)
            hi = min(c.offset + c.size, want_start + want_len)
            if lo < hi:
                jobs.append((c, lo, hi))

        def fetch(job):
            c, lo, hi = job
            return self._fetch_fid_range(c.fid, lo - c.offset,
                                         hi - lo)

        out = bytearray(want_len)
        for (c, lo, hi), seg, exc in fan_out(fetch, jobs, dedicated=True):
            if exc is not None:
                raise HttpError(
                    502, f"chunk {c.fid} unavailable: {exc}")
            out[lo - want_start:lo - want_start + len(seg)] = seg
        if parsed is not None:
            headers["Content-Range"] = (
                f"bytes {want_start}-{want_start + want_len - 1}"
                f"/{manifest.size}")
            return Response(bytes(out), 206, ctype, headers)
        return Response(bytes(out), 200, ctype, headers)

    def _fetch_fid_range(self, fid: str, offset: int, size: int) -> bytes:
        """Range-read one fid from whichever server holds it, using the
        push-updated vid map (fallback: lookup) for routing."""
        from ..storage.types import parse_file_id
        vid, _, _ = parse_file_id(fid)
        urls = self._vid_map.lookup(vid) if self._vid_map else None
        if not urls:
            from ..client.operation import lookup
            urls = lookup(self.master_url, vid)
        headers = {"Range": f"bytes={offset}-{offset + size - 1}"}
        last = None
        for u in urls:
            try:
                return http_call("GET", f"http://{u}/{fid}",
                                 headers=headers)
            except HttpError as e:
                last = e
        raise last or HttpError(404, f"no locations for {fid}")

    def _cascade_chunk_manifest_delete(self, vid: int, n: Needle):
        """Deleting a manifest deletes its chunk needles first
        (reference volume_server_handlers_write.go DeleteHandler +
        operation.DeleteChunks) — orphaned chunks are unreachable
        garbage otherwise. The flag is probed with two tiny preads so
        ordinary deletes never pay a full payload read."""
        from ..client.chunked import ChunkManifest
        from ..client.operation import delete_file
        from ..storage.needle import FLAG_IS_CHUNK_MANIFEST
        from ..util.fanout import fan_out
        try:
            flags = self.store.read_needle_flags(
                vid, Needle(id=n.id, cookie=n.cookie))
            if not flags & FLAG_IS_CHUNK_MANIFEST:
                return
            got = self.store.read_needle(vid, Needle(id=n.id,
                                                     cookie=n.cookie))
        except (NotFound, VolumeError):
            return
        try:
            manifest = ChunkManifest.from_json(got.data)
        except Exception:  # noqa: BLE001 - corrupt manifest: nothing to do
            return
        fan_out(lambda c: delete_file(self.master_url, c.fid),
                manifest.chunks, dedicated=True)

    # -- EC degraded read (reference store_ec.go:119-373) ------------------
    def _read_ec_needle(self, req: Request, ev, vid, key, cookie):
        got = self._read_needle_local(vid, key, cookie, f"{vid},{key:x}")
        return self._needle_response(got, req)

    def _fetch_ec_shard_locations(self, vid: int) -> Dict[int, List[str]]:
        try:
            out = get_json(f"http://{self.master_url}/cluster/ec_lookup"
                           f"?volumeId={vid}", timeout=10)
            return {int(k): v for k, v in out.get("shards", {}).items()}
        except HttpError:
            return {}

    def _ec_shard_locations(self, vid: int) -> Dict[int, List[str]]:
        """Cached with tiered freshness + invalidate-on-failure
        (reference store_ec.go:218-259); raw master hits only on expiry."""
        return self._ec_loc_cache.lookup(vid)

    def _read_shard_from_holders(self, vid: int, sid: int, offset: int,
                                 size: int) -> Optional[bytes]:
        """Try each cached holder of one shard; forget holders that fail
        (reference forgetShardId, store_ec.go:211). The per-holder
        budget is SW_EC_DEGRADED_READ_TIMEOUT_S — the old hardcoded 30 s
        let one dead holder eat the whole request deadline — and a
        socket timeout forgets the holder exactly like an HTTP error."""
        from ..ec.degraded import degraded_read_timeout_s
        from ..stats.health import BOARD
        timeout = degraded_read_timeout_s()
        for holder in self._ec_shard_locations(vid).get(sid, []):
            if holder == self.url:
                continue
            t0 = time.perf_counter()
            try:
                data = http_call(
                    "GET",
                    f"http://{holder}/admin/ec/shard_read?volume={vid}"
                    f"&shard={sid}&offset={offset}&size={size}",
                    timeout=timeout)
            except (HttpError, OSError):
                BOARD.record_error(holder, "degraded_read")
                self._ec_loc_cache.forget(vid, sid, holder)
                continue
            BOARD.record_latency(holder, "degraded_read",
                                 time.perf_counter() - t0)
            return data
        return None

    def _reconstruct_shard_range(self, vid, sid, offset, size) -> bytes:
        """Reconstruct-on-read of one lost shard's range (reference
        store_ec.go:329-362). Served by the batched DegradedReadEngine
        — coalesced fused-dispatch decode, exactly-k survivor gather,
        slab LRU — unless SW_EC_DEGRADED_MODE=naive selects the
        unbatched per-read path below (kept for A/B benching)."""
        from ..ec.degraded import degraded_mode
        if degraded_mode() == "naive":
            return self._reconstruct_shard_range_naive(
                vid, sid, offset, size)
        return self.degraded.read(vid, sid, offset, size)

    def _reconstruct_shard_range_naive(self, vid, sid, offset,
                                       size) -> bytes:
        """Per-read fallback. Still fixed relative to the original loop:
        fetches only the first-k survivors the decode plan needs (never
        all TOTAL_SHARDS-1 siblings) and decodes only the lost shard's
        row (codec.lost_row_coeffs) instead of regenerating the full
        stripe with codec.reconstruct."""
        from ..util.fanout import fan_out
        ev = self.store.find_ec_volume(vid)
        locations = self._ec_shard_locations(vid)
        codec = self.store.codec or get_codec(DATA_SHARDS, 4)

        present = []
        for other in range(codec.total):
            if other == sid:
                present.append(False)
            elif ev is not None and other in ev.shards:
                present.append(True)
            else:
                present.append(any(h != self.url
                                   for h in locations.get(other, [])))
        if sum(present) < DATA_SHARDS:
            raise HttpError(
                503, f"cannot reconstruct {vid}.{sid}: "
                     f"{sum(present)} shards")
        src, row = codec.lost_row_coeffs(tuple(present), sid)

        def pad(data: bytes) -> np.ndarray:
            if len(data) < size:  # shard tail: zero-pad like local reads
                data = data + b"\x00" * (size - len(data))
            return np.frombuffer(data, dtype=np.uint8)

        rows: List[Optional[np.ndarray]] = [None] * len(src)
        remote = []
        for pos, other in enumerate(src):
            if ev is not None and other in ev.shards:
                rows[pos] = pad(ev.shards[other].read_at(offset, size))
            else:
                remote.append(pos)
        for pos, data, exc in fan_out(
                lambda p: self._read_shard_from_holders(
                    vid, src[p], offset, size), remote, dedicated=True):
            if exc is None and data is not None:
                rows[pos] = pad(data)
        if any(r is None for r in rows):
            have = sum(r is not None for r in rows)
            raise HttpError(
                503, f"cannot reconstruct {vid}.{sid}: {have} of "
                     f"{len(src)} survivors answered")
        from ..ops.codec import host_matmul
        out = host_matmul(row, np.stack(rows, axis=0))
        return out[0].tobytes()

    def _delete_ec_needle(self, req: Request, ev, vid, key):
        """EC delete: tombstone + journal locally, then broadcast to every
        other shard holder (reference store_ec_delete.go:15-110)."""
        found = ev.delete_needle(key)
        if found:
            # mirror the tombstone into the plane's .ecx mirror so the
            # fast path redirects (and Python 404s) instead of serving
            self._fast_ec_delete(vid, key)
        if req.query.get("type") != "replicate":
            from ..security.jwt import jwt_from_request
            from ..util.fanout import fan_out
            token = jwt_from_request(req.headers, req.query) \
                if self.jwt_signing_key else None
            jwt_q = f"&jwt={token}" if token else ""
            notified = {self.url}
            targets = []
            # fresh master lookup, NOT the tiered cache: a holder that
            # mounted shards after the cache filled (ec.balance/rebuild)
            # would otherwise miss the delete and resurrect the needle —
            # the exact failure this broadcast exists to prevent
            locations = self._fetch_ec_shard_locations(vid) or \
                self._ec_shard_locations(vid)
            for holders in locations.values():
                for holder in holders:
                    if holder not in notified:
                        notified.add(holder)
                        targets.append(holder)

            def broadcast(holder: str):
                http_call("DELETE",
                          f"http://{holder}{req.path}?type=replicate"
                          f"{jwt_q}")

            # a holder that misses the delete would silently resurrect the
            # needle on a read redirect — fail loudly like writes do; 404
            # (holder no longer has the volume) is benign
            failed = []
            for holder, _, exc in fan_out(broadcast, targets):
                if exc is None:
                    found = True
                elif not (isinstance(exc, HttpError) and exc.status == 404):
                    failed.append(f"{holder}: {exc}")
            if failed:
                raise HttpError(
                    500, "ec delete replication failed on "
                    + "; ".join(failed))
        if not found:
            raise HttpError(404, f"needle {key} not in ec volume {vid}")
        return {"size": 0}

    def delete_needle(self, req: Request, vid, key, cookie):
        n = Needle(id=key, cookie=cookie)
        v = self.store.find_volume(vid)
        if v is None:
            ev = self.store.find_ec_volume(vid)
            if ev is not None:
                return self._delete_ec_needle(req, ev, vid, key)
            raise HttpError(404, f"volume {vid} not found")
        if req.query.get("type") != "replicate" and \
                req.query.get("cm") != "false":
            self._cascade_chunk_manifest_delete(vid, n)
        try:
            freed = self.store.delete_needle(vid, n)
        except VolumeError as e:
            raise HttpError(500, str(e)) from None
        self._fast_delete(vid, key)
        if req.query.get("type") != "replicate":
            from ..security.jwt import jwt_from_request
            from ..util.fanout import fan_out
            token = jwt_from_request(req.headers, req.query) \
                if self.jwt_signing_key else None
            jwt_q = f"&jwt={token}" if token else ""

            def replicate(node_url: str):
                http_call("DELETE",
                          f"http://{node_url}{req.path}?type=replicate"
                          f"{jwt_q}")

            # deletes must fail-on-any-replica like writes (reference
            # ReplicatedDelete, store_replicate.go): a replica that keeps
            # the needle resurrects it via read redirects. 404 = already
            # gone there, which is the goal state.
            failed = []
            for node_url, _, exc in fan_out(replicate,
                                            self._other_replicas(vid)):
                if exc is not None and not (
                        isinstance(exc, HttpError) and exc.status == 404):
                    failed.append(f"{node_url}: {exc}")
            if failed:
                raise HttpError(
                    500, "delete replication failed on " + "; ".join(failed))
        return {"size": freed}
