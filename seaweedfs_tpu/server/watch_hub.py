"""Master-side volume-location push channel.

The reference holds a gRPC stream per client (KeepConnected,
weed/server/master_grpc_server.go:180-234) and pushes VolumeLocation
new/deleted deltas the moment heartbeats or node death change the
topology; clients fold them into a vidMap (weed/wdclient/vid_map.go).
The HTTP/JSON control plane here uses a long-poll hub instead: clients
GET /cluster/watch?since=<seq> and the master answers immediately with
any newer events, or parks the request until one arrives (or the poll
times out and returns empty — the client just re-polls).

A client whose `since` has fallen off the bounded event buffer (or a
fresh client with since=0) gets a full snapshot with reset=True.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List


class WatchHub:
    def __init__(self, snapshot_fn: Callable[[], Dict[str, List[dict]]],
                 maxlen: int = 8192):
        self._snapshot_fn = snapshot_fn
        self._events: deque = deque(maxlen=maxlen)  # (seq, event dict)
        # the epoch starts at 1 so a just-snapshotted client (since=1)
        # parks on the next poll instead of re-triggering the since=0
        # snapshot path in a hot loop
        self._seq = 1
        self._cond = threading.Condition()

    def publish(self, etype: str, vid: int, url: str, public_url: str = "",
                fast_url: str = ""):
        """Emit one VolumeLocation delta (etype: 'new' | 'deleted')."""
        with self._cond:
            self._seq += 1
            ev = {"type": etype, "vid": vid, "url": url,
                  "publicUrl": public_url or url}
            if fast_url:
                ev["fastUrl"] = fast_url
            self._events.append((self._seq, ev))
            self._cond.notify_all()

    def wait(self, since: int, timeout: float = 20.0) -> dict:
        """Long-poll: events newer than `since`, a reset snapshot when
        `since` predates the buffer OR comes from another hub epoch
        (a restarted/failed-over master has a smaller seq — without the
        reset the client would silently keep its stale map), or {} after
        `timeout` idle."""
        with self._cond:
            oldest = self._events[0][0] if self._events else self._seq + 1
            need_reset = (since == 0 or since < oldest - 1
                          or since > self._seq)
            seq = self._seq
        if need_reset:
            # snapshot OUTSIDE the condition: snapshot_fn takes
            # topology.lock, and topology calls publish() (which takes
            # the condition) while holding that lock — nesting them here
            # is a lock-order inversion that deadlocks the master. The
            # seq captured before the snapshot may lag it; replaying
            # those deltas onto the newer snapshot is harmless because
            # new/deleted are idempotent set ops.
            return {"reset": True, "seq": seq,
                    "locations": self._snapshot_fn()}
        with self._cond:
            if since >= self._seq:
                self._cond.wait(timeout)
            if since >= self._seq:
                return {"seq": self._seq, "events": []}
            if self._events and since < self._events[0][0] - 1:
                need_reset = True  # buffer rolled while we parked
            else:
                return {"seq": self._seq,
                        "events": [e for s, e in self._events if s > since]}
        return {"reset": True, "seq": self._seq,
                "locations": self._snapshot_fn()}
