"""server — master / volume / filer HTTP servers.

The reference speaks HTTP on the public data path and gRPC for control
(SURVEY §5.8); this environment has no gRPC, so control-plane RPCs are
HTTP/JSON under /cluster/* and /admin/* — same message shapes, different
framing. Bulk shard transfer streams over plain HTTP ranges.
"""

from .master import MasterServer  # noqa: F401
from .volume_server import VolumeServer  # noqa: F401
