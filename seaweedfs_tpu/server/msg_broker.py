"""Message broker — topic pub/sub service.

Reference weed/server/msg_broker_grpc_server.go + weed/pb/queue.proto
(SeaweedQueue: ConfigureTopic, Publish, Subscribe, DeleteTopic — stubs
only in the reference). This build implements the same surface as a
working HTTP service: per-topic append logs with long-poll subscribe,
the same LogBuffer machinery the filer event stream uses.
"""

from __future__ import annotations

import base64
import threading
from ..util.locks import make_lock
import time
from typing import Dict

from ..filer.log_buffer import LogBuffer
from .http_util import HttpError, HttpServer, Request, Router


class MsgBrokerServer:
    def __init__(self, port: int = 17777, host: str = "127.0.0.1",
                 max_topics: int = 1024):
        router = Router()
        router.add("GET", "/queue/status", self.status_handler)
        router.add("GET", "/queue/topics", self.topics_handler)
        router.add("POST", "/queue/publish", self.publish_handler)
        router.add("GET", "/queue/subscribe", self.subscribe_handler)
        router.add("POST", "/queue/delete", self.delete_handler)
        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        self.host = host
        self.max_topics = max_topics
        self.topics: Dict[str, LogBuffer] = {}
        self.lock = make_lock("msg_broker.lock")

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop()
        with self.lock:
            for lb in self.topics.values():
                lb.close()
            self.topics.clear()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def _topic(self, name: str, create: bool = True) -> LogBuffer:
        if not name:
            raise HttpError(400, "missing topic")
        with self.lock:
            lb = self.topics.get(name)
            if lb is None:
                if not create:
                    raise HttpError(404, f"topic {name!r} not found")
                if len(self.topics) >= self.max_topics:
                    raise HttpError(429, "too many topics")
                lb = self.topics[name] = LogBuffer(flush_interval=3600)
            return lb

    # -- handlers ----------------------------------------------------------
    def status_handler(self, req: Request):
        with self.lock:
            return {"topics": len(self.topics)}

    def topics_handler(self, req: Request):
        with self.lock:
            return {"topics": sorted(self.topics)}

    def publish_handler(self, req: Request):
        lb = self._topic(req.query.get("topic", ""))
        ts = time.time()
        lb.append({
            "data": base64.b64encode(req.body or b"").decode(),
            "headers": {k[len("x-queue-"):].lower(): v
                        for k, v in req.headers.items()
                        if k.lower().startswith("x-queue-")},
        }, ts=ts)
        return {"position": repr(ts)}

    def subscribe_handler(self, req: Request):
        lb = self._topic(req.query.get("topic", ""), create=False)
        since = float(req.query.get("since", 0) or 0)
        timeout = min(float(req.query.get("timeout", 10) or 10), 55.0)
        events = lb.wait_since(since, timeout=timeout)
        return {"messages": [
            {"ts": t, "data": e["data"], "headers": e.get("headers", {})}
            for t, e in events]}

    def delete_handler(self, req: Request):
        name = req.query.get("topic", "")
        with self.lock:
            lb = self.topics.pop(name, None)
        if lb is None:
            raise HttpError(404, f"topic {name!r} not found")
        lb.close()
        return {"deleted": name}


class QueueClient:
    """Client helper (reference would be the SeaweedQueue stub's
    client side)."""

    def __init__(self, broker_url: str):
        self.url = f"http://{broker_url}"
        self.cursors: Dict[str, float] = {}

    def publish(self, topic: str, data: bytes, **headers):
        from .http_util import http_call
        import urllib.parse
        hdrs = {f"X-Queue-{k}": v for k, v in headers.items()}
        http_call("POST",
                  f"{self.url}/queue/publish?topic="
                  f"{urllib.parse.quote(topic)}", data, hdrs)

    def poll(self, topic: str, timeout: float = 1.0):
        from .http_util import get_json
        import urllib.parse
        since = self.cursors.get(topic, 0.0)
        out = get_json(
            f"{self.url}/queue/subscribe?topic="
            f"{urllib.parse.quote(topic)}&since={since!r}"
            f"&timeout={timeout}", timeout=timeout + 30)
        msgs = out.get("messages", [])
        if msgs:
            self.cursors[topic] = max(m["ts"] for m in msgs)
        return [(base64.b64decode(m["data"]), m.get("headers", {}))
                for m in msgs]
