"""MasterServer — cluster coordinator.

Reference weed/server/master_server.go: HTTP API (/dir/assign, /dir/lookup,
/vol/grow, /vol/vacuum, /col/delete, /submit, status pages) + the heartbeat
channel (HTTP POST here instead of a gRPC stream; same payload). Volume
growth happens on demand under a lock when an Assign finds no writable
volume (reference master_grpc_server_volume.go:43-101).
"""

from __future__ import annotations

import os
import threading
from ..util.locks import make_lock
import time

from ..storage.types import TTL, ReplicaPlacement
from ..util import config, tracing
from ..topology.topology import RaftSequencer, Topology
from ..topology.volume_growth import NoFreeSlots, find_empty_slots
from .http_util import (HttpError, HttpServer, Request, Response,
                        Router, post_json, post_multipart, profile_handler,
                        traces_export_handler, traces_handler)


class MasterServer:
    def __init__(self, port: int = 9333, host: str = "127.0.0.1",
                 volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = None,
                 garbage_threshold: float = 0.3,
                 jwt_signing_key: str = "",
                 peers: str = "", raft_dir: str = "",
                 maintenance_scripts: str = "",
                 maintenance_interval: float = 17 * 60,
                 vacuum_interval: float = 15 * 60,
                 whitelist=(), metrics_address: str = "",
                 metrics_interval: int = 15, sequencer=None,
                 growth_counts: dict = None,
                 maintenance_filer_url: str = ""):
        if pulse_seconds is None:
            pulse_seconds = config.env_float("SW_PULSE_S")
        self.topology = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds, sequencer=sequencer)
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.jwt_signing_key = jwt_signing_key
        self.vg_lock = make_lock("master.vg_lock")
        self.host = host

        router = Router()
        router.add("*", "/dir/assign", self.dir_assign)
        router.add("*", "/dir/lookup", self.dir_lookup)
        router.add("*", "/dir/status", self.dir_status)
        router.add("*", "/vol/grow", self.vol_grow)
        router.add("*", "/vol/status", self.vol_status)
        router.add("*", "/vol/vacuum", self.vol_vacuum)
        router.add("GET", "/stats/health", self.stats_health)
        router.add("GET", "/stats/memory", self.stats_memory)
        router.add("*", "/col/delete", self.col_delete)
        router.add("POST", "/submit", self.submit)
        router.add("POST", "/cluster/heartbeat", self.cluster_heartbeat)
        router.add("POST", "/cluster/goodbye", self.cluster_goodbye)
        router.add("*", "/cluster/status", self.cluster_status)
        router.add("*", "/cluster/ec_lookup", self.ec_lookup)
        router.add("*", "/cluster/ec_status", self.ec_status)
        router.add("*", "/cluster/volumes", self.cluster_volumes)
        router.add("GET", "/cluster/watch", self.cluster_watch)
        router.add("GET", "/metrics", self.metrics_handler)
        router.add("GET", "/cluster/metrics", self.cluster_metrics)
        router.add("GET", "/cluster/health", self.cluster_health)
        router.add("GET", "/cluster/repairs", self.cluster_repairs)
        router.add("GET", "/cluster/tiering", self.cluster_tiering)
        router.add("POST", "/cluster/scrub_report",
                   self.cluster_scrub_report)
        router.add("GET", "/admin/traces", traces_handler)
        router.add("GET", "/admin/traces/export", traces_export_handler)
        router.add("POST", "/admin/profile", profile_handler)
        router.add("GET", "/", self.ui_handler)
        router.add("GET", "/ui", self.ui_handler)
        # GET /<fid> on the master redirects to a holder (reference
        # master_server.go:125 redirectHandler)
        router.set_fallback(self.redirect_handler)
        # ip whitelist on the user-facing surface (reference
        # guard.WhiteList wrapping of master_server.go:112-123); the
        # cluster-internal channels stay open — volume servers and raft
        # peers are not client traffic
        from ..security.guard import Guard
        self.guard = Guard(whitelist)
        router.before = self._guard_check
        # metrics push config broadcast to volume servers via heartbeat
        # responses (reference master_grpc_server.go:75-77)
        self.metrics_address = metrics_address
        self.metrics_interval = int(metrics_interval)
        # volume-location push channel (reference KeepConnected,
        # master_grpc_server.go:180-234): heartbeat deltas and node
        # deaths publish here; clients long-poll /cluster/watch
        from .watch_hub import WatchHub
        self.watch_hub = WatchHub(self._location_snapshot)
        self.topology.location_listener = self.watch_hub.publish
        from ..stats.metrics import (MASTER_REQUEST_COUNTER,
                                     MASTER_REQUEST_HISTOGRAM)

        def observe(label, seconds, ok):
            MASTER_REQUEST_COUNTER.inc(label if ok else label + " error")
            MASTER_REQUEST_HISTOGRAM.observe(
                seconds, label, trace_id=tracing.current_trace_id())
        router.observe = observe
        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        router.node = f"{host}:{self.port}"
        # fleet health plane: scrape every heartbeating node's /metrics
        # on SW_CLUSTER_SCRAPE_S and serve the merged view at
        # /cluster/metrics (+ the per-holder fold at /cluster/health)
        from ..stats.aggregate import ClusterMetricsAggregator
        self.cluster_agg = ClusterMetricsAggregator(self._scrape_targets)
        # integrity plane: scrub findings + topology scans + health
        # signals feed a priority queue that drives repairs and accounts
        # time-to-re-protection (stats/repair_queue.py)
        from ..stats.repair_queue import RepairQueue
        self.repair_queue = RepairQueue()
        # vids whose stripe the scan has seen complete at least once —
        # only those can report lost shards (mid-encode holes are not
        # losses)
        self._repair_seen_complete: set = set()
        self.repair_interval = config.env_float("SW_REPAIR_INTERVAL_S")
        self.at_risk_score = config.env_float("SW_REPAIR_AT_RISK_SCORE")
        self._repair_thread = threading.Thread(
            target=self._repair_loop, daemon=True,
            name="master-repair-queue") \
            if self.repair_interval > 0 else None
        self._pruner = threading.Thread(target=self._prune_loop, daemon=True,
                                        name="master-pruner")
        self._stop = threading.Event()
        # cron'd embedded shell (reference startAdminScripts,
        # master_server.go:187-253): ';'-separated command lines run
        # against this master on an interval, leader-only
        from ..shell.command_env import split_script
        self.maintenance_scripts = split_script(maintenance_scripts)
        self.maintenance_interval = float(maintenance_interval)
        self.maintenance_filer_url = maintenance_filer_url
        # volumes grown per growth event by replica copy count
        # (reference master.toml [master.volume_growth])
        self.growth_counts = dict(growth_counts or {})
        self._maintenance_runs = 0
        self._maintenance_thread = None
        if self.maintenance_scripts:
            self._maintenance_thread = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="master-maintenance")
        # automatic vacuum + TTL expiry (reference
        # Topo.StartRefreshWritableVolumes, master_server.go:128 →
        # topology_vacuum.go:139); 0 disables
        self.vacuum_interval = float(vacuum_interval)
        self._vacuum_thread = threading.Thread(
            target=self._vacuum_loop, daemon=True,
            name="master-vacuum") \
            if self.vacuum_interval > 0 else None
        # hot→warm tiering: leader-gated background demotion of sealed
        # volumes into EC over the shared stripe transport
        # (server/tiering.py); enabled via SW_TIER_ENABLE
        from .tiering import VolumeTierer
        self.tierer = VolumeTierer(self)

        # raft HA (reference weed/server/raft_server.go): multi-master
        # when -peers is set; single-master otherwise (no raft at all)
        self.raft = None
        if peers:
            from ..topology.raft import RaftNode
            peer_list = [p.strip() for p in peers.split(",")
                         if p.strip()]
            if not raft_dir:
                # persistence must never silently vanish: a node that
                # forgets voted_for can grant two votes in one term and
                # elect two leaders (reference defaults -mdir to the OS
                # temp dir the same way)
                import tempfile
                raft_dir = os.path.join(tempfile.gettempdir(),
                                        "weed-tpu-raft")
            # snapshots must capture only COMMITTED state:
            # topology.max_volume_id is bumped optimistically before
            # propose (and rolled back on failure), so it can briefly
            # exceed any committed entry — _raft_committed_max_vid
            # tracks the apply stream instead
            self._raft_committed_max_vid = 0
            # file keys become raft-backed grants so a failover leader
            # can never re-issue an id (the reference reaches for etcd
            # for this, sequence/etcd_sequencer.go; this build already
            # has a consensus log). Installed BEFORE RaftNode so a
            # disk-restored snapshot's sequence_ceiling lands in it;
            # the lambda resolves self.raft lazily for the same reason.
            # An explicitly injected sequencer (e.g. EtcdSequencer,
            # which coordinates across masters on its own) wins.
            if sequencer is None:
                self.topology.sequencer = RaftSequencer(
                    lambda cmd: self.raft.propose(cmd))

            def _snapshot_state():
                state = {"max_volume_id": self._raft_committed_max_vid}
                seq = self.topology.sequencer
                if isinstance(seq, RaftSequencer):
                    state["sequence_ceiling"] = seq.ceiling()
                return state

            def _restore_state(st):
                self._apply_raft(
                    {"type": "max_volume_id",
                     "value": int(st.get("max_volume_id", 0))})
                self._apply_raft(
                    {"type": "sequence_ceiling",
                     "value": int(st.get("sequence_ceiling", 0))})

            self.raft = RaftNode(
                self.url, peer_list, self._apply_raft,
                state_dir=raft_dir,
                snapshot_state_fn=_snapshot_state,
                restore_fn=_restore_state)
            router.add("POST", "/raft/request_vote",
                       self.raft_request_vote)
            router.add("POST", "/raft/append_entries",
                       self.raft_append_entries)
            router.add("POST", "/raft/install_snapshot",
                       self.raft_install_snapshot)
            router.add("GET", "/raft/status", self.raft_status)

    # -- raft glue ---------------------------------------------------------
    def _apply_raft(self, command: dict):
        """Apply a committed raft command (reference
        topology/cluster_commands.go MaxVolumeIdCommand)."""
        if command.get("type") == "max_volume_id":
            value = int(command["value"])
            self._raft_committed_max_vid = max(
                getattr(self, "_raft_committed_max_vid", 0), value)
            with self.topology.lock:
                self.topology.max_volume_id = max(
                    self.topology.max_volume_id, value)
        elif command.get("type") == "sequence_ceiling":
            seq = self.topology.sequencer
            if isinstance(seq, RaftSequencer):
                seq.apply_ceiling(int(command["value"]),
                                  command.get("nonce"))

    def raft_request_vote(self, req: Request):
        return self.raft.handle_request_vote(req.json())

    def raft_append_entries(self, req: Request):
        return self.raft.handle_append_entries(req.json())

    def raft_install_snapshot(self, req: Request):
        return self.raft.handle_install_snapshot(req.json())

    def raft_status(self, req: Request):
        return self.raft.status()

    def is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader

    def leader_url(self) -> str:
        if self.raft is None:
            return self.url
        return self.raft.leader() or ""

    def _leader_forward(self, req: Request):
        """Proxy a request to the raft leader when this master is a
        follower (reference master_server.go proxyToLeader:155-185) —
        followers hold no topology (volume servers heartbeat only to
        the leader), so every data-affecting call must run there.
        Returns None when this node should handle the request itself."""
        if self.is_leader():
            return None
        if req.headers.get("X-Raft-Forwarded"):
            raise HttpError(503, "raft leadership unsettled, retry")
        leader = self.leader_url()
        if not leader:
            raise HttpError(503, "no raft leader elected yet")
        import json as _json
        import urllib.parse
        from .http_util import http_call
        q = urllib.parse.urlencode(req.query)
        url = f"http://{leader}{req.path}" + (f"?{q}" if q else "")
        headers = {"X-Raft-Forwarded": "1"}
        # the payload-shaping headers must survive the hop or a
        # multipart /submit arrives at the leader as opaque bytes
        for h in ("Content-Type", "Authorization"):
            v = req.headers.get(h)
            if v:
                headers[h] = v
        out = http_call(req.method, url, req.body or None, headers)
        return _json.loads(out or b"{}")

    def metrics_handler(self, req: Request):
        from ..stats.metrics import MASTER_GATHER, observe_repair_queue
        from .http_util import Response
        observe_repair_queue(self.repair_queue.snapshot())
        return Response(MASTER_GATHER.render().encode(),
                        content_type="text/plain; version=0.0.4")

    def _scrape_targets(self):
        with self.topology.lock:
            return [n.url for n in self.topology.all_nodes()]

    def cluster_metrics(self, req: Request):
        """Merged cluster exposition: counters/histograms summed across
        nodes, gauges per-node under a node= label. ``?refresh=1``
        forces a synchronous scrape sweep first (tests, impatient
        operators); otherwise the background loop's snapshots serve."""
        if req.query.get("refresh"):
            self.cluster_agg.scrape_once()
        return Response(self.cluster_agg.render().encode(),
                        content_type="text/plain; version=0.0.4")

    def cluster_health(self, req: Request):
        """Per-holder health fold of every node's ec_holder_* families
        (worst observer score wins) + per-node scrape freshness + the
        repair queue's open-incident / time-to-re-protection summary."""
        if req.query.get("refresh"):
            self.cluster_agg.scrape_once()
        out = self.cluster_agg.holder_health()
        out["repairs"] = self.repair_queue.summary()
        return out

    def cluster_repairs(self, req: Request):
        """Integrity-plane view: open incidents by priority, recently
        resolved ones with their time-to-re-protection, and queue
        counters. ``?refresh=1`` runs a topology/health scan first so
        tests and operators see lost shards without waiting a repair
        interval."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        if req.query.get("refresh"):
            self._repair_scan()
        return self.repair_queue.snapshot()

    def cluster_tiering(self, req: Request):
        """Hot→warm lifecycle view: per-volume demotion state
        (candidate → demoting → warm / failed), knob values, and pass
        counters. ``?scan=1`` runs one scan+demote pass synchronously —
        how tests and the bench drive a demotion without waiting a
        tier interval (and without needing SW_TIER_ENABLE's loop)."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        if req.query.get("scan"):
            self.tierer.run_pass()
        return self.tierer.snapshot()

    def cluster_scrub_report(self, req: Request):
        """Scrub corruption findings from volume servers. One incident
        per (volume, corrupt shard); an unattributed finding (locator
        could not pin a shard) opens one incident keyed shard=-1 so the
        exposure is still tracked."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        finding = req.json()
        vid = int(finding.get("volume", 0))
        shards = [int(s) for s in (finding.get("shards") or [])] or [-1]
        detected = finding.get("detected_at")
        opened = []
        for sid in shards:
            inc = self.repair_queue.report(
                "corruption", volume=vid, shard=sid,
                source=str(finding.get("source", "")),
                detail={"slabs": finding.get("slabs"),
                        "columns": finding.get("columns"),
                        "collection": finding.get("collection", "")},
                detected_at=float(detected) if detected else None)
            opened.append(inc.id)
        return {"volume": vid, "incidents": opened}

    def ui_handler(self, req: Request):
        """HTML status dashboard (reference master_ui/templates.go)."""
        from .http_util import Response
        from .status_ui import master_status_page
        return Response(master_status_page(self),
                        content_type="text/html; charset=utf-8")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.server.start()
        self._pruner.start()
        self.cluster_agg.start()
        if self.raft is not None:
            self.raft.start()
        if self._maintenance_thread is not None:
            self._maintenance_thread.start()
        if self._vacuum_thread is not None:
            self._vacuum_thread.start()
        if self._repair_thread is not None:
            self._repair_thread.start()
        self.tierer.start()
        return self

    def stop(self):
        self._stop.set()
        self.cluster_agg.stop()
        if self.raft is not None:
            self.raft.stop()
        self.server.stop()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def _prune_loop(self):
        while not self._stop.wait(self.topology.pulse_seconds):
            self.topology.prune_dead_nodes()

    def _ttl_expired_volumes(self):
        """(vid, [node urls]) for TTL volumes whose content outlived its
        TTL (reference volume.expired() + the vacuum loop's expiry
        sweep). Empty volumes never expire — they are writable targets."""
        out = {}
        now = time.time()
        with self.topology.lock:
            for node in self.topology.all_nodes():
                for vid, vi in node.volumes.items():
                    ttl = TTL.from_uint32(vi.ttl or 0)
                    if ttl.minutes == 0 or vi.size == 0:
                        continue
                    if not vi.modified_at:
                        continue
                    # 10% grace past the TTL like the reference, so a
                    # volume isn't reaped while still serving tail reads
                    if now - vi.modified_at > ttl.minutes * 60 * 1.1:
                        out.setdefault(vid, []).append(node.url)
        return sorted(out.items())

    def _run_vacuum_pass(self, threshold: float = None,
                         reap_ttl: bool = False) -> dict:
        """One vacuum sweep; ``reap_ttl`` additionally deletes
        TTL-expired volumes — only the background loop passes it (a
        manual /vol/vacuum must never have destructive side effects the
        operator didn't ask for)."""
        threshold = threshold if threshold is not None \
            else self.garbage_threshold
        results = []
        for vid, nodes in self.topology.vacuum_candidates(threshold):
            ok = True
            for n in nodes:
                try:
                    post_json(f"http://{n.url}/admin/vacuum/compact"
                              f"?volume={vid}")
                except HttpError:
                    ok = False
                    break
            if ok:
                for n in nodes:
                    try:
                        post_json(f"http://{n.url}/admin/vacuum/commit"
                                  f"?volume={vid}")
                    except HttpError:
                        ok = False
            results.append({"volume": vid, "ok": ok})
        expired = []
        if reap_ttl:
            for vid, urls in self._ttl_expired_volumes():
                # stop assigns FIRST (readonly in every layout) so no
                # fid can be handed out for a volume dying under it —
                # but keep the registration until each replica's delete
                # actually succeeds: a popped-but-undeleted volume would
                # be orphaned forever (delta heartbeats only resend
                # CHANGED volumes, so the master would never relearn it)
                with self.topology.lock:
                    for layout in self.topology.layouts.values():
                        layout.set_volume_readonly(vid, True)
                reaped = []
                for u in urls:
                    try:
                        post_json(f"http://{u}/admin/delete_volume"
                                  f"?volume={vid}")
                    except HttpError:
                        continue  # still registered: retried next pass
                    reaped.append(u)
                    with self.topology.lock:
                        node = self.topology.find_node(u)
                        if node is None:
                            continue
                        node.volumes.pop(vid, None)
                        for layout in self.topology.layouts.values():
                            layout.unregister_volume(vid, node)
                        if self.topology.location_listener is not None:
                            self.topology.location_listener(
                                "deleted", vid, node.url,
                                node.public_url, node.fast_url)
                if reaped:
                    expired.append(vid)
        return {"vacuumed": results, "ttl_expired": expired}

    def _vacuum_loop(self):
        from ..util import glog
        while not self._stop.wait(self.vacuum_interval):
            if not self.is_leader():
                continue
            try:
                out = self._run_vacuum_pass(reap_ttl=True)
                if out["vacuumed"] or out["ttl_expired"]:
                    glog.V(0).infof("auto vacuum: %s", out)
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                glog.V(0).infof("auto vacuum failed: %s", e)

    # -- repair queue drive (integrity plane) ------------------------------
    def _repair_scan(self):
        """Open/close incidents from what the master already knows:
        missing shards in the heartbeat-built topology and holders the
        health fold scores at-risk. Scrub corruption arrives separately
        via /cluster/scrub_report. Idempotent — repeat sightings
        collapse onto the open incident and keep its original
        detection time."""
        from ..ec import TOTAL_SHARDS
        with self.topology.lock:
            shard_map = {vid: [[n.url for n in holders]
                               for holders in per_shard]
                         for vid, per_shard in
                         self.topology.ec_shard_map.items()}
        for vid, per_shard in shard_map.items():
            if not any(per_shard):
                continue  # fully unregistered volume, not a shard loss
            present = sum(1 for holders in per_shard if holders)
            if present == TOTAL_SHARDS:
                self._repair_seen_complete.add(vid)
            # a hole is only a LOSS if the stripe was once whole: a
            # streaming encode registers shards incrementally, and
            # opening incidents mid-spread fires doomed rebuilds at a
            # half-built volume
            if vid not in self._repair_seen_complete:
                continue
            for sid in range(TOTAL_SHARDS):
                holders = per_shard[sid] if sid < len(per_shard) else []
                if holders:
                    self.repair_queue.resolve("lost_shard", volume=vid,
                                              shard=sid, via="remounted")
                else:
                    self.repair_queue.report("lost_shard", volume=vid,
                                             shard=sid, source=self.url)
        # volumes gone from the map entirely: their incidents are moot
        self._repair_seen_complete &= set(shard_map)
        for inc in list(self.repair_queue.snapshot()["open"]):
            if inc["kind"] == "lost_shard" \
                    and inc["volume"] not in shard_map:
                self.repair_queue.resolve("lost_shard",
                                          volume=inc["volume"],
                                          shard=inc["shard"],
                                          via="volume_removed")
        health = self.cluster_agg.holder_health().get("holders", {})
        for holder, h in health.items():
            score = float(h.get("score", 1.0))
            if score < self.at_risk_score:
                self.repair_queue.report(
                    "at_risk_holder", holder=holder, source=self.url,
                    detail={"score": round(score, 3)})
            elif score > self.at_risk_score + 0.1:  # hysteresis
                self.repair_queue.resolve("at_risk_holder",
                                          holder=holder, via="recovered")

    def _repair_loop(self):
        from ..util import glog
        while not self._stop.wait(self.repair_interval):
            if not self.is_leader():
                continue
            try:
                self._repair_scan()
                for _ in range(4):  # bounded drain per tick
                    inc = self.repair_queue.next_incident()
                    if inc is None:
                        break
                    self._drain_one(inc)
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                glog.V(0).infof("repair loop failed: %s", e)

    def _drain_one(self, inc):
        """Drive one incident through the existing repair machinery:
        corruption → the holder quarantines + rebuilds the poisoned
        shard (/admin/ec/scrub_repair); lost shard → a surviving holder
        streams the missing shard back (/admin/ec/rebuild + mount)."""
        from ..util import glog
        vid = inc.volume
        shards = self.topology.lookup_ec_shards(vid) or {}
        collection = self.topology.ec_collections.get(vid, "")
        try:
            if inc.kind == "corruption":
                if inc.shard < 0 or not shards.get(inc.shard):
                    raise RuntimeError(
                        f"no holder for corrupt shard {vid}.{inc.shard}")
                target = shards[inc.shard][0]
                sources = {str(s): [u for u in urls if u != target]
                           for s, urls in shards.items() if s != inc.shard}
                post_json(
                    f"http://{target}/admin/ec/scrub_repair"
                    f"?volume={vid}&shard={inc.shard}"
                    f"&collection={collection}",
                    {"sources": sources}, timeout=300)
                self.repair_queue.resolve("corruption", volume=vid,
                                          shard=inc.shard,
                                          via="scrub_repair")
            elif inc.kind == "lost_shard":
                if not shards:
                    raise RuntimeError(f"no survivors for volume {vid}")
                # rebuild on a node already holding shards of this
                # volume — its local rows never cross the wire
                target = shards[min(shards)][0]
                sources = {str(s): urls for s, urls in shards.items()
                           if target not in urls}
                out = post_json(
                    f"http://{target}/admin/ec/rebuild"
                    f"?volume={vid}&collection={collection}",
                    {"sources": sources}, timeout=300)
                rebuilt = out.get("rebuilt") or []
                if not rebuilt:
                    raise RuntimeError(f"rebuild of {vid} restored "
                                       f"nothing")
                post_json(
                    f"http://{target}/admin/ec/mount?volume={vid}"
                    f"&collection={collection}"
                    f"&shards={','.join(map(str, rebuilt))}", {},
                    timeout=60)
                for sid in rebuilt:
                    self.repair_queue.resolve("lost_shard", volume=vid,
                                              shard=int(sid),
                                              via="rebuild")
        except Exception as e:  # noqa: BLE001 - back off, retry later
            self.repair_queue.attempt_failed(inc, str(e))
            glog.V(0).infof("repair of %s %s.%s failed: %s",
                            inc.kind, vid, inc.shard, e)

    def _maintenance_loop(self):
        """Run the configured shell scripts every interval (leader-only,
        like the reference's masterClient-gated script runner)."""
        import seaweedfs_tpu.shell  # noqa: F401 (registers commands)
        from ..shell.command_env import CommandEnv, run_command
        from ..util import glog
        while not self._stop.wait(self.maintenance_interval):
            if not self.is_leader():
                continue
            env = CommandEnv(self.url,
                             filer_url=self.maintenance_filer_url)
            # unattended cron: one wedged volume server must not stall
            # the loop for the interactive shell's 3600s admin budget
            env.admin_timeout = 900.0
            for line in self.maintenance_scripts:
                try:
                    run_command(env, line)
                except Exception as e:  # noqa: BLE001 - keep the cron alive
                    glog.V(0).infof("maintenance %r failed: %s", line, e)
            self._maintenance_runs += 1

    # -- handlers ----------------------------------------------------------
    def cluster_heartbeat(self, req: Request):
        # volume servers must register with the LEADER only (reference
        # master_grpc_server.go: topology lives on the leader; followers
        # hand back the leader address and the client re-targets)
        if not self.is_leader():
            return {"volume_size_limit":
                    self.topology.volume_size_limit,
                    "leader": self.leader_url(),
                    "not_leader": True}
        hb = req.json()
        ec_shards = {int(k): v
                     for k, v in (hb.get("ec_shards") or {}).items()}
        ec_collections = {int(k): v
                          for k, v in
                          (hb.get("ec_collections") or {}).items()}
        if hb.get("delta"):
            # incremental heartbeat (reference master_grpc_server.go:
            # 94-152): only new/changed/deleted volumes ride the wire.
            # An unknown node means we lost its registration (restart,
            # failover) — ask for a full resync instead of guessing.
            applied = self.topology.apply_heartbeat_delta(
                url=f"{hb.get('ip', '127.0.0.1')}:{hb.get('port', 0)}",
                new_volumes=hb.get("new_volumes", []),
                deleted_volumes=[int(v) for v in
                                 hb.get("deleted_volumes", [])],
                ec_shards=ec_shards, ec_collections=ec_collections,
                max_file_key=int(hb.get("max_file_key", 0)))
            if not applied:
                return {"resync": True,
                        "volume_size_limit":
                        self.topology.volume_size_limit,
                        "leader": self.leader_url() or self.url}
        else:
            self.topology.register_heartbeat(
                dc_id=hb.get("data_center", ""),
                rack_id=hb.get("rack", ""),
                ip=hb.get("ip", "127.0.0.1"),
                port=int(hb.get("port", 0)),
                public_url=hb.get("public_url", ""),
                fast_url=hb.get("fast_url", ""),
                max_volume_count=int(hb.get("max_volume_count", 7)),
                volumes=hb.get("volumes", []),
                ec_shards=ec_shards,
                ec_collections=ec_collections,
                max_file_key=int(hb.get("max_file_key", 0)),
            )
        out = {"volume_size_limit": self.topology.volume_size_limit,
               "leader": self.leader_url() or self.url}
        if self.metrics_address:
            # reference master_grpc_server.go:75-77: the master decides
            # where and how often servers push metrics
            out["metrics_address"] = self.metrics_address
            out["metrics_interval_seconds"] = self.metrics_interval
        return out

    def cluster_goodbye(self, req: Request):
        """Clean volume-server shutdown: unregister immediately and push
        the deletions, instead of waiting for heartbeat expiry (the
        reference gets this for free from gRPC stream breakage,
        master_grpc_server.go:24-50). Leader-forwarded like every other
        topology mutation — a goodbye swallowed by a follower would
        leave the dead node routed until expiry."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        url = req.json().get("url", "")
        node = self.topology.find_node(url)
        if node is not None:
            self.topology.unregister_node(node)
        return {"removed": node is not None}

    def dir_assign(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        from ..topology.raft import NotLeaderError
        try:
            return self._dir_assign_local(req)
        except NotLeaderError as e:
            # deposed between the forward check and the sequencer's
            # raft grant: answer like the forward path would — a
            # retriable 503 carrying the new leader
            raise HttpError(
                503, f"leadership changed during assign; leader is "
                     f"{e.leader or 'unknown'}") from None
        except TimeoutError:
            raise HttpError(
                503, "raft commit timed out during assign; retry"
            ) from None

    def _dir_assign_local(self, req: Request):
        count = int(req.query.get("count", 1))
        collection = req.query.get("collection", "")
        replication = req.query.get("replication") \
            or self.default_replication
        ttl = TTL.parse(req.query.get("ttl", ""))
        preferred_dc = req.query.get("dataCenter", "")

        picked = self.topology.pick_for_write(collection, replication, ttl,
                                              count)
        if picked is None:
            with self.vg_lock:
                picked = self.topology.pick_for_write(
                    collection, replication, ttl, count)
                if picked is None:
                    try:
                        self._grow_volumes(collection, replication, ttl,
                                           preferred_dc)
                    except NoFreeSlots as e:
                        raise HttpError(
                            406, f"no free volumes: {e}") from None
                    picked = self.topology.pick_for_write(
                        collection, replication, ttl, count)
        if picked is None:
            raise HttpError(406, "no writable volumes")
        fid, cnt, node, _ = picked
        out = {"fid": fid, "url": node.url,
               "publicUrl": node.public_url, "count": cnt}
        if node.fast_url:
            # the holder's native data plane: plain uploads land there
            # without the Python server in the loop (off-fast-path
            # shapes bounce back via 307, which clients follow)
            out["fastUrl"] = node.fast_url
        if self.jwt_signing_key:
            # hand out a write token bound to this fid (reference
            # master_server_handlers.go + security/jwt.go GenJwt)
            from ..security.jwt import GenJwt
            out["auth"] = GenJwt(self.jwt_signing_key, fid)
        return out

    def _next_volume_id(self) -> int:
        """New volume id — a raft command in HA mode (reference
        Topology.NextVolumeId raising a MaxVolumeIdCommand,
        topology.go:115-122) so a new leader never reissues an id."""
        if self.raft is None:
            return self.topology.next_volume_id()
        with self.topology.lock:
            # bump before proposing: two concurrent Assign/grow requests
            # must read distinct values, not both propose max+1 (the raft
            # apply is max(), so the optimistic local bump converges)
            value = self.topology.max_volume_id + 1
            self.topology.max_volume_id = value
        try:
            self.raft.propose({"type": "max_volume_id", "value": value})
        except Exception:
            # roll back the optimistic bump (only if no later bump landed
            # on top) so a failed propose — e.g. NotLeaderError during a
            # transition — doesn't leave the counter inflated and
            # un-backed by any raft entry
            with self.topology.lock:
                if self.topology.max_volume_id == value:
                    self.topology.max_volume_id = value - 1
            raise
        return value

    def _grow_volumes(self, collection: str, replication: str, ttl: TTL,
                      preferred_dc: str = "", count: int = None):
        rp = ReplicaPlacement.parse(replication)
        # reference growth counts by copy type (volume_growth.go:39-53),
        # overridable via master.toml [master.volume_growth]
        if count is None:
            defaults = {1: 7, 2: 6, 3: 3}
            if rp.copy_count in defaults:
                count = self.growth_counts.get(
                    rp.copy_count, defaults[rp.copy_count])
            else:
                count = self.growth_counts.get("other", 1)
        grown = 0
        for _ in range(count):
            try:
                nodes = find_empty_slots(self.topology, rp, preferred_dc)
            except NoFreeSlots:
                if grown:
                    break
                raise
            vid = self._next_volume_id()
            ok = True
            for n in nodes:
                try:
                    post_json(
                        f"http://{n.url}/admin/assign_volume"
                        f"?volume={vid}&collection={collection}"
                        f"&replication={replication}&ttl={ttl}")
                except HttpError:
                    ok = False
                    break
            if ok:
                grown += 1
        return grown

    def vol_grow(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        collection = req.query.get("collection", "")
        replication = req.query.get("replication") \
            or self.default_replication
        ttl = TTL.parse(req.query.get("ttl", ""))
        count = int(req.query.get("count", 1))
        with self.vg_lock:
            grown = self._grow_volumes(collection, replication, ttl,
                                       req.query.get("dataCenter", ""),
                                       count)
        return {"count": grown}

    def _location_snapshot(self):
        with self.topology.lock:
            out = {}
            for node in self.topology.all_nodes():
                for vid in node.volumes:
                    out.setdefault(str(vid), []).append(
                        {"url": node.url, "publicUrl": node.public_url,
                         **({"fastUrl": node.fast_url}
                            if node.fast_url else {})})
            return out

    def cluster_watch(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        since = int(req.query.get("since", 0))
        timeout = min(float(req.query.get("timeout", 20)), 25.0)
        return self.watch_hub.wait(since, timeout)

    def dir_lookup(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        vid_s = req.query.get("volumeId", "")
        if "," in vid_s:
            vid_s = vid_s.split(",")[0]
        if not vid_s:
            raise HttpError(400, "volumeId required")
        vid = int(vid_s)
        locs = self.topology.lookup(req.query.get("collection", ""), vid)
        if not locs:
            raise HttpError(404, f"volume {vid} not found")
        return {"volumeId": vid_s,
                "locations": [
                    {"url": n.url, "publicUrl": n.public_url,
                     **({"fastUrl": n.fast_url} if n.fast_url else {})}
                    for n in locs]}

    def ec_lookup(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        vid = int(req.query.get("volumeId", 0))
        shards = self.topology.lookup_ec_shards(vid)
        if shards is None:
            raise HttpError(404, f"ec volume {vid} not found")
        return {"volumeId": vid, "shards": shards}

    def ec_status(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        """Full EC shard map: vid -> shard -> holder urls."""
        with self.topology.lock:
            return {"volumes": {
                str(vid): {
                    "collection": self.topology.ec_collections.get(vid, ""),
                    "shards": {str(sid): [n.url for n in holders]
                               for sid, holders in enumerate(per_shard)
                               if holders},
                } for vid, per_shard in self.topology.ec_shard_map.items()}}

    def cluster_volumes(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        """Every volume replica: vid -> [{url, ...volume info}]."""
        out = {}
        with self.topology.lock:
            for node in self.topology.all_nodes():
                for vid, vi in list(node.volumes.items()):
                    d = vi.to_dict()
                    d["url"] = node.url
                    out.setdefault(str(vid), []).append(d)
        return {"volumes": out}

    def dir_status(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        return {"topology": self.topology.to_dict(),
                "volumeSizeLimit": self.topology.volume_size_limit,
                "version": "seaweedfs_tpu 0.1"}

    def _guard_check(self, req: Request):
        # cluster-internal planes demand a CA-verified client cert
        # under mutual TLS (reference tls.go RequireAndVerifyClientCert
        # on every gRPC service; /dir/* and UI stay public like the
        # reference's public HTTP port)
        from .http_util import require_client_cert
        if req.path.startswith(("/cluster/", "/raft/", "/vol/")):
            require_client_cert(req)
        if not self.guard.enabled:
            return
        p = req.path
        # only genuinely server-to-server channels are exempt; watch/
        # volumes/status/ec_lookup serve the same data as the guarded
        # lookups, so cluster nodes (volume servers, filers, gateways)
        # must be included in -whiteList like any other HTTP client
        if p in ("/cluster/heartbeat", "/cluster/goodbye",
                 "/cluster/scrub_report", "/metrics") \
                or p.startswith("/raft/"):
            return
        if not self.guard.allows(req.handler.client_address[0]):
            raise HttpError(403, "ip not in whitelist")

    def vol_status(self, req: Request):
        """Cluster-wide volume map (reference volumeStatusHandler +
        Topology.ToVolumeMap, topology_map.go:30)."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        with self.topology.lock:
            dcs = {}
            total_max = 0
            for dc in self.topology.data_centers.values():
                racks = {}
                for rack in dc.racks.values():
                    racks[rack.id] = {
                        f"{n.ip}:{n.port}":
                            [vi.to_dict() for vi in n.volumes.values()]
                        for n in rack.nodes.values()}
                    total_max += sum(n.max_volume_count
                                     for n in rack.nodes.values())
                dcs[dc.id] = racks
            used = sum(len(n.volumes)
                       for n in self.topology.all_nodes())
        return {"Version": "seaweedfs_tpu 0.1",
                "Volumes": {"Max": total_max,
                            "Free": total_max - used,
                            "DataCenters": dcs}}

    def stats_health(self, req: Request):
        return {"ok": True, "leader": self.is_leader()}

    def stats_memory(self, req: Request):
        """Process memory stats (reference statsMemoryHandler)."""
        from .http_util import process_memory_stats
        return process_memory_stats()

    def redirect_handler(self, req: Request):
        """GET /<fid> → 301 to a random holder, query preserved
        (reference redirectHandler, master_server_handlers_admin.go:101).
        Only fid-shaped paths redirect; anything else is a 404."""
        import random as _random
        from ..storage.types import parse_file_id
        try:
            vid, _, _ = parse_file_id(req.path.lstrip("/"))
        except ValueError:
            raise HttpError(404, f"no such path {req.path}") from None
        q = ("?" + req.raw_query) if req.raw_query else ""
        # followers hold no topology: bounce the client to the leader
        # with the SAME path (a JSON-proxying _leader_forward would eat
        # the 301)
        if not self.is_leader():
            leader = self.leader_url()
            if not leader:
                raise HttpError(503, "no leader")
            return Response(b"", 301, headers={
                "Location": f"http://{leader}{req.path}{q}"})
        locs = self.topology.lookup(req.query.get("collection", ""), vid)
        if not locs:
            raise HttpError(404, f"volume {vid} not found")
        node = _random.choice(locs)
        return Response(b"", 301, headers={
            "Location": f"http://{node.public_url}{req.path}{q}"})

    def cluster_status(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        return {"isLeader": self.is_leader(),
                "leader": self.leader_url() or self.url,
                "peers": self.raft.peers if self.raft else [],
                "nodes": [n.to_dict() for n in self.topology.all_nodes()]}

    def vol_vacuum(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        threshold = float(req.query.get("garbageThreshold",
                                        self.garbage_threshold))
        return self._run_vacuum_pass(threshold)

    def col_delete(self, req: Request):
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        collection = req.query.get("collection", "")
        if not collection:
            raise HttpError(400, "collection required")
        deleted = []
        for node in self.topology.all_nodes():
            for vid, vi in list(node.volumes.items()):
                if vi.collection == collection:
                    try:
                        post_json(f"http://{node.url}/admin/delete_volume"
                                  f"?volume={vid}")
                        deleted.append(vid)
                    except HttpError:
                        pass
        # drop layouts for the collection
        with self.topology.lock:
            for key in [k for k in self.topology.layouts
                        if k[0] == collection]:
                del self.topology.layouts[key]
        return {"deleted": sorted(set(deleted))}

    def submit(self, req: Request):
        """Convenience upload: assign + forward (reference /submit)."""
        fwd = self._leader_forward(req)
        if fwd is not None:
            return fwd
        filename, ctype, data = req.upload_payload()
        assign = self.dir_assign(req)
        headers = {}
        if assign.get("auth"):
            headers["Authorization"] = f"Bearer {assign['auth']}"
        result = post_multipart(
            f"http://{assign['url']}/{assign['fid']}", filename, data,
            ctype or "application/octet-stream", headers=headers)
        return {"fid": assign["fid"], "fileUrl":
                f"{assign['publicUrl']}/{assign['fid']}",
                "size": result.get("size", len(data))}
