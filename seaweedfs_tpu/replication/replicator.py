"""Replicator — route filer events to a sink.

Reference weed/replication/replicator.go:15-60: oldEntry/newEntry
presence decides create vs update vs delete; only events under the
source's watched path prefix replicate, keyed by the path relative to
that prefix.
"""

from __future__ import annotations

from .sink import ReplicationSink
from .source import FilerSource


class Replicator:
    def __init__(self, source: FilerSource, sink: ReplicationSink):
        self.source = source
        self.sink = sink

    def replicate(self, event: dict) -> str:
        """Apply one EventNotification. Returns what was done
        ('create' / 'update' / 'delete' / 'skip')."""
        old = event.get("oldEntry")
        new = event.get("newEntry")
        old_path = old.get("FullPath") if old else None
        new_path = new.get("FullPath") if new else None

        if new is not None and not self.source.matches(new_path):
            new = None
        if old is not None and not self.source.matches(old_path):
            old = None

        if old is None and new is None:
            return "skip"
        if old is None:
            self._with_data(new, lambda data: self.sink.create_entry(
                self.source.relative(new_path), new, data))
            return "create"
        if new is None:
            self.sink.delete_entry(self.source.relative(old_path),
                                   old.get("IsDirectory", False))
            return "delete"
        if old_path == new_path:
            self._with_data(new, lambda data: self.sink.update_entry(
                self.source.relative(new_path), old, new, data))
            return "update"
        # rename: delete at the old key, create at the new
        self.sink.delete_entry(self.source.relative(old_path),
                               old.get("IsDirectory", False))
        self._with_data(new, lambda data: self.sink.create_entry(
            self.source.relative(new_path), new, data))
        return "update"

    def _with_data(self, entry: dict, fn):
        """Run fn with the entry's content as a spooled (fileobj, size)
        — RAM-bounded however large the entry — closing the spool after."""
        if entry.get("IsDirectory"):
            return fn(b"")
        fileobj, size = self.source.open_entry_data(entry)
        try:
            return fn((fileobj, size))
        finally:
            fileobj.close()
