"""Async geo-replication (reference weed/replication/).

Event-sourced: a subscriber follows the source filer's metadata event
log, the Replicator routes each create/update/delete to a sink
(another filer cluster, an S3 bucket, or — stubbed pending SDKs —
GCS/Azure/B2), and the sink fetches chunk bytes from the source cluster
on demand.
"""

from .replicator import Replicator  # noqa: F401
from .sink import (B2Sink, FilerSink, GcsSink,  # noqa: F401
                   ReplicationSink, SinkError, make_sink)
from .source import FilerSource  # noqa: F401
from .sub import EventSubscriber  # noqa: F401
