"""Async geo-replication (reference weed/replication/).

Event-sourced: a subscriber follows the source filer's metadata event
log, the Replicator routes each create/update/delete to a sink —
another filer cluster, any S3-compatible bucket (AWS/GCS-interop/B2),
or Azure Blob via SharedKey REST — and the sink fetches chunk bytes
from the source cluster on demand. All five sinks are real, no SDKs.
"""

from .replicator import Replicator  # noqa: F401
from .sink import (AzureSink, B2Sink, FilerSink, GcsSink,  # noqa: F401
                   ReplicationSink, SinkError, make_sink)
from .source import FilerSource  # noqa: F401
from .sub import EventSubscriber  # noqa: F401
