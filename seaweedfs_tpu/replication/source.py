"""FilerSource — fetch entry bytes from the source cluster.

Reference weed/replication/source/filer_source.go: the event stream
carries metadata only; a sink that needs file content reads the chunks
from the source cluster's volume servers.
"""

from __future__ import annotations

import tempfile

from ..filer.entry import FileChunk
from ..filer.stream import default_fetcher, stream_chunked

# entries at most this big replicate via RAM; larger ones spool to disk
SPOOL_MAX_BYTES = 32 << 20


class FilerSource:
    def __init__(self, filer_url: str, master_url: str,
                 path_prefix: str = "/"):
        self.filer_url = filer_url
        self.master_url = master_url
        self.path_prefix = path_prefix if path_prefix.endswith("/") \
            else path_prefix + "/"
        self._fetch = default_fetcher(master_url)

    def matches(self, path: str) -> bool:
        return path.startswith(self.path_prefix) or \
            path == self.path_prefix.rstrip("/")

    def relative(self, path: str) -> str:
        """Path with the watched prefix stripped (keyed into the sink)."""
        root = self.path_prefix.rstrip("/")
        if path == root:
            return ""
        return path[len(self.path_prefix):] if \
            path.startswith(self.path_prefix) else path.lstrip("/")

    def open_entry_data(self, entry: dict):
        """(fileobj, size) for an entry's content — spooled to disk past
        SPOOL_MAX_BYTES so replicating a volume-sized file cannot OOM
        the replicator. Caller closes the file."""
        chunks = [FileChunk.from_dict(c) for c in entry.get("chunks", [])]
        spool = tempfile.SpooledTemporaryFile(max_size=SPOOL_MAX_BYTES)
        size = stream_chunked(chunks, self._fetch, spool) if chunks else 0
        spool.seek(0)
        return spool, size
