"""Replication sinks (reference weed/replication/sink/).

FilerSink replicates into another filer cluster (the reference's
filersink, the only sink with full fidelity there too); S3Sink writes
objects to any S3-compatible endpoint through the same SigV4 client the
tier backend uses; GCS and B2 ride their S3-interoperability APIs over
the same client; AzureSink speaks the Blob REST API directly with
SharedKey request signing (reference azuresink wraps the
azure-storage-blob SDK; the wire calls here are the same PutBlob /
DeleteBlob).
"""

from __future__ import annotations

import io
import posixpath
from typing import Optional

from ..server.http_util import HttpError, post_multipart_file


class SinkError(Exception):
    """`status` carries the HTTP status when the failure was an HTTP
    response (0 otherwise), so callers can branch on e.g. 404 without
    parsing the message."""

    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = int(status)


def _file_and_size(data):
    """Sinks take bytes (tests, small entries) or a (fileobj, size)
    pair (the replicator's spooled stream)."""
    if isinstance(data, (bytes, bytearray)):
        return io.BytesIO(data), len(data)
    return data


class ReplicationSink:
    kind = "?"

    def create_entry(self, key: str, entry: dict, data: bytes):
        raise NotImplementedError

    def update_entry(self, key: str, old: dict, new: dict, data):
        """Default: replace (reference sinks mostly delete+create).
        Directory updates are metadata-only — a recursive delete here
        would wipe the replicated subtree."""
        if old.get("IsDirectory") and new.get("IsDirectory"):
            self.create_entry(key, new, data)
            return
        self.delete_entry(key, old.get("IsDirectory", False))
        self.create_entry(key, new, data)

    def delete_entry(self, key: str, is_directory: bool):
        raise NotImplementedError


class FilerSink(ReplicationSink):
    """Write entries into a target filer over its public HTTP surface —
    uploads re-chunk on the target cluster, so the two clusters share
    nothing but this sink's HTTP calls."""

    kind = "filer"

    def __init__(self, filer_url: str, target_dir: str = "/"):
        from ..filer.filer_client import FilerClient
        self.filer_url = filer_url
        self.target_dir = "/" + target_dir.strip("/")
        self.client = FilerClient(filer_url)

    def _path(self, key: str) -> str:
        return posixpath.normpath(
            posixpath.join(self.target_dir, key.lstrip("/")))

    def create_entry(self, key: str, entry: dict, data):
        path = self._path(key)
        if entry.get("IsDirectory"):
            self.client.mkdir(path)
            return
        mime = entry.get("Mime") or "application/octet-stream"
        name = posixpath.basename(path) or "file"
        fileobj, size = _file_and_size(data)
        try:
            post_multipart_file(f"http://{self.filer_url}{path}",
                                name, fileobj, size, content_type=mime)
        except HttpError as e:
            raise SinkError(f"filer sink create {path}: {e}") from None

    def delete_entry(self, key: str, is_directory: bool):
        path = self._path(key)
        try:
            self.client.delete_entry(path, recursive=is_directory,
                                     ignore_recursive_error=True)
        except HttpError as e:
            if e.status != 404:
                raise SinkError(
                    f"filer sink delete {path}: {e}") from None


class S3Sink(ReplicationSink):
    """Replicate files as objects into an S3 bucket (reference s3sink)."""

    kind = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 region: str = "us-east-1"):
        from ..storage.backend import S3Backend
        self.s3 = S3Backend("replication", endpoint, bucket,
                            access_key=access_key, secret_key=secret_key,
                            region=region)
        self.directory = directory.strip("/")

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.directory}/{key}" if self.directory else key

    def create_entry(self, key: str, entry: dict, data):
        if entry.get("IsDirectory"):
            return                     # S3 has no directories
        from ..storage.backend import BackendError
        try:
            self.s3._request("PUT", self._key(key), _file_and_size(data))
        except BackendError as e:
            raise SinkError(str(e), status=e.status) from None

    def delete_entry(self, key: str, is_directory: bool):
        if is_directory:
            return
        from ..storage.backend import BackendError
        try:
            self.s3.delete(self._key(key))
        except BackendError as e:
            if e.status != 404:
                raise SinkError(str(e), status=e.status) from None


class GcsSink(S3Sink):
    """Google Cloud Storage via its S3-interoperability XML API
    (storage.googleapis.com speaks SigV4 with HMAC interop keys) — a
    real sink over the same from-scratch S3 client, covering the
    reference's gcssink without the GCS SDK."""

    kind = "gcs"

    def __init__(self, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 region: str = "auto"):
        super().__init__(endpoint, bucket, access_key=access_key,
                         secret_key=secret_key, directory=directory,
                         region=region)


class B2Sink(S3Sink):
    """Backblaze B2 via its S3-compatible API (reference b2sink)."""

    kind = "b2"

    def __init__(self, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 region: str = "us-west-004", endpoint: str = ""):
        endpoint = endpoint or f"https://s3.{region}.backblazeb2.com"
        super().__init__(endpoint, bucket, access_key=access_key,
                         secret_key=secret_key, directory=directory,
                         region=region)


def azure_shared_key_signature(account: str, key_b64: str, method: str,
                               path: str, headers: dict,
                               query: dict) -> str:
    """Azure Storage SharedKey string-to-sign + HMAC (the 2015+ scheme:
    Content-Length is the empty string when 0). `headers` keys must be
    lowercase; `path` is the URL path (/container/blob)."""
    import base64
    import hashlib
    import hmac as _hmac

    length = headers.get("content-length", "")
    if length in ("0", 0):
        length = ""
    canon_headers = "".join(
        f"{k}:{headers[k]}\n" for k in sorted(headers)
        if k.startswith("x-ms-"))
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    sts = "\n".join([
        method.upper(),
        headers.get("content-encoding", ""),
        headers.get("content-language", ""),
        str(length),
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
        headers.get("if-modified-since", ""),
        headers.get("if-match", ""),
        headers.get("if-none-match", ""),
        headers.get("if-unmodified-since", ""),
        headers.get("range", ""),
    ]) + "\n" + canon_headers + canon_resource
    mac = _hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                    hashlib.sha256).digest()
    return base64.b64encode(mac).decode()


class AzureSink(ReplicationSink):
    """Replicate files as block blobs into an Azure Storage container —
    Blob REST API with SharedKey auth, no SDK (reference azuresink's
    CreateBlockBlobFromReader/DeleteBlob, sink/azuresink/azure_sink.go).
    `endpoint` is overridable for Azurite or test doubles."""

    kind = "azure"
    api_version = "2020-10-02"

    def __init__(self, account: str, account_key: str, container: str,
                 directory: str = "", endpoint: str = ""):
        import urllib.parse
        self.account = account
        self.account_key = account_key
        self.container = container
        self.directory = directory.strip("/")
        endpoint = (endpoint.rstrip("/") or
                    f"https://{account}.blob.core.windows.net")
        # split any path prefix out of the endpoint (Azurite uses
        # http://host:port/<account>): the prefix is part of the
        # request path and MUST be part of the signed canonical
        # resource, or every request 403s
        parsed = urllib.parse.urlparse(endpoint)
        self.endpoint = f"{parsed.scheme}://{parsed.netloc}"
        self.path_prefix = parsed.path.rstrip("/")

    def _blob_path(self, key: str) -> str:
        """Full request path (incl. any endpoint prefix) — signed and
        sent identically."""
        import urllib.parse
        key = key.lstrip("/")
        if self.directory:
            key = f"{self.directory}/{key}"
        return (f"{self.path_prefix}/{self.container}/"
                + urllib.parse.quote(key))

    def _request(self, method: str, path: str, body=None,
                 content_type: str = "", blob_type: str = ""):
        import email.utils
        import urllib.request

        body_file = body_len = None
        if isinstance(body, tuple):
            body_file, body_len = body
        elif body is not None:
            body_len = len(body)
        headers = {
            # formatdate, not strftime: RFC1123 day/month names must be
            # English regardless of LC_TIME — the server validates this
            # date as part of SharedKey auth
            "x-ms-date": email.utils.formatdate(usegmt=True),
            "x-ms-version": self.api_version,
        }
        if blob_type:
            headers["x-ms-blob-type"] = blob_type
        if content_type:
            headers["content-type"] = content_type
        if body_len is not None:
            headers["content-length"] = str(body_len)
        sig = azure_shared_key_signature(
            self.account, self.account_key, method, path, headers, {})
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        data = body_file if body_file is not None else body
        req = urllib.request.Request(self.endpoint + path, data=data,
                                     method=method, headers=headers)
        import urllib.error
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:200]
            raise SinkError(
                f"azure {method} {path}: {e.code} {detail}",
                status=e.code) from None
        except (urllib.error.URLError, OSError) as e:
            raise SinkError(f"azure {method} {path}: {e}") from None

    def create_entry(self, key: str, entry: dict, data):
        if entry.get("IsDirectory"):
            return                     # blob storage has no directories
        mime = entry.get("Mime") or "application/octet-stream"
        self._request("PUT", self._blob_path(key), _file_and_size(data),
                      content_type=mime, blob_type="BlockBlob")

    def delete_entry(self, key: str, is_directory: bool):
        if is_directory:
            return
        try:
            self._request("DELETE", self._blob_path(key))
        except SinkError as e:
            if e.status != 404:
                raise


_SINKS = {"filer": FilerSink, "s3": S3Sink, "gcs": GcsSink, "b2": B2Sink,
          "azure": AzureSink}


def make_sink(cfg: dict) -> ReplicationSink:
    """cfg = {"type": "filer", ...kwargs} (reference replication.toml
    [sink.<type>] sections)."""
    kind = cfg.get("type")
    if kind not in _SINKS:
        raise SinkError(f"unknown sink type {kind!r}")
    kwargs = {k: v for k, v in cfg.items() if k != "type"}
    try:
        return _SINKS[kind](**kwargs)
    except TypeError as e:
        # config errors (missing bucket, reference-toml key names this
        # build doesn't take) must surface as SinkError, not TypeError —
        # callers validate configs by catching SinkError
        raise SinkError(f"{kind} sink config: {e}") from None
