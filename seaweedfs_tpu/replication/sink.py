"""Replication sinks (reference weed/replication/sink/).

FilerSink replicates into another filer cluster (the reference's
filersink, the only sink with full fidelity there too); S3Sink writes
objects to any S3-compatible endpoint through the same SigV4 client the
tier backend uses. GCS/Azure/B2 exist for config parity but raise at
construction — their SDKs are not in this build.
"""

from __future__ import annotations

import io
import posixpath
from typing import Optional

from ..server.http_util import HttpError, post_multipart_file


class SinkError(Exception):
    pass


def _file_and_size(data):
    """Sinks take bytes (tests, small entries) or a (fileobj, size)
    pair (the replicator's spooled stream)."""
    if isinstance(data, (bytes, bytearray)):
        return io.BytesIO(data), len(data)
    return data


class ReplicationSink:
    kind = "?"

    def create_entry(self, key: str, entry: dict, data: bytes):
        raise NotImplementedError

    def update_entry(self, key: str, old: dict, new: dict, data):
        """Default: replace (reference sinks mostly delete+create).
        Directory updates are metadata-only — a recursive delete here
        would wipe the replicated subtree."""
        if old.get("IsDirectory") and new.get("IsDirectory"):
            self.create_entry(key, new, data)
            return
        self.delete_entry(key, old.get("IsDirectory", False))
        self.create_entry(key, new, data)

    def delete_entry(self, key: str, is_directory: bool):
        raise NotImplementedError


class FilerSink(ReplicationSink):
    """Write entries into a target filer over its public HTTP surface —
    uploads re-chunk on the target cluster, so the two clusters share
    nothing but this sink's HTTP calls."""

    kind = "filer"

    def __init__(self, filer_url: str, target_dir: str = "/"):
        from ..filer.filer_client import FilerClient
        self.filer_url = filer_url
        self.target_dir = "/" + target_dir.strip("/")
        self.client = FilerClient(filer_url)

    def _path(self, key: str) -> str:
        return posixpath.normpath(
            posixpath.join(self.target_dir, key.lstrip("/")))

    def create_entry(self, key: str, entry: dict, data):
        path = self._path(key)
        if entry.get("IsDirectory"):
            self.client.mkdir(path)
            return
        mime = entry.get("Mime") or "application/octet-stream"
        name = posixpath.basename(path) or "file"
        fileobj, size = _file_and_size(data)
        try:
            post_multipart_file(f"http://{self.filer_url}{path}",
                                name, fileobj, size, content_type=mime)
        except HttpError as e:
            raise SinkError(f"filer sink create {path}: {e}") from None

    def delete_entry(self, key: str, is_directory: bool):
        path = self._path(key)
        try:
            self.client.delete_entry(path, recursive=is_directory,
                                     ignore_recursive_error=True)
        except HttpError as e:
            if e.status != 404:
                raise SinkError(
                    f"filer sink delete {path}: {e}") from None


class S3Sink(ReplicationSink):
    """Replicate files as objects into an S3 bucket (reference s3sink)."""

    kind = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 region: str = "us-east-1"):
        from ..storage.backend import S3Backend
        self.s3 = S3Backend("replication", endpoint, bucket,
                            access_key=access_key, secret_key=secret_key,
                            region=region)
        self.directory = directory.strip("/")

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.directory}/{key}" if self.directory else key

    def create_entry(self, key: str, entry: dict, data):
        if entry.get("IsDirectory"):
            return                     # S3 has no directories
        from ..storage.backend import BackendError
        try:
            self.s3._request("PUT", self._key(key), _file_and_size(data))
        except BackendError as e:
            raise SinkError(str(e)) from None

    def delete_entry(self, key: str, is_directory: bool):
        if is_directory:
            return
        from ..storage.backend import BackendError
        try:
            self.s3.delete(self._key(key))
        except BackendError as e:
            if "404" not in str(e) and "NoSuchKey" not in str(e):
                raise SinkError(str(e)) from None


class GcsSink(S3Sink):
    """Google Cloud Storage via its S3-interoperability XML API
    (storage.googleapis.com speaks SigV4 with HMAC interop keys) — a
    real sink over the same from-scratch S3 client, covering the
    reference's gcssink without the GCS SDK."""

    kind = "gcs"

    def __init__(self, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 region: str = "auto"):
        super().__init__(endpoint, bucket, access_key=access_key,
                         secret_key=secret_key, directory=directory,
                         region=region)


class B2Sink(S3Sink):
    """Backblaze B2 via its S3-compatible API (reference b2sink)."""

    kind = "b2"

    def __init__(self, bucket: str, access_key: str = "",
                 secret_key: str = "", directory: str = "",
                 region: str = "us-west-004", endpoint: str = ""):
        endpoint = endpoint or f"https://s3.{region}.backblazeb2.com"
        super().__init__(endpoint, bucket, access_key=access_key,
                         secret_key=secret_key, directory=directory,
                         region=region)


_SINKS = {"filer": FilerSink, "s3": S3Sink, "gcs": GcsSink, "b2": B2Sink}


def make_sink(cfg: dict) -> ReplicationSink:
    """cfg = {"type": "filer", ...kwargs} (reference replication.toml
    [sink.<type>] sections)."""
    kind = cfg.get("type")
    if kind == "azure":
        # the lone sink with no S3-compatible endpoint; its SDK is not
        # in this build (reference azuresink wraps azure-storage-blob)
        raise SinkError(
            "azure sink requires the Azure Blob SDK, which is not "
            "available in this build; use the filer, s3, gcs or b2 sink")
    if kind not in _SINKS:
        raise SinkError(f"unknown sink type {kind!r}")
    kwargs = {k: v for k, v in cfg.items() if k != "type"}
    try:
        return _SINKS[kind](**kwargs)
    except TypeError as e:
        # config errors (missing bucket, reference-toml key names this
        # build doesn't take) must surface as SinkError, not TypeError —
        # callers validate configs by catching SinkError
        raise SinkError(f"{kind} sink config: {e}") from None
