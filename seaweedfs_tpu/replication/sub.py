"""EventSubscriber — follow a filer's metadata event stream.

Reference `weed watch` / filer_pb.SubscribeMetadata: long-polls the
filer's /filer/events endpoint, yielding (ts, event) in order and
resuming from the last seen timestamp.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Iterator, Tuple

from ..server.http_util import HttpError, get_json
from ..util import config


class EventSubscriber:
    def __init__(self, filer_url: str, since: float = 0.0,
                 poll_timeout: float = 10.0, path_prefix: str = ""):
        self.filer_url = filer_url
        self.since = since
        self.poll_timeout = poll_timeout
        self.path_prefix = path_prefix
        self.stopped = False
        self._batch_cursor = since  # scanned mark of the last poll

    def poll_once(self, advance: bool = True):
        """One long-poll; returns the (possibly empty) event batch. With
        advance=False the cursor stays put — callers that might fail to
        apply the batch (a replicator with its sink down) commit() only
        after the whole batch landed, so nothing is ever skipped."""
        params = {"since": repr(self.since),
                  "timeout": self.poll_timeout}
        if self.path_prefix:
            # server-side filter (reference watch -pathPrefix)
            params["prefix"] = self.path_prefix
        q = urllib.parse.urlencode(params)
        out = get_json(f"http://{self.filer_url}/filer/events?{q}",
                       timeout=self.poll_timeout + 30)
        events = out.get("events", [])
        # the server's scanned high-water mark covers every event it
        # looked at, INCLUDING ones the prefix filter dropped — safe to
        # resume from (dropped events can never concern this watcher).
        # A pre-cursor server omits the field: fall back to the batch's
        # own max ts, NOT self.since (that fallback would never advance
        # and follow() would hot-loop re-fetching the same batch)
        batch_hi = max((e["ts"] for e in events), default=self.since)
        self._batch_cursor = max(self._batch_cursor, batch_hi,
                                 float(out.get("cursor", 0) or 0))
        if advance:
            self.since = max(self.since, self._batch_cursor)
        return events

    def commit(self, events):
        """Advance the cursor past an applied batch (and past whatever
        filtered-out foreign events the server scanned alongside it —
        an advance=False + prefix consumer would otherwise busy-loop
        rescanning them)."""
        hi = max((e["ts"] for e in events), default=self.since)
        self.since = max(self.since, hi, self._batch_cursor)

    def follow(self) -> Iterator[Tuple[float, dict]]:
        """Yield (ts, event) forever (until .stopped is set). Transient
        filer outages back off and resume from the cursor."""
        import time
        while not self.stopped:
            try:
                batch = self.poll_once()
            except HttpError:
                time.sleep(max(0.02, config.retry_backoff_s(1.0)))
                continue
            for e in batch:
                yield e["ts"], e["event"]


def format_event(ts: float, event: dict) -> str:
    """One-line rendering for `weed-tpu watch`."""
    old = event.get("oldEntry")
    new = event.get("newEntry")
    if old and new:
        kind = "update" if old.get("FullPath") == new.get("FullPath") \
            else "rename"
    elif new:
        kind = "create"
    elif old:
        kind = "delete"
    else:
        kind = "noop"
    path = (new or old or {}).get("FullPath", "?")
    extra = ""
    if kind == "rename":
        extra = f" <- {old.get('FullPath')}"
    return f"{ts:.6f} {kind:7s} {path}{extra}"
