"""EventSubscriber — follow a filer's metadata event stream.

Reference `weed watch` / filer_pb.SubscribeMetadata: long-polls the
filer's /filer/events endpoint, yielding (ts, event) in order and
resuming from the last seen timestamp.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Iterator, Tuple

from ..server.http_util import HttpError, get_json


class EventSubscriber:
    def __init__(self, filer_url: str, since: float = 0.0,
                 poll_timeout: float = 10.0):
        self.filer_url = filer_url
        self.since = since
        self.poll_timeout = poll_timeout
        self.stopped = False

    def poll_once(self, advance: bool = True):
        """One long-poll; returns the (possibly empty) event batch. With
        advance=False the cursor stays put — callers that might fail to
        apply the batch (a replicator with its sink down) commit() only
        after the whole batch landed, so nothing is ever skipped."""
        q = urllib.parse.urlencode(
            {"since": repr(self.since), "timeout": self.poll_timeout})
        out = get_json(f"http://{self.filer_url}/filer/events?{q}",
                       timeout=self.poll_timeout + 30)
        events = out.get("events", [])
        if events and advance:
            self.since = max(e["ts"] for e in events)
        return events

    def commit(self, events):
        """Advance the cursor past an applied batch."""
        if events:
            self.since = max(self.since,
                             max(e["ts"] for e in events))

    def follow(self) -> Iterator[Tuple[float, dict]]:
        """Yield (ts, event) forever (until .stopped is set). Transient
        filer outages back off and resume from the cursor."""
        import time
        while not self.stopped:
            try:
                batch = self.poll_once()
            except HttpError:
                time.sleep(1.0)
                continue
            for e in batch:
                yield e["ts"], e["event"]


def format_event(ts: float, event: dict) -> str:
    """One-line rendering for `weed-tpu watch`."""
    old = event.get("oldEntry")
    new = event.get("newEntry")
    if old and new:
        kind = "update" if old.get("FullPath") == new.get("FullPath") \
            else "rename"
    elif new:
        kind = "create"
    elif old:
        kind = "delete"
    else:
        kind = "noop"
    path = (new or old or {}).get("FullPath", "?")
    extra = ""
    if kind == "rename":
        extra = f" <- {old.get('FullPath')}"
    return f"{ts:.6f} {kind:7s} {path}{extra}"
