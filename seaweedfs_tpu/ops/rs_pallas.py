"""Fused Pallas TPU kernel for GF(2^8) Reed-Solomon coding.

The round-2 XLA kernel (ops/rs_tpu.py) materialized the 8x bit-plane
expansion and the 4-byte-per-bit int32 matmul result in HBM around a
skinny matmul — bandwidth-bound on its own temporaries at ~0.3% MXU.
This kernel fuses unpack -> matmul -> pack into one pallas_call so the
only HBM traffic is the uint8 payload in and the uint8 code rows out
((k + r)/k bytes moved per payload byte); the bit-planes and int32
products live and die in VMEM, tile by tile.

Layout trick that keeps the kernel reshape-free: bit-plane rows are
ordered (bit, shard) — row l*k + j is bit l of input shard j — so the
in-kernel expansion is a plain sublane-axis concatenation of the eight
shifted-AND planes, and the pack side slices eight (r, tile) blocks
back out of the (8r, tile) matmul result. The GF(2) lift of the byte
coefficient matrix (ops/gf256.bit_matrix, input rows (shard, bit),
output cols (shard, bit)) is permuted once on the host to match
(fuse_bitmat below).

Exactness: everything is integer — the (8r, 8k) 0/1 matrix times 0/1
planes accumulates in int32 (row sums <= 8k <= 2048), & 1 recovers the
GF(2) sum, and the byte pack is an OR of disjoint bits — so output is
bit-identical to the numpy oracle / native AVX2 path for every matrix
and geometry (tests/test_rs_pallas.py pins this, incl. ragged widths).

Column independence makes grid-edge padding safe: the matmul contracts
over sublanes only, so garbage lanes in a ragged final tile never leak
into valid output columns. Any n >= 1 works without host-side padding.

Replaces the hot loop of reference ec_encoder.go:118-134 (klauspost
AVX2 GF multiply) — same contract, MXU execution.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


def _pl():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return jax, jnp, pl, pltpu


@functools.lru_cache(maxsize=64)
def _fused_bitmat_cached(coeff_bytes: bytes, r: int, k: int) -> np.ndarray:
    coeffs = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(r, k)
    b0 = gf256.bit_matrix(coeffs)  # (k*8, r*8): in row j*8+l, out col i*8+b
    # -> (8r, 8k): out row b*r+i, in col l*k+j  (transposed for the MXU,
    # both axes re-grouped plane-major)
    return np.ascontiguousarray(
        b0.reshape(k, 8, r, 8).transpose(3, 2, 1, 0).reshape(8 * r, 8 * k)
    ).astype(np.int8)


def fuse_bitmat(coeffs: np.ndarray) -> np.ndarray:
    """(r, k) GF(2^8) byte matrix -> (8r, 8k) int8 plane-major GF(2) lift."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    r, k = coeffs.shape
    return _fused_bitmat_cached(coeffs.tobytes(), r, k)


def pick_tile(k: int, r: int, n: int, vmem_budget: int = 8 << 20) -> int:
    """Largest lane-tile (multiple of 128, <= 64K) whose working set fits
    the VMEM budget: payload tile (k), 8 planes (8k), int32 products
    (32r), unpacked bits (8r), packed out (r), plus pallas's double
    buffering of the in/out blocks (2(k+r))."""
    per_lane = 9 * k + 41 * r + 2 * (k + r)
    tile = (vmem_budget // per_lane) // 128 * 128
    tile = max(128, min(tile, 64 << 10))
    if n < tile:
        tile = max(128, (n + 127) // 128 * 128)
    return tile


@functools.lru_cache(maxsize=256)
def _fused_fn(k: int, r: int, n: int, tile: int, interpret: bool):
    """Jitted (bitmat (8r, 8k) int8, data (k, n) uint8) -> (r, n) uint8."""
    jax, jnp, pl, pltpu = _pl()

    def kernel(bitmat_ref, data_ref, out_ref):
        data = data_ref[...]  # (k, tile) uint8
        # unpack: eight mask-and-compare planes, stacked plane-major
        # along sublanes -> (8k, tile) in {0,1}. (Mask+compare, not
        # shifts: Mosaic has no uint8 shrui legalization. The masks and
        # the payload view are int8 — bit-identical for bitwise AND,
        # and Mosaic can't materialize uint8 constants.)
        bits = jax.lax.bitcast_convert_type(data, jnp.int8)
        masks = (1, 2, 4, 8, 16, 32, 64, -128)
        x = jnp.concatenate(
            [((bits & jnp.int8(m)) != 0).astype(jnp.int8) for m in masks],
            axis=0)
        # MXU: exact 0/1 arithmetic, int32 accumulation
        y = jax.lax.dot_general(
            bitmat_ref[...], x,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        # pack: bit b of output shard i is row b*r+i; multiply-accumulate
        # in int32 (disjoint bits), downcast once
        acc = y[0:r, :] & 1
        for b in range(1, 8):
            acc = acc + (y[b * r:(b + 1) * r, :] & 1) * (1 << b)
        out_ref[...] = acc.astype(jnp.uint8)

    grid = (n + tile - 1) // tile
    fn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
        interpret=interpret,
    )
    from . import device_stats
    return device_stats.wrap(jax.jit(fn), "rs_pallas._fused_fn")


from . import device_stats as _device_stats  # noqa: E402

_device_stats.register_jit_factory("rs_pallas._fused_fn", _fused_fn)
_device_stats.register_jit_factory("rs_pallas._fused_bitmat_cached",
                                   _fused_bitmat_cached)


def _use_interpret() -> bool:
    """Pallas compiles natively only on TPU; everywhere else (the CPU
    test mesh) the interpreter gives the same bit-exact semantics."""
    from .rs_tpu import on_tpu
    return not on_tpu()


def fused_matmul(coeffs: np.ndarray, data, interpret: bool = None):
    """coeffs (r, k) GF(2^8) x data (k, n) uint8 -> (r, n) uint8 (device
    array). `data` may be a numpy or device array; transfer is implicit."""
    import jax.numpy as jnp
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    r, k = coeffs.shape
    n = data.shape[1]
    if interpret is None:
        interpret = _use_interpret()
    bitmat = jnp.asarray(fuse_bitmat(coeffs))
    fn = _fused_fn(k, r, n, pick_tile(k, r, n), interpret)
    return fn(bitmat, data)


def make_fused_encode_fn(k: int, m: int, n: int,
                         matrix_kind: str = "vandermonde",
                         interpret: bool = None):
    """(jitted fn(bitmat, data (k,n) uint8) -> (m,n) uint8, bitmat (8m,8k)).

    Direct Pallas-path handle with an explicit interpret switch — the
    production entry point is rs_tpu.make_encode_fn / fn_and_bitmat,
    which dispatches here automatically on TPU.
    """
    if interpret is None:
        interpret = _use_interpret()
    matrix = gf256.build_matrix(k, k + m, matrix_kind)
    bitmat = fuse_bitmat(matrix[k:])
    return _fused_fn(k, m, n, pick_tile(k, m, n), interpret), bitmat
