// seaweed_ec — GF(2^8) Reed-Solomon matrix multiply, native CPU path.
//
// Replaces the reference's klauspost/reedsolomon SIMD dependency (reference
// go.mod:47, hot loop ec_encoder.go:118-134): out[i] = XOR_j coeffs[i][j] *
// data[j] over GF(2^8) with polynomial 0x11D.
//
// Algorithm: classic nibble-split table lookup. For a constant c,
// c*b = LO[c][b & 15] ^ HI[c][b >> 4], so the inner loop is two 16-entry
// shuffles + XOR — vectorized with AVX2 _mm256_shuffle_epi8 when available
// (32 bytes/iteration), with a portable scalar fallback.
//
// Exposed C ABI (ctypes from Python, see ops/rs_native.py):
//   void sw_ec_matmul(const uint8_t* coeffs, int r, int k,
//                     const uint8_t* data, long long n, uint8_t* out);

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr int kPoly = 0x11D;

struct Tables {
  // lo[c][x]  = c * x        (x in 0..15)
  // hi[c][x]  = c * (x<<4)
  alignas(32) uint8_t lo[256][16];
  alignas(32) uint8_t hi[256][16];

  Tables() {
    uint8_t mul[256][256];
    uint8_t exp[512];
    int log[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= kPoly;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    log[0] = 0;
    for (int a = 0; a < 256; a++) {
      for (int b = 0; b < 256; b++) {
        mul[a][b] = (a && b)
                        ? exp[log[a] + log[b]]
                        : 0;
      }
    }
    for (int c = 0; c < 256; c++) {
      for (int xn = 0; xn < 16; xn++) {
        lo[c][xn] = mul[c][xn];
        hi[c][xn] = mul[c][xn << 4];
      }
    }
  }
};

const Tables g_tables;

// out[0..n) ^= c * src[0..n)
void mul_xor_row(uint8_t c, const uint8_t* __restrict src, long long n,
                 uint8_t* __restrict dst) {
  if (c == 0) return;
  if (c == 1) {
    long long t = 0;
#if defined(__AVX2__)
    for (; t + 32 <= n; t += 32) {
      __m256i d = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src + t));
      __m256i o = _mm256_loadu_si256(reinterpret_cast<__m256i*>(dst + t));
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + t),
                          _mm256_xor_si256(o, d));
    }
#endif
    for (; t < n; t++) dst[t] ^= src[t];
    return;
  }
  const uint8_t* lo = g_tables.lo[c];
  const uint8_t* hi = g_tables.hi[c];
  long long t = 0;
#if defined(__AVX2__)
  const __m256i vlo = _mm256_broadcastsi128_si256(
      _mm_load_si128(reinterpret_cast<const __m128i*>(lo)));
  const __m256i vhi = _mm256_broadcastsi128_si256(
      _mm_load_si128(reinterpret_cast<const __m128i*>(hi)));
  const __m256i mask = _mm256_set1_epi8(0x0F);
  for (; t + 32 <= n; t += 32) {
    __m256i d = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src + t));
    __m256i dl = _mm256_and_si256(d, mask);
    __m256i dh = _mm256_and_si256(_mm256_srli_epi64(d, 4), mask);
    __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, dl),
                                 _mm256_shuffle_epi8(vhi, dh));
    __m256i o = _mm256_loadu_si256(reinterpret_cast<__m256i*>(dst + t));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + t),
                        _mm256_xor_si256(o, p));
  }
#endif
  for (; t < n; t++) {
    uint8_t d = src[t];
    dst[t] ^= static_cast<uint8_t>(lo[d & 0x0F] ^ hi[d >> 4]);
  }
}

}  // namespace

namespace {

// CRC32-C (Castagnoli), slicing-by-8 — needle checksums (the reference uses
// klauspost/crc32 Castagnoli, weed/storage/needle/crc.go).
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xFF] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

const CrcTables g_crc;

}  // namespace

extern "C" {

uint32_t sw_crc32c(uint32_t crc, const uint8_t* data, long long n) {
  crc = ~crc;
  long long i = 0;
  for (; i + 8 <= n; i += 8) {
    crc ^= static_cast<uint32_t>(data[i]) |
           (static_cast<uint32_t>(data[i + 1]) << 8) |
           (static_cast<uint32_t>(data[i + 2]) << 16) |
           (static_cast<uint32_t>(data[i + 3]) << 24);
    crc = g_crc.t[7][crc & 0xFF] ^ g_crc.t[6][(crc >> 8) & 0xFF] ^
          g_crc.t[5][(crc >> 16) & 0xFF] ^ g_crc.t[4][crc >> 24] ^
          g_crc.t[3][data[i + 4]] ^ g_crc.t[2][data[i + 5]] ^
          g_crc.t[1][data[i + 6]] ^ g_crc.t[0][data[i + 7]];
  }
  for (; i < n; i++) crc = g_crc.t[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

void sw_ec_matmul(const uint8_t* coeffs, int r, int k, const uint8_t* data,
                  long long n, uint8_t* out) {
  for (int i = 0; i < r; i++) {
    uint8_t* dst = out + static_cast<long long>(i) * n;
    for (int j = 0; j < k; j++) {
      mul_xor_row(coeffs[i * k + j], data + static_cast<long long>(j) * n, n,
                  dst);
    }
  }
}

// Multi-threaded variant: the byte range [0, n) is split into per-thread
// column slices (the reference dependency parallelizes the same way —
// klauspost/reedsolomon splits shards across goroutines). nthreads <= 0
// means hardware concurrency. Each slice is independent, so output is
// bit-identical to the single-threaded path.
void sw_ec_matmul_mt(const uint8_t* coeffs, int r, int k, const uint8_t* data,
                     long long n, uint8_t* out, int nthreads) {
  if (nthreads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    nthreads = hc ? static_cast<int>(hc) : 1;
  }
  constexpr long long kMinSlice = 64 * 1024;
  long long max_by_size = (n + kMinSlice - 1) / kMinSlice;
  if (max_by_size < nthreads) nthreads = static_cast<int>(max_by_size);
  if (nthreads <= 1) {
    sw_ec_matmul(coeffs, r, k, data, n, out);
    return;
  }
  // 64-byte-aligned slice boundaries keep the AVX2 loops off split lines
  long long step = ((n + nthreads - 1) / nthreads + 63) & ~63LL;
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  for (int t = 0; t < nthreads; t++) {
    long long lo = t * step;
    if (lo >= n) break;
    long long hi = lo + step < n ? lo + step : n;
    workers.emplace_back([=] {
      for (int i = 0; i < r; i++) {
        uint8_t* dst = out + static_cast<long long>(i) * n + lo;
        for (int j = 0; j < k; j++) {
          mul_xor_row(coeffs[i * k + j],
                      data + static_cast<long long>(j) * n + lo, hi - lo, dst);
        }
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
