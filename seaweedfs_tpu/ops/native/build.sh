#!/bin/sh
# Build the native EC codec shared library.
# AVX2 is used when the build host supports it (-march=native); the source
# has a portable scalar fallback, so this always succeeds.
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -pthread -o libseaweed_ec.so seaweed_ec.cc
echo "built $(pwd)/libseaweed_ec.so"
