"""Reed-Solomon codec API + backend registry.

Semantics mirror the reference dependency's Encode/Reconstruct/ReconstructData
(klauspost/reedsolomon, used at reference ec_encoder.go:118-134, 231-285 and
store_ec.go:319-373): shards are equal-length byte rows, data rows are stored
verbatim (systematic code), missing shards are None and are regenerated
in place.

Backend selection (the reference's `-ec.backend` analog, SURVEY §5.6):
    get_codec(k, m, backend="numpy" | "native" | "tpu" | "auto")
"auto" picks tpu if a TPU is visible, else native if the C++ library is
built, else numpy. All backends produce bit-identical output.
"""

from __future__ import annotations

import itertools
import os
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import gf256
from ..util import config


def host_matmul(coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
    """The pure-numpy GF(2^8) matmul: one 256-entry LUT gather + XOR per
    (output row, input row) pair. The conformance oracle, and the
    small-payload path device codecs delegate kilobyte reads to (a
    device dispatch costs more than the whole LUT walk below
    small_dispatch_bytes)."""
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r = coeffs.shape[0]
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    mt = gf256.MUL_TABLE
    for i in range(r):
        acc = out[i]
        for j in range(coeffs.shape[1]):
            c = coeffs[i, j]
            if c == 0:
                continue
            if c == 1:
                acc ^= data[j]
            else:
                acc ^= mt[c][data[j]]
    return out


# Live override of the hybrid threshold, set by the auto-tuner
# (stats.metrics.observe_span when SW_EC_SMALL_DISPATCH_AUTO=1) once it
# has fitted the host/device crossover from the first reconstruct
# calls. Consulted by small_dispatch_default() (new codecs) AND by
# reconstruct() (codecs already constructed), so a suggestion applies
# without a server restart.
_SMALL_DISPATCH_OVERRIDE: "int | None" = None


def small_dispatch_default() -> int:
    """Width (bytes) below which device codecs answer reconstruct() on
    the host: reconstruct-on-read serves kilobyte needle ranges
    (server/volume_server._reconstruct_shard_range) and a full device
    round-trip per read would dominate the latency. Env-tunable, and
    superseded by the auto-tuner's override once one is applied."""
    if _SMALL_DISPATCH_OVERRIDE is not None:
        return _SMALL_DISPATCH_OVERRIDE
    return config.env_int("SW_EC_SMALL_DISPATCH_BYTES")


def small_dispatch_override() -> "int | None":
    return _SMALL_DISPATCH_OVERRIDE


def set_small_dispatch_override(nbytes: "int | None"):
    """Install (or clear, with None/0) the live hybrid-threshold
    override."""
    global _SMALL_DISPATCH_OVERRIDE
    _SMALL_DISPATCH_OVERRIDE = int(nbytes) if nbytes else None


def maybe_auto_apply_small_dispatch(suggestion: int) -> bool:
    """Apply the tuner's suggested threshold when the operator opted in
    via SW_EC_SMALL_DISPATCH_AUTO=1. Returns whether it was applied."""
    if not config.env_bool("SW_EC_SMALL_DISPATCH_AUTO"):
        return False
    set_small_dispatch_override(suggestion)
    return True


def dispatch_threshold(codec) -> int:
    """Live host/device crossover width for a codec: the
    SW_EC_SMALL_DISPATCH_AUTO fitted override (installed by the tuner
    via set_small_dispatch_override) supersedes whatever the codec
    snapshotted at construction, so a tuner suggestion applies without
    reconstructing the codec; host-only codecs
    (small_dispatch_bytes == 0) never delegate to the device."""
    if not codec.small_dispatch_bytes:
        return 0
    ov = small_dispatch_override()
    return ov if ov is not None else codec.small_dispatch_bytes


class _ConstCache:
    """Bounded LRU of device-resident coefficient constants, keyed by
    the coefficient bytes. A 256 MB rebuild must upload its ~14 KB
    bit-matrix once, not once per slab — every make() call counts as a
    bitmat_upload in ops/telemetry, so the bench can assert exactly
    that."""

    def __init__(self, maxsize: int = 32):
        self._entries: OrderedDict = OrderedDict()
        self._maxsize = maxsize
        from .device_stats import DEVICE_STATS
        DEVICE_STATS.register_const_cache(self)

    def get(self, key, make):
        from .device_stats import DEVICE_STATS
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
            DEVICE_STATS.note_const_cache("hits")
            return hit
        val = make()
        from .telemetry import STATS
        STATS.add("bitmat_uploads")
        DEVICE_STATS.note_const_cache("misses")
        self._entries[key] = val
        if len(self._entries) > self._maxsize:
            self._entries.popitem(last=False)
            DEVICE_STATS.note_const_cache("evictions")
        return val

    def occupancy(self) -> dict:
        """Entries and device bytes currently pinned (best-effort:
        constants without .nbytes count zero bytes)."""
        nbytes = 0
        for val in list(self._entries.values()):
            nbytes += int(getattr(val, "nbytes", 0) or 0)
        return {"entries": len(self._entries), "bytes": nbytes}


class ReedSolomonCodec:
    """Base class: matrix construction + reconstruction planning.

    Subclasses implement _matmul(coeffs, data) — the GF(2^8) matrix-vector
    product over byte rows — which is the only compute-heavy primitive.
    Device-backed subclasses additionally expose device_fn() so
    ops/pipeline.PipelinedMatmul can stream slabs through their kernel
    (encode and rebuild share the same pipelined hot path).
    """

    backend = "abstract"
    # 0 = never delegate; device codecs override with the env default
    small_dispatch_bytes = 0

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde"):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("data_shards and parity_shards must be > 0")
        if data_shards + parity_shards > 256:
            raise ValueError("k + m must be <= 256 in GF(2^8)")
        self.k = data_shards
        self.m = parity_shards
        self.total = data_shards + parity_shards
        self.matrix_kind = matrix_kind
        self.matrix = gf256.build_matrix(self.k, self.total, matrix_kind)
        self._decode_cache: dict = {}
        self._plan_cache: dict = {}
        self._syndrome_rows: Optional[np.ndarray] = None

    # -- primitive ---------------------------------------------------------
    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- device streaming hooks (ops/pipeline.PipelinedMatmul) -------------
    def device_fn(self, coeffs: np.ndarray, width: int):
        """Device-backed codecs return (jitted fn, device-resident
        constant, put) for `width`-wide slabs: ``fn(constant,
        put(slab))`` dispatches asynchronously and the constant stays
        resident across slabs. Host codecs return None (no pipeline)."""
        return None

    def pipeline_width_bucket(self, n: int, cap: int) -> int:
        """Bucket a slab width for compiled-executable reuse; mesh
        codecs additionally pad to their shard split."""
        from .rs_tpu import width_bucket
        return width_bucket(n, cap)

    # -- public API --------------------------------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        """data (k, n) uint8 -> parity (m, n) uint8."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.shape[0] != self.k:
            raise ValueError(f"expected {self.k} data rows, got {data.shape[0]}")
        return self._matmul(self.matrix[self.k:], data)

    def encode_to_all(self, data: np.ndarray) -> np.ndarray:
        """data (k, n) -> all shards (total, n); data rows verbatim."""
        parity = self.encode(data)
        return np.concatenate([np.asarray(data, dtype=np.uint8), parity], axis=0)

    def _decode_coeffs(self, present: tuple) -> tuple:
        """For a presence tuple, return (src_rows, inv_matrix) where
        data = inv_matrix @ shards[src_rows]."""
        key = present
        hit = self._decode_cache.get(key)
        if hit is not None:
            return hit
        src = [i for i, p in enumerate(present) if p][: self.k]
        if len(src) < self.k:
            raise ValueError(
                f"too few shards: have {sum(present)}, need {self.k}")
        sub = self.matrix[src, :]
        inv = gf256.mat_inv(sub)
        self._decode_cache[key] = (src, inv)
        return src, inv

    def decode_plan(self, present: tuple, data_only: bool = False) -> tuple:
        """Fused decode plan for a presence pattern: (src_rows, missing,
        coeffs) with coeffs (len(missing), k) such that ALL missing rows
        — data and parity stacked — come from ONE matmul against the
        first k survivors. Cached per (present, data_only) alongside
        _decode_cache, so steady-state rebuild pays zero GF planning per
        slab and exactly one device dispatch."""
        key = (tuple(present), bool(data_only))
        hit = self._plan_cache.get(key)
        if hit is not None:
            return hit
        src, inv = self._decode_coeffs(key[0])
        limit = self.k if data_only else self.total
        missing = [i for i in range(limit) if not present[i]]
        coeffs = gf256.decode_coeff_rows(self.matrix, self.k, src,
                                         missing, inv=inv)
        plan = (src, missing, coeffs)
        self._plan_cache[key] = plan
        return plan

    def lost_row_coeffs(self, present: tuple, sid: int) -> tuple:
        """Single-shard slice of the fused decode plan: (src_rows,
        coeffs) with coeffs (1, k) such that shard[sid] = coeffs @
        shards[src_rows]. Degraded reads regenerate exactly one lost
        row — the full plan's other missing rows would be wasted
        compute per request — while still riding the _plan_cache, so
        repeated reads of the same loss pattern pay zero GF planning."""
        src, missing, coeffs = self.decode_plan(tuple(present))
        if sid not in missing:
            raise ValueError(f"shard {sid} is not missing in {present}")
        r = missing.index(sid)
        return src, np.ascontiguousarray(coeffs[r:r + 1])

    def reconstruct(self, shards: Sequence[Optional[np.ndarray]],
                    data_only: bool = False) -> List[np.ndarray]:
        """Fill in missing (None) shards. Mirrors reference Reconstruct /
        ReconstructData. Returns the full shard list (data-only mode leaves
        missing parity as None).

        All missing rows are regenerated by a single fused matmul
        (decode_plan), and device codecs answer sub-small_dispatch_bytes
        widths on the host — reconstruct-on-read of a kilobyte range
        must not pay a device round-trip."""
        shards = list(shards)
        if len(shards) != self.total:
            raise ValueError(f"expected {self.total} shards, got {len(shards)}")
        present = tuple(s is not None for s in shards)
        if all(present):
            return shards
        lens = {s.shape[-1] for s in shards if s is not None}
        if len(lens) != 1:
            raise ValueError("surviving shards have differing lengths")
        from ..util import tracing
        with tracing.span("plan", backend=self.backend):
            src, missing, coeffs = self.decode_plan(present, data_only)
        if not missing:
            return shards
        survivors = np.stack([np.asarray(shards[i], dtype=np.uint8)
                              for i in src], axis=0)
        thr = self.small_dispatch_bytes
        if thr and _SMALL_DISPATCH_OVERRIDE is not None:
            # the auto-tuner's live override supersedes the snapshot
            # taken at construction; host-only codecs (thr == 0) keep
            # their never-delegate behavior
            thr = _SMALL_DISPATCH_OVERRIDE
        small = thr and survivors.shape[1] < thr
        # the reconstruct span's (bytes, seconds, path) tags feed the
        # SW_EC_SMALL_DISPATCH_BYTES tuner (stats.metrics.observe_span)
        with tracing.span("reconstruct", backend=self.backend,
                          bytes=int(survivors.nbytes),
                          path="host" if small else "device"):
            if small:
                from .telemetry import STATS
                STATS.add("host_fallbacks")
                out = host_matmul(coeffs, survivors)
            else:
                out = self._matmul(coeffs, survivors)
        for r, i in enumerate(missing):
            shards[i] = out[r]
        return shards

    def reconstruct_data(self, shards: Sequence[Optional[np.ndarray]]
                         ) -> List[np.ndarray]:
        return self.reconstruct(shards, data_only=True)

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        """True iff parity rows match the data rows."""
        data = np.stack([np.asarray(s, dtype=np.uint8)
                         for s in shards[: self.k]], axis=0)
        parity = self.encode(data)
        for i in range(self.m):
            if not np.array_equal(parity[i],
                                  np.asarray(shards[self.k + i], dtype=np.uint8)):
                return False
        return True

    def syndrome_plan(self) -> np.ndarray:
        """Parity-check rows H = [P | I_m], shape (m, k+m), derived from
        the cached encode matrix ([I_k; P] for systematic codes).

        For a consistent codeword column x (all k+m shard bytes at one
        offset), H @ x = P @ data XOR parity = 0 — GF(2^8) addition IS
        subtraction, so the identity block needs no negation. Any
        nonzero syndrome byte pins corruption to that byte column, and
        the scrub verifies a whole slab as ONE (m, k+m) x (k+m, w)
        fused matmul — the same PipelinedMatmul hot path encode and
        rebuild ride, with coefficients swapped. Cached like the decode
        plans: steady-state scrub pays zero GF planning per slab."""
        if self._syndrome_rows is None:
            h = np.zeros((self.m, self.total), dtype=np.uint8)
            h[:, : self.k] = self.matrix[self.k:]
            h[:, self.k:] = np.eye(self.m, dtype=np.uint8)
            self._syndrome_rows = np.ascontiguousarray(h)
        return self._syndrome_rows


class NumpyCodec(ReedSolomonCodec):
    """Pure-numpy reference backend — the conformance oracle.

    Inner loop: one 256-entry LUT gather + XOR per (output row, input row)
    pair, equivalent to the reference dependency's galMulSlice without SIMD.
    """

    backend = "numpy"

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        return host_matmul(coeffs, data)


_TPU_PROBE_RESULT = None


def _tpu_present(timeout_s: float = 60.0) -> bool:
    """Watchdogged TPU probe: jax.devices() can hang forever when the
    device tunnel is broken, and a hung probe must not take the whole
    server down with it. Result is cached for the process."""
    global _TPU_PROBE_RESULT
    if _TPU_PROBE_RESULT is not None:
        return _TPU_PROBE_RESULT
    import threading
    result = {}

    def probe():
        try:
            import jax
            result["tpu"] = any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            result["tpu"] = False

    th = threading.Thread(target=probe, daemon=True,
                          name="device-init-probe")
    th.start()
    th.join(timeout_s)
    _TPU_PROBE_RESULT = bool(result.get("tpu", False))
    return _TPU_PROBE_RESULT


def get_codec(data_shards: int, parity_shards: int,
              backend: str = "auto",
              matrix_kind: str = "vandermonde") -> ReedSolomonCodec:
    if backend == "auto":
        from .rs_native import native_available
        if _tpu_present():
            backend = "tpu"
        elif native_available():
            backend = "native"
        else:
            backend = "numpy"
    if backend == "numpy":
        return NumpyCodec(data_shards, parity_shards, matrix_kind)
    if backend == "native":
        from .rs_native import NativeCodec
        return NativeCodec(data_shards, parity_shards, matrix_kind)
    if backend == "tpu":
        from .rs_tpu import TpuCodec
        return TpuCodec(data_shards, parity_shards, matrix_kind)
    if backend == "mesh":
        # SPMD over every visible device (multi-chip hosts); same
        # programs the multichip dryrun validates on a virtual mesh
        from ..parallel.mesh_codec import MeshCodec
        return MeshCodec(data_shards, parity_shards, matrix_kind)
    raise ValueError(f"unknown backend {backend!r}")


# ---------------------------------------------------------------------------
# Trace repair of a single lost shard (arxiv 2205.11015).
#
# A dual codeword g satisfies sum_i g[i]*c_i = 0 over every stripe, so
#     Tr(g[lost]*c_lost) = sum_{i != lost} Tr(g[i]*c_i).
# Pick 8 dual codewords whose values at the lost position are
# GF(2)-independent and every bit of c_lost is a GF(2) combination of
# the trace bits Tr(g_j[i]*c_i).  Helper i only has to ship
# t_i = dim_2 span{g_j[i]} bits per byte — its projection onto a
# reduced basis of that span — instead of all 8, which is where the
# sub-k*slab repair bandwidth comes from.  The rebuilder's combine is a
# {0,1}-coefficient GF(2^8) matmul (XOR of bit-planes), so the existing
# pipelined device kernels run it unchanged: one dispatch per slab.
# ---------------------------------------------------------------------------

REPAIR_MAX_SUBSETS = 400   # cap on vanish-subset enumeration (RS(20,4))
REPAIR_RESTARTS = 3        # greedy restarts with shuffled candidate order


@dataclass(frozen=True, eq=False)
class RepairPlan:
    """Single-lost-shard trace-repair scheme for one geometry.

    helpers lists the shard ids that must be contacted (t_i > 0 only);
    masks[sid] are the GF(2^8) projection masks that holder applies
    (one packed bit-plane per mask); combine is the (8, total_bits)
    {0,1} matrix that XORs the concatenated symbol planes back into
    the lost shard's 8 bit-planes, in helpers-then-mask order.
    """

    k: int
    m: int
    lost: int
    helpers: Tuple[int, ...]
    masks: Dict[int, Tuple[int, ...]] = field(hash=False)
    combine: np.ndarray = field(hash=False)
    matrix_kind: str = "vandermonde"

    @property
    def total_bits(self) -> int:
        return sum(len(v) for v in self.masks.values())

    @property
    def frac(self) -> float:
        """Repair symbol bits per stripe byte vs the k-byte baseline."""
        return self.total_bits / (8.0 * self.k)

    def bits_for(self, sid: int) -> int:
        return len(self.masks[sid])

    def wire_bytes(self, width: int) -> int:
        """Bytes on the wire for a width-byte slab range (all helpers,
        packed planes; excludes HTTP framing)."""
        return self.total_bits * ((width + 7) // 8)


def project_slab(data: np.ndarray, masks) -> np.ndarray:
    """Holder-side projection: trace bits Tr(mask * data) packed
    little-bit-first per mask. data (w,) uint8 -> (len(masks),
    ceil(w/8)) uint8. One LUT gather + packbits — cheap enough to run
    on the volume server's host CPU."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m = np.asarray(list(masks), dtype=np.uint8)
    bits = gf256.TRACE_MUL[m[:, None], data[None, :]]
    return np.packbits(bits, axis=1, bitorder="little")


def combine_planes_to_bytes(planes: np.ndarray, width: int) -> np.ndarray:
    """Rebuilder-side interleave: 8 packed output bit-planes (8,
    ceil(width/8)) -> the lost shard's bytes (width,). Plane b holds
    bit b of every output byte."""
    bits = np.unpackbits(np.ascontiguousarray(planes, dtype=np.uint8),
                         axis=1, count=width, bitorder="little")
    return np.packbits(bits, axis=0, bitorder="little").reshape(-1)


class _PlanLRU:
    """Bounded LRU for derived GF plans (repair / piggyback), shared
    hit/miss/evict accounting. Unlike _ConstCache this holds host-side
    plan objects, and identity is stable across repeated gets — callers
    (and tests) rely on ``plan_fn(...) is plan_fn(...)``. The bound is
    SW_EC_PLAN_CACHE_SIZE, read live so operators can resize without a
    restart; under geometry/survivor churn the old unbounded dict grew
    one entry per (k, m, lost, helpers, matrix) combination forever."""

    def __init__(self, name: str):
        self.name = name
        self._entries: OrderedDict = OrderedDict()

    def get(self, key, make):
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
            _PLAN_CACHE_EVENTS["hits"] += 1
            return hit
        _PLAN_CACHE_EVENTS["misses"] += 1
        val = make()
        self._entries[key] = val
        maxsize = max(config.env_int("SW_EC_PLAN_CACHE_SIZE"), 1)
        while len(self._entries) > maxsize:
            self._entries.popitem(last=False)
            _PLAN_CACHE_EVENTS["evictions"] += 1
        return val

    def __len__(self):
        return len(self._entries)


_PLAN_CACHE_EVENTS = {"hits": 0, "misses": 0, "evictions": 0}
_REPAIR_PLAN_CACHE = _PlanLRU("repair")


def plan_cache_stats() -> dict:
    """Snapshot for stats/metrics export (ec_plan_cache_* families):
    cumulative hit/miss/evict event counts plus current entry counts
    per plan cache."""
    return {
        "events": dict(_PLAN_CACHE_EVENTS),
        "entries": {c.name: len(c) for c in
                    (_REPAIR_PLAN_CACHE, _PIGGYBACK_PLAN_CACHE,
                     _PIGGYBACK_REPAIR_CACHE, _PIGGYBACK_DECODE_CACHE)},
    }


def repair_plan(k: int, m: int, lost_sid: int, survivors=None,
                matrix_kind: str = "vandermonde",
                matrix: "np.ndarray | None" = None,
                seed: int = 0) -> RepairPlan:
    """Build (and cache) the trace-repair scheme for one lost shard.

    survivors: iterable of reachable shard ids (default: all others).
    Unreachable positions are handled by forcing every dual codeword to
    vanish there, which needs n - 1 - len(survivors) <= m - 1; with
    fewer survivors than k the code cannot repair at all and this
    raises ValueError.

    The scheme search enumerates dual codewords supported off an
    (m-1)-subset of positions (nullspace of the transposed generator
    restricted to the complement), scales each by all 255 nonzero
    constants, and greedily picks 8 equations minimizing the total
    per-helper GF(2) span growth — deterministic for a given seed, so
    every process derives the identical plan.
    """
    n = k + m
    if not (0 <= lost_sid < n):
        raise ValueError(f"lost shard {lost_sid} outside 0..{n - 1}")
    if survivors is None:
        survivors = [i for i in range(n) if i != lost_sid]
    helpers = sorted(set(int(s) for s in survivors) - {lost_sid})
    unavailable = [i for i in range(n) if i != lost_sid and i not in helpers]
    if len(unavailable) > m - 1:
        raise ValueError(
            f"too few survivors: {len(helpers)} reachable, need >= {k}")
    key = (k, m, lost_sid, tuple(helpers), matrix_kind,
           None if matrix is None else matrix.tobytes(), seed)
    return _REPAIR_PLAN_CACHE.get(
        key, lambda: _build_repair_plan(k, m, lost_sid, helpers, unavailable,
                                        matrix_kind, matrix, seed))


def _build_repair_plan(k, m, lost_sid, helpers, unavailable, matrix_kind,
                       matrix, seed) -> RepairPlan:
    n = k + m
    if matrix is None:
        matrix = gf256.build_matrix(k, n, matrix_kind)

    # -- candidate dual codewords: vanish on unavailable + an
    #    (m-1-|unavailable|)-subset of helpers ---------------------------
    free = m - 1 - len(unavailable)
    subsets = list(itertools.combinations(helpers, free))
    rng = np.random.default_rng(seed)
    if len(subsets) > REPAIR_MAX_SUBSETS:
        idx = rng.choice(len(subsets), size=REPAIR_MAX_SUBSETS,
                         replace=False)
        subsets = [subsets[i] for i in sorted(idx)]
    base = []
    for sub in subsets:
        vanish = set(unavailable) | set(sub)
        support = [i for i in range(n) if i not in vanish]
        g_u = gf256.gf_nullspace(matrix[support, :].T)
        if g_u is None:
            continue
        g = np.zeros(n, dtype=np.uint8)
        g[support] = g_u
        if g[lost_sid] == 0:
            continue
        base.append(g)
    if not base:
        raise ValueError("no usable dual codewords for this geometry")
    base = np.stack(base, axis=0)
    betas = np.arange(1, 256, dtype=np.uint8)
    cand = gf256.MUL_TABLE[betas[None, :, None], base[:, None, :]]
    cand = cand.reshape(-1, n)

    # -- greedy scheme selection (restarts keep the best) ----------------
    best = None
    for r in range(REPAIR_RESTARTS):
        order = rng.permutation(cand.shape[0]) if r else \
            np.arange(cand.shape[0])
        cv = cand[order]
        chosen = []
        star_basis: list = []
        pos_basis = {i: [] for i in helpers}
        total = 0
        for _ in range(8):
            ok = gf256.gf2_reduce(cv[:, lost_sid], star_basis) != 0
            cost = np.zeros(cv.shape[0], dtype=np.int32)
            for i in helpers:
                cost += (gf256.gf2_reduce(cv[:, i], pos_basis[i]) != 0
                         ).astype(np.int32)
            c = int(np.argmin(np.where(ok, cost, np.int32(1 << 20))))
            chosen.append(cv[c].copy())
            gf256.gf2_insert(star_basis, int(cv[c, lost_sid]))
            for i in helpers:
                if gf256.gf2_insert(pos_basis[i], int(cv[c, i])):
                    total += 1
        if best is None or total < best[0]:
            best = (total, chosen, {i: list(pos_basis[i]) for i in helpers})

    _, chosen, bases = best
    active = [i for i in helpers if bases[i]]
    masks = {i: tuple(bases[i]) for i in active}

    # -- combine matrix: bits(c_lost) = inv(A) @ Lambda @ sigma ----------
    a = np.zeros((8, 8), dtype=np.uint8)
    for j, g in enumerate(chosen):
        for b in range(8):
            a[j, b] = gf256.TRACE_MUL[int(g[lost_sid]), 1 << b]
    lam = np.zeros((8, sum(len(masks[i]) for i in active)), dtype=np.uint8)
    for j, g in enumerate(chosen):
        col = 0
        for i in active:
            coords = gf256.gf2_decompose(int(g[i]), masks[i])
            lam[j, col:col + len(coords)] = coords
            col += len(coords)
    combine = (gf256.gf2_mat_inv(a).astype(np.int32) @
               lam.astype(np.int32)) % 2
    return RepairPlan(k=k, m=m, lost=lost_sid, helpers=tuple(active),
                      masks=masks, combine=combine.astype(np.uint8),
                      matrix_kind=matrix_kind)


def repair_gain(plan: RepairPlan) -> float:
    """Fraction of the k*slab baseline saved by trace repair
    (0 = no gain; ec.rebuild -repair auto requires > 0)."""
    return 1.0 - plan.frac


# ---------------------------------------------------------------------------
# Piggybacked sub-chunk layout (SW_EC_LAYOUT=piggyback).
#
# Each shard is split into alpha = 2^npairs sub-chunks per window
# (npairs = min(k//2, 5) data-shard pairs). Data shards stay verbatim;
# parity shard j's sub-chunk z couples each data shard i (pair p = i>>1,
# side b = i&1) with its partner sub-chunk across bit p:
#
#   P_j[z] = XOR_i  a[j,i]*s_i[z]  ^  [z_p == b] * c[j,i]*s_i[z ^ 2^p]
#
# with a = the flat RS parity rows and c[j,i] = theta_j * a[j,i]
# (theta distinct per parity). The gate [z_p == b] is what makes
# single-data-shard repair plane-local: to repair shard i*, the other
# k-1 data shards and any TWO parities ship only the half-plane
# {z : z_{p*} = b*}; per z the two parity equations form one constant
# 2x2 system in (s[z], s[z ^ 2^{p*}]), recovering both halves of the
# lost shard. Download = (k+1) * alpha/2 sub-chunks = (k+1)/(2k) of
# k*shard — 0.55 for RS(10,4), the d=k+1 cut-set point, below the
# 0.69 floor proven for linear repair of the flat code (2205.11015).
#
# Full decode of any <= m lost shards block-diagonalizes over cosets
# of span{2^{p(i)} : i lost}: at most (m * 2^m) x (m * 2^m) GF systems
# shared by every coset, so planning stays milliseconds and the slab
# hot path is still ONE fused matmul on the unchanged kernels. Node-MDS
# of the coupled code is not automatic — theta is chosen by a
# deterministic seed search that exhaustively sweeps every
# (lost-data, parity-subset) pattern at plan build, and the known-good
# seeds for common geometries are pinned below.
# ---------------------------------------------------------------------------

PIGGYBACK_MAX_PAIRS = 5            # alpha capped at 2^5 = 32 sub-chunks
PIGGYBACK_SEED_TRIES = 32          # theta seed search bound
# geometry -> verified theta seed (the MDS sweep still reruns once per
# process at plan build; these just skip the failed-seed prefix)
PIGGYBACK_KNOWN_SEEDS = {
    (10, 4): 5, (6, 3): 0, (20, 4): 1, (4, 2): 0, (8, 3): 0, (12, 4): 8,
}


def _pb_pairs_cap() -> int:
    """Effective pair cap: SW_EC_PIGGYBACK_PAIRS clamped to
    [1, PIGGYBACK_MAX_PAIRS]. Part of the plan cache key — lowering it
    trades repair savings on the tail shards for a smaller alpha."""
    cap = config.env_int("SW_EC_PIGGYBACK_PAIRS")
    return max(1, min(int(cap), PIGGYBACK_MAX_PAIRS))


def piggyback_supported(k: int, m: int) -> bool:
    """Geometries the piggyback layout accepts: >= 2 parities (the
    repair plane solves a 2x2 per z) and >= 1 data pair. Odd-k tails
    beyond the paired prefix stay uncoupled and repair via the flat
    fallback paths."""
    return m >= 2 and k >= 2 and k + m <= 256


@dataclass(frozen=True, eq=False)
class PiggybackPlan:
    """Verified coupled-layout geometry: encode matrix + coupling
    coefficients. emat is the (m*alpha, k*alpha) block matrix a single
    batched GF matmul applies per window-split slab."""

    k: int
    m: int
    npairs: int
    alpha: int
    theta_seed: int
    matrix_kind: str = "vandermonde"
    amat: np.ndarray = field(hash=False, default=None)
    cmat: np.ndarray = field(hash=False, default=None)
    emat: np.ndarray = field(hash=False, default=None)

    @property
    def coupled(self) -> int:
        """Number of data shards with a coupling partner (cheap repair)."""
        return 2 * self.npairs

    @property
    def repair_frac(self) -> float:
        """Single-coupled-data-shard repair download vs k*shard."""
        return (self.k + 1) / (2.0 * self.k)

    def syndrome_rows(self) -> np.ndarray:
        """[E | I] over flattened sub-chunk columns: zero syndrome iff
        the window's parity sub-chunks match the coupled encode."""
        ka, ma = self.k * self.alpha, self.m * self.alpha
        h = np.zeros((ma, ka + ma), dtype=np.uint8)
        h[:, :ka] = self.emat
        h[:, ka:] = np.eye(ma, dtype=np.uint8)
        return h


def _pb_build(k: int, m: int, matrix_kind: str, matrix, theta_seed: int,
              cap: int):
    """(a, c) coefficient rows for one theta seed."""
    n = k + m
    if matrix is None:
        matrix = gf256.build_matrix(k, n, matrix_kind)
    a = np.ascontiguousarray(matrix[k:])
    npairs = min(k // 2, cap)
    theta = [gf256.EXP_TABLE[(theta_seed * m + j) * 11 % 255]
             for j in range(m)]
    if len(set(theta)) != m:
        raise ValueError("theta collision — geometry too wide for seed")
    c = gf256.MUL_TABLE[np.asarray(theta, dtype=np.uint8)[:, None], a]
    c[:, 2 * npairs:] = 0
    return a, c, npairs, 1 << npairs


def _pb_encode_matrix(k, m, a, c, npairs, alpha) -> np.ndarray:
    emat = np.zeros((m * alpha, k * alpha), dtype=np.uint8)
    for j in range(m):
        for z in range(alpha):
            r = j * alpha + z
            for i in range(k):
                emat[r, i * alpha + z] ^= a[j, i]
                if i < 2 * npairs:
                    p, b = i >> 1, i & 1
                    if (z >> p) & 1 == b:
                        emat[r, i * alpha + (z ^ (1 << p))] ^= c[j, i]
    return emat


def _pb_decode_block(k, m, a, c, npairs, lostF, pJ):
    """Per-coset solve for lost data shards lostF from parities pJ:
    (Minv, V) with V the coupling span (coset offsets) and Minv the
    (f*|V|, f*|V|) inverse, or None when singular. Unknown order is
    (i in sorted F) x (v in V); equation order (j in pJ) x (v in V)."""
    F = sorted(lostF)
    f = len(F)
    V = [0]
    for p in sorted(set(i >> 1 for i in F if i < 2 * npairs)):
        V = V + [v | (1 << p) for v in V]
    t2 = len(V)
    vidx = {v: e for e, v in enumerate(V)}
    mat = np.zeros((f * t2, f * t2), dtype=np.uint8)
    for je, j in enumerate(pJ):
        for ve, v in enumerate(V):
            r = je * t2 + ve
            for ui, i in enumerate(F):
                mat[r, ui * t2 + ve] ^= a[j, i]
                if i < 2 * npairs:
                    p, b = i >> 1, i & 1
                    if (v >> p) & 1 == b:
                        mat[r, ui * t2 + vidx[v ^ (1 << p)]] ^= c[j, i]
    try:
        return gf256.mat_inv(mat), V
    except Exception:  # noqa: BLE001 - singular candidate
        return None


def _pb_mds_sweep(k, m, a, c, npairs) -> bool:
    """True iff every (lost-data, parity-subset) pattern is decodable.
    Coset block structure keeps this to small inversions; RS(10,4)
    sweeps its 1000 patterns in well under a second."""
    for f in range(1, m + 1):
        for F in itertools.combinations(range(k), f):
            for J in itertools.combinations(range(m), f):
                if _pb_decode_block(k, m, a, c, npairs, F, J) is None:
                    return False
    return True


_PIGGYBACK_PLAN_CACHE = _PlanLRU("piggyback")
_PIGGYBACK_REPAIR_CACHE = _PlanLRU("piggyback_repair")
_PIGGYBACK_DECODE_CACHE = _PlanLRU("piggyback_decode")


def piggyback_plan(k: int, m: int, matrix_kind: str = "vandermonde",
                   matrix: "np.ndarray | None" = None,
                   pairs: "int | None" = None) -> PiggybackPlan:
    """Build (and cache) the verified coupled-layout plan for a
    geometry. Deterministic: the theta seed search starts from the
    pinned known-good seed when the geometry has one, and every
    candidate must pass the exhaustive node-MDS sweep before the plan
    is returned — a layout that cannot decode some failure pattern
    must never reach a disk.

    `pairs` pins the pair cap for an already-encoded volume (from its
    sidecar); new encodes leave it None and take the
    SW_EC_PIGGYBACK_PAIRS knob."""
    if not piggyback_supported(k, m):
        raise ValueError(
            f"piggyback layout needs m >= 2 and k >= 2, got RS({k},{m})")
    cap = _pb_pairs_cap() if pairs is None else max(
        1, min(int(pairs), PIGGYBACK_MAX_PAIRS))
    key = (k, m, matrix_kind, cap,
           None if matrix is None else matrix.tobytes())
    return _PIGGYBACK_PLAN_CACHE.get(
        key, lambda: _build_piggyback_plan(k, m, matrix_kind, matrix, cap))


def _build_piggyback_plan(k, m, matrix_kind, matrix, cap) -> PiggybackPlan:
    known = PIGGYBACK_KNOWN_SEEDS.get((k, m))
    order = list(range(PIGGYBACK_SEED_TRIES))
    if known is not None:
        order.remove(known)
        order.insert(0, known)
    for seed in order:
        a, c, npairs, alpha = _pb_build(k, m, matrix_kind, matrix, seed,
                                        cap)
        if _pb_mds_sweep(k, m, a, c, npairs):
            emat = _pb_encode_matrix(k, m, a, c, npairs, alpha)
            return PiggybackPlan(k=k, m=m, npairs=npairs, alpha=alpha,
                                 theta_seed=seed, matrix_kind=matrix_kind,
                                 amat=a, cmat=c, emat=emat)
    raise ValueError(
        f"no MDS theta seed within {PIGGYBACK_SEED_TRIES} tries for "
        f"RS({k},{m}) {matrix_kind}")


@dataclass(frozen=True, eq=False)
class PiggybackRepairPlan:
    """Half-plane repair of one coupled data shard. Every helper
    (the k-1 other data shards + the two parity_sids) ships the
    sub-chunks {z : bit plane_bit of z == plane_side}; matrix is the
    (alpha, (k+1)*alpha/2) combine applied per window — one fused
    matmul rebuilds the lost shard bit-identically."""

    k: int
    m: int
    lost: int
    alpha: int
    plane_bit: int
    plane_side: int
    data_helpers: Tuple[int, ...]
    parity_sids: Tuple[int, ...]
    matrix: np.ndarray = field(hash=False, default=None)
    matrix_kind: str = "vandermonde"

    @property
    def helpers(self) -> Tuple[int, ...]:
        return self.data_helpers + self.parity_sids

    @property
    def frac(self) -> float:
        """Downloaded bytes vs the k*shard full-rebuild baseline."""
        return len(self.helpers) / (2.0 * self.k)

    def plane(self) -> Tuple[int, ...]:
        return tuple(z for z in range(self.alpha)
                     if (z >> self.plane_bit) & 1 == self.plane_side)

    def wire_bytes(self, shard_bytes: int) -> int:
        """Bytes on the wire for whole-shard repair (all helpers,
        half a shard each; excludes HTTP framing)."""
        return len(self.helpers) * (shard_bytes // 2)


def piggyback_repair_plan(k: int, m: int, lost_sid: int,
                          parity_sids=None,
                          matrix_kind: str = "vandermonde",
                          matrix: "np.ndarray | None" = None,
                          pairs: "int | None" = None
                          ) -> PiggybackRepairPlan:
    """Build (and cache) the half-plane repair scheme for one lost
    COUPLED data shard. parity_sids: the two reachable parity shard
    ids to use (absolute, >= k; default the first two). Uncoupled
    shards (odd-k tail, parity shards) have no plane scheme — callers
    route them to trace/full repair instead."""
    pplan = piggyback_plan(k, m, matrix_kind, matrix, pairs=pairs)
    if not (0 <= lost_sid < pplan.coupled):
        raise ValueError(
            f"shard {lost_sid} is not a coupled data shard "
            f"(coupled: 0..{pplan.coupled - 1})")
    if parity_sids is None:
        parity_sids = (k, k + 1)
    pj = tuple(sorted(int(s) for s in parity_sids))
    if len(pj) != 2 or not all(k <= s < k + m for s in pj):
        raise ValueError(f"need exactly two parity shard ids, got {pj}")
    key = (k, m, pplan.npairs, lost_sid, pj, matrix_kind,
           None if matrix is None else matrix.tobytes())
    return _PIGGYBACK_REPAIR_CACHE.get(
        key, lambda: _build_piggyback_repair(pplan, lost_sid, pj))


def _build_piggyback_repair(pplan: PiggybackPlan, lost: int,
                            pj: Tuple[int, int]) -> PiggybackRepairPlan:
    k, m = pplan.k, pplan.m
    a, c, alpha = pplan.amat, pplan.cmat, pplan.alpha
    npairs = pplan.npairs
    p_, b_ = lost >> 1, lost & 1
    half = alpha // 2
    plane = [z for z in range(alpha) if (z >> p_) & 1 == b_]
    zidx = {z: t for t, z in enumerate(plane)}
    dh = [i for i in range(k) if i != lost]
    j1, j2 = pj[0] - k, pj[1] - k
    minv = gf256.mat_inv(np.array(
        [[a[j1, lost], c[j1, lost]],
         [a[j2, lost], c[j2, lost]]], dtype=np.uint8))
    w = np.zeros((alpha, (len(dh) + 2) * half), dtype=np.uint8)
    colbase = {h: t * half for t, h in enumerate(dh)}
    pbase = {j1: len(dh) * half, j2: (len(dh) + 1) * half}
    mt = gf256.MUL_TABLE
    for z in plane:
        t = zidx[z]
        for col, jp in ((0, j1), (1, j2)):
            # K_jp[z] weights into the two unknowns (s[z], s[z^2^p*])
            for out_z, wc in ((z, minv[0, col]), (z ^ (1 << p_),
                                                  minv[1, col])):
                if wc == 0:
                    continue
                w[out_z, pbase[jp] + t] ^= wc
                for h in dh:
                    ah = mt[wc, a[jp, h]]
                    if ah:
                        w[out_z, colbase[h] + t] ^= ah
                    if h < 2 * npairs:
                        ph, bh = h >> 1, h & 1
                        if (z >> ph) & 1 == bh and c[jp, h]:
                            # gated partner term: stays on the plane
                            # because ph != p* for every helper whose
                            # gate can fire here
                            w[out_z, colbase[h] + zidx[z ^ (1 << ph)]] ^= \
                                mt[wc, c[jp, h]]
    return PiggybackRepairPlan(
        k=k, m=m, lost=lost, alpha=alpha, plane_bit=p_, plane_side=b_,
        data_helpers=tuple(dh), parity_sids=pj, matrix=w,
        matrix_kind=pplan.matrix_kind)


def piggyback_decode_plan(k: int, m: int, present,
                          matrix_kind: str = "vandermonde",
                          matrix: "np.ndarray | None" = None,
                          pairs: "int | None" = None):
    """Fused full decode for a presence pattern on the coupled layout:
    returns (src_sids, missing_sids, coeffs) with coeffs
    (len(missing)*alpha, len(src)*alpha) so every missing shard — data
    and parity — comes from ONE window-split matmul against the
    survivors. src is every surviving data shard plus as many parities
    as there are missing data shards (full decode still reads exactly
    k shards, same as the flat layout)."""
    pplan = piggyback_plan(k, m, matrix_kind, matrix, pairs=pairs)
    key = (k, m, tuple(bool(p) for p in present), matrix_kind,
           pplan.npairs,
           None if matrix is None else matrix.tobytes())
    return _PIGGYBACK_DECODE_CACHE.get(
        key, lambda: _build_piggyback_decode(pplan, key[2]))


def _build_piggyback_decode(pplan: PiggybackPlan, present):
    k, m, alpha = pplan.k, pplan.m, pplan.alpha
    n = k + m
    if len(present) != n:
        raise ValueError(f"presence tuple must have {n} entries")
    a, c, npairs = pplan.amat, pplan.cmat, pplan.npairs
    missing = [i for i in range(n) if not present[i]]
    lost_data = [i for i in missing if i < k]
    f = len(lost_data)
    live_data = [i for i in range(k) if present[i]]
    live_par = [j for j in range(m) if present[k + j]]
    if len(live_data) + len(live_par) < k:
        raise ValueError(
            f"too few shards: have {sum(present)}, need {k}")
    use_par = live_par[:f]
    src = live_data + [k + j for j in use_par]
    mt = gf256.MUL_TABLE
    src_col = {s: t * alpha for t, s in enumerate(src)}
    # L: full data flat (k*alpha) as a GF-linear map of the src stack
    ldat = np.zeros((k * alpha, len(src) * alpha), dtype=np.uint8)
    for i in live_data:
        for z in range(alpha):
            ldat[i * alpha + z, src_col[i] + z] = 1
    if f:
        blk = _pb_decode_block(k, m, a, c, npairs, lost_data, use_par)
        if blk is None:
            raise ValueError(
                "singular decode pattern — layout verification bug")
        minv, v_span = blk
        t2 = len(v_span)
        mask = 0
        for v in v_span:
            mask |= v
        vidx = {v: e for e, v in enumerate(v_span)}
        fs = sorted(lost_data)
        for z0 in range(alpha):
            if z0 & mask:
                continue
            # K rows for this coset, as rows over the src stack
            krows = np.zeros((f * t2, len(src) * alpha), dtype=np.uint8)
            for je, j in enumerate(use_par):
                for ve, v in enumerate(v_span):
                    z = z0 | v
                    r = je * t2 + ve
                    krows[r, src_col[k + j] + z] ^= 1
                    for h in live_data:
                        krows[r, src_col[h] + z] ^= a[j, h]
                        if h < 2 * npairs:
                            ph, bh = h >> 1, h & 1
                            if (z >> ph) & 1 == bh and c[j, h]:
                                krows[r, src_col[h] + (z ^ (1 << ph))] ^= \
                                    c[j, h]
            sol = gf256.mat_mul(minv, krows)
            for ui, i in enumerate(fs):
                for ve, v in enumerate(v_span):
                    ldat[i * alpha + (z0 | v)] = sol[ui * t2 + ve]
    rows = []
    for s in missing:
        if s < k:
            rows.append(ldat[s * alpha:(s + 1) * alpha])
        else:
            j = s - k
            erows = pplan.emat[j * alpha:(j + 1) * alpha]
            rows.append(gf256.mat_mul(erows, ldat))
    coeffs = np.concatenate(rows, axis=0) if rows else \
        np.zeros((0, len(src) * alpha), dtype=np.uint8)
    return src, missing, np.ascontiguousarray(coeffs)


# -- sub-chunk window transforms (pure reshapes, zero copy semantics
#    beyond the transpose) ---------------------------------------------------

def pb_window(small_block: int, alpha: int) -> int:
    """Sub-chunk window: every window bytes of a shard split into alpha
    interleaved sub-chunks. The window is the small stripe block, which
    divides every shard size the two-level striping can produce; it
    must itself be alpha-divisible."""
    if small_block % alpha:
        raise ValueError(
            f"small block {small_block} not divisible by alpha {alpha}")
    return small_block


def pb_split(rows: np.ndarray, alpha: int, window: int) -> np.ndarray:
    """(r, W) shard rows -> (r*alpha, W/alpha) sub-chunk rows, window
    by window; W must be window-aligned. Row order (shard-major,
    sub-chunk z) matches the encode/decode matrix column order."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    r, width = rows.shape
    if width % window:
        raise ValueError(f"width {width} not aligned to window {window}")
    wsub = window // alpha
    x = rows.reshape(r, width // window, alpha, wsub)
    return np.ascontiguousarray(
        x.transpose(0, 2, 1, 3).reshape(r * alpha, width // alpha))


def pb_merge(flat: np.ndarray, alpha: int, window: int) -> np.ndarray:
    """Inverse of pb_split: (r*alpha, W/alpha) -> (r, W)."""
    wsub = window // alpha
    ra, cols = flat.shape
    r = ra // alpha
    x = flat.reshape(r, alpha, cols // wsub, wsub)
    return np.ascontiguousarray(
        x.transpose(0, 2, 1, 3).reshape(r, cols * alpha))


def pb_plane_slice(shard: np.ndarray, alpha: int, window: int,
                   plane_bit: int, plane_side: int) -> np.ndarray:
    """Holder-side half-plane extraction: the repair protocol ships
    exactly these bytes. (W,) -> (W/2,) — the plane's sub-chunks in
    increasing z, window-major, so the rebuilder's pb_plane_rows can
    restack them without knowing the holder's file layout."""
    shard = np.ascontiguousarray(shard, dtype=np.uint8)
    wsub = window // alpha
    zs = [z for z in range(alpha) if (z >> plane_bit) & 1 == plane_side]
    x = shard.reshape(-1, alpha, wsub)
    return np.ascontiguousarray(x[:, zs, :].reshape(-1))


def pb_plane_rows(plane: np.ndarray, alpha: int, window: int) -> np.ndarray:
    """Rebuilder-side restack of one helper's plane bytes:
    (W/2,) -> (alpha/2, W/alpha) rows in plan column order."""
    half = alpha // 2
    wsub = window // alpha
    x = plane.reshape(-1, half, wsub)
    return np.ascontiguousarray(
        x.transpose(1, 0, 2).reshape(half, -1))
