"""Reed-Solomon codec API + backend registry.

Semantics mirror the reference dependency's Encode/Reconstruct/ReconstructData
(klauspost/reedsolomon, used at reference ec_encoder.go:118-134, 231-285 and
store_ec.go:319-373): shards are equal-length byte rows, data rows are stored
verbatim (systematic code), missing shards are None and are regenerated
in place.

Backend selection (the reference's `-ec.backend` analog, SURVEY §5.6):
    get_codec(k, m, backend="numpy" | "native" | "tpu" | "auto")
"auto" picks tpu if a TPU is visible, else native if the C++ library is
built, else numpy. All backends produce bit-identical output.
"""

from __future__ import annotations

import itertools
import os
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import gf256
from ..util import config


def host_matmul(coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
    """The pure-numpy GF(2^8) matmul: one 256-entry LUT gather + XOR per
    (output row, input row) pair. The conformance oracle, and the
    small-payload path device codecs delegate kilobyte reads to (a
    device dispatch costs more than the whole LUT walk below
    small_dispatch_bytes)."""
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r = coeffs.shape[0]
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    mt = gf256.MUL_TABLE
    for i in range(r):
        acc = out[i]
        for j in range(coeffs.shape[1]):
            c = coeffs[i, j]
            if c == 0:
                continue
            if c == 1:
                acc ^= data[j]
            else:
                acc ^= mt[c][data[j]]
    return out


# Live override of the hybrid threshold, set by the auto-tuner
# (stats.metrics.observe_span when SW_EC_SMALL_DISPATCH_AUTO=1) once it
# has fitted the host/device crossover from the first reconstruct
# calls. Consulted by small_dispatch_default() (new codecs) AND by
# reconstruct() (codecs already constructed), so a suggestion applies
# without a server restart.
_SMALL_DISPATCH_OVERRIDE: "int | None" = None


def small_dispatch_default() -> int:
    """Width (bytes) below which device codecs answer reconstruct() on
    the host: reconstruct-on-read serves kilobyte needle ranges
    (server/volume_server._reconstruct_shard_range) and a full device
    round-trip per read would dominate the latency. Env-tunable, and
    superseded by the auto-tuner's override once one is applied."""
    if _SMALL_DISPATCH_OVERRIDE is not None:
        return _SMALL_DISPATCH_OVERRIDE
    return config.env_int("SW_EC_SMALL_DISPATCH_BYTES")


def small_dispatch_override() -> "int | None":
    return _SMALL_DISPATCH_OVERRIDE


def set_small_dispatch_override(nbytes: "int | None"):
    """Install (or clear, with None/0) the live hybrid-threshold
    override."""
    global _SMALL_DISPATCH_OVERRIDE
    _SMALL_DISPATCH_OVERRIDE = int(nbytes) if nbytes else None


def maybe_auto_apply_small_dispatch(suggestion: int) -> bool:
    """Apply the tuner's suggested threshold when the operator opted in
    via SW_EC_SMALL_DISPATCH_AUTO=1. Returns whether it was applied."""
    if not config.env_bool("SW_EC_SMALL_DISPATCH_AUTO"):
        return False
    set_small_dispatch_override(suggestion)
    return True


def dispatch_threshold(codec) -> int:
    """Live host/device crossover width for a codec: the
    SW_EC_SMALL_DISPATCH_AUTO fitted override (installed by the tuner
    via set_small_dispatch_override) supersedes whatever the codec
    snapshotted at construction, so a tuner suggestion applies without
    reconstructing the codec; host-only codecs
    (small_dispatch_bytes == 0) never delegate to the device."""
    if not codec.small_dispatch_bytes:
        return 0
    ov = small_dispatch_override()
    return ov if ov is not None else codec.small_dispatch_bytes


class _ConstCache:
    """Bounded LRU of device-resident coefficient constants, keyed by
    the coefficient bytes. A 256 MB rebuild must upload its ~14 KB
    bit-matrix once, not once per slab — every make() call counts as a
    bitmat_upload in ops/telemetry, so the bench can assert exactly
    that."""

    def __init__(self, maxsize: int = 32):
        self._entries: OrderedDict = OrderedDict()
        self._maxsize = maxsize
        from .device_stats import DEVICE_STATS
        DEVICE_STATS.register_const_cache(self)

    def get(self, key, make):
        from .device_stats import DEVICE_STATS
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
            DEVICE_STATS.note_const_cache("hits")
            return hit
        val = make()
        from .telemetry import STATS
        STATS.add("bitmat_uploads")
        DEVICE_STATS.note_const_cache("misses")
        self._entries[key] = val
        if len(self._entries) > self._maxsize:
            self._entries.popitem(last=False)
            DEVICE_STATS.note_const_cache("evictions")
        return val

    def occupancy(self) -> dict:
        """Entries and device bytes currently pinned (best-effort:
        constants without .nbytes count zero bytes)."""
        nbytes = 0
        for val in list(self._entries.values()):
            nbytes += int(getattr(val, "nbytes", 0) or 0)
        return {"entries": len(self._entries), "bytes": nbytes}


class ReedSolomonCodec:
    """Base class: matrix construction + reconstruction planning.

    Subclasses implement _matmul(coeffs, data) — the GF(2^8) matrix-vector
    product over byte rows — which is the only compute-heavy primitive.
    Device-backed subclasses additionally expose device_fn() so
    ops/pipeline.PipelinedMatmul can stream slabs through their kernel
    (encode and rebuild share the same pipelined hot path).
    """

    backend = "abstract"
    # 0 = never delegate; device codecs override with the env default
    small_dispatch_bytes = 0

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde"):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("data_shards and parity_shards must be > 0")
        if data_shards + parity_shards > 256:
            raise ValueError("k + m must be <= 256 in GF(2^8)")
        self.k = data_shards
        self.m = parity_shards
        self.total = data_shards + parity_shards
        self.matrix_kind = matrix_kind
        self.matrix = gf256.build_matrix(self.k, self.total, matrix_kind)
        self._decode_cache: dict = {}
        self._plan_cache: dict = {}
        self._syndrome_rows: Optional[np.ndarray] = None

    # -- primitive ---------------------------------------------------------
    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- device streaming hooks (ops/pipeline.PipelinedMatmul) -------------
    def device_fn(self, coeffs: np.ndarray, width: int):
        """Device-backed codecs return (jitted fn, device-resident
        constant, put) for `width`-wide slabs: ``fn(constant,
        put(slab))`` dispatches asynchronously and the constant stays
        resident across slabs. Host codecs return None (no pipeline)."""
        return None

    def pipeline_width_bucket(self, n: int, cap: int) -> int:
        """Bucket a slab width for compiled-executable reuse; mesh
        codecs additionally pad to their shard split."""
        from .rs_tpu import width_bucket
        return width_bucket(n, cap)

    # -- public API --------------------------------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        """data (k, n) uint8 -> parity (m, n) uint8."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.shape[0] != self.k:
            raise ValueError(f"expected {self.k} data rows, got {data.shape[0]}")
        return self._matmul(self.matrix[self.k:], data)

    def encode_to_all(self, data: np.ndarray) -> np.ndarray:
        """data (k, n) -> all shards (total, n); data rows verbatim."""
        parity = self.encode(data)
        return np.concatenate([np.asarray(data, dtype=np.uint8), parity], axis=0)

    def _decode_coeffs(self, present: tuple) -> tuple:
        """For a presence tuple, return (src_rows, inv_matrix) where
        data = inv_matrix @ shards[src_rows]."""
        key = present
        hit = self._decode_cache.get(key)
        if hit is not None:
            return hit
        src = [i for i, p in enumerate(present) if p][: self.k]
        if len(src) < self.k:
            raise ValueError(
                f"too few shards: have {sum(present)}, need {self.k}")
        sub = self.matrix[src, :]
        inv = gf256.mat_inv(sub)
        self._decode_cache[key] = (src, inv)
        return src, inv

    def decode_plan(self, present: tuple, data_only: bool = False) -> tuple:
        """Fused decode plan for a presence pattern: (src_rows, missing,
        coeffs) with coeffs (len(missing), k) such that ALL missing rows
        — data and parity stacked — come from ONE matmul against the
        first k survivors. Cached per (present, data_only) alongside
        _decode_cache, so steady-state rebuild pays zero GF planning per
        slab and exactly one device dispatch."""
        key = (tuple(present), bool(data_only))
        hit = self._plan_cache.get(key)
        if hit is not None:
            return hit
        src, inv = self._decode_coeffs(key[0])
        limit = self.k if data_only else self.total
        missing = [i for i in range(limit) if not present[i]]
        coeffs = gf256.decode_coeff_rows(self.matrix, self.k, src,
                                         missing, inv=inv)
        plan = (src, missing, coeffs)
        self._plan_cache[key] = plan
        return plan

    def lost_row_coeffs(self, present: tuple, sid: int) -> tuple:
        """Single-shard slice of the fused decode plan: (src_rows,
        coeffs) with coeffs (1, k) such that shard[sid] = coeffs @
        shards[src_rows]. Degraded reads regenerate exactly one lost
        row — the full plan's other missing rows would be wasted
        compute per request — while still riding the _plan_cache, so
        repeated reads of the same loss pattern pay zero GF planning."""
        src, missing, coeffs = self.decode_plan(tuple(present))
        if sid not in missing:
            raise ValueError(f"shard {sid} is not missing in {present}")
        r = missing.index(sid)
        return src, np.ascontiguousarray(coeffs[r:r + 1])

    def reconstruct(self, shards: Sequence[Optional[np.ndarray]],
                    data_only: bool = False) -> List[np.ndarray]:
        """Fill in missing (None) shards. Mirrors reference Reconstruct /
        ReconstructData. Returns the full shard list (data-only mode leaves
        missing parity as None).

        All missing rows are regenerated by a single fused matmul
        (decode_plan), and device codecs answer sub-small_dispatch_bytes
        widths on the host — reconstruct-on-read of a kilobyte range
        must not pay a device round-trip."""
        shards = list(shards)
        if len(shards) != self.total:
            raise ValueError(f"expected {self.total} shards, got {len(shards)}")
        present = tuple(s is not None for s in shards)
        if all(present):
            return shards
        lens = {s.shape[-1] for s in shards if s is not None}
        if len(lens) != 1:
            raise ValueError("surviving shards have differing lengths")
        from ..util import tracing
        with tracing.span("plan", backend=self.backend):
            src, missing, coeffs = self.decode_plan(present, data_only)
        if not missing:
            return shards
        survivors = np.stack([np.asarray(shards[i], dtype=np.uint8)
                              for i in src], axis=0)
        thr = self.small_dispatch_bytes
        if thr and _SMALL_DISPATCH_OVERRIDE is not None:
            # the auto-tuner's live override supersedes the snapshot
            # taken at construction; host-only codecs (thr == 0) keep
            # their never-delegate behavior
            thr = _SMALL_DISPATCH_OVERRIDE
        small = thr and survivors.shape[1] < thr
        # the reconstruct span's (bytes, seconds, path) tags feed the
        # SW_EC_SMALL_DISPATCH_BYTES tuner (stats.metrics.observe_span)
        with tracing.span("reconstruct", backend=self.backend,
                          bytes=int(survivors.nbytes),
                          path="host" if small else "device"):
            if small:
                from .telemetry import STATS
                STATS.add("host_fallbacks")
                out = host_matmul(coeffs, survivors)
            else:
                out = self._matmul(coeffs, survivors)
        for r, i in enumerate(missing):
            shards[i] = out[r]
        return shards

    def reconstruct_data(self, shards: Sequence[Optional[np.ndarray]]
                         ) -> List[np.ndarray]:
        return self.reconstruct(shards, data_only=True)

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        """True iff parity rows match the data rows."""
        data = np.stack([np.asarray(s, dtype=np.uint8)
                         for s in shards[: self.k]], axis=0)
        parity = self.encode(data)
        for i in range(self.m):
            if not np.array_equal(parity[i],
                                  np.asarray(shards[self.k + i], dtype=np.uint8)):
                return False
        return True

    def syndrome_plan(self) -> np.ndarray:
        """Parity-check rows H = [P | I_m], shape (m, k+m), derived from
        the cached encode matrix ([I_k; P] for systematic codes).

        For a consistent codeword column x (all k+m shard bytes at one
        offset), H @ x = P @ data XOR parity = 0 — GF(2^8) addition IS
        subtraction, so the identity block needs no negation. Any
        nonzero syndrome byte pins corruption to that byte column, and
        the scrub verifies a whole slab as ONE (m, k+m) x (k+m, w)
        fused matmul — the same PipelinedMatmul hot path encode and
        rebuild ride, with coefficients swapped. Cached like the decode
        plans: steady-state scrub pays zero GF planning per slab."""
        if self._syndrome_rows is None:
            h = np.zeros((self.m, self.total), dtype=np.uint8)
            h[:, : self.k] = self.matrix[self.k:]
            h[:, self.k:] = np.eye(self.m, dtype=np.uint8)
            self._syndrome_rows = np.ascontiguousarray(h)
        return self._syndrome_rows


class NumpyCodec(ReedSolomonCodec):
    """Pure-numpy reference backend — the conformance oracle.

    Inner loop: one 256-entry LUT gather + XOR per (output row, input row)
    pair, equivalent to the reference dependency's galMulSlice without SIMD.
    """

    backend = "numpy"

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        return host_matmul(coeffs, data)


_TPU_PROBE_RESULT = None


def _tpu_present(timeout_s: float = 60.0) -> bool:
    """Watchdogged TPU probe: jax.devices() can hang forever when the
    device tunnel is broken, and a hung probe must not take the whole
    server down with it. Result is cached for the process."""
    global _TPU_PROBE_RESULT
    if _TPU_PROBE_RESULT is not None:
        return _TPU_PROBE_RESULT
    import threading
    result = {}

    def probe():
        try:
            import jax
            result["tpu"] = any(d.platform == "tpu" for d in jax.devices())
        except Exception:
            result["tpu"] = False

    th = threading.Thread(target=probe, daemon=True,
                          name="device-init-probe")
    th.start()
    th.join(timeout_s)
    _TPU_PROBE_RESULT = bool(result.get("tpu", False))
    return _TPU_PROBE_RESULT


def get_codec(data_shards: int, parity_shards: int,
              backend: str = "auto",
              matrix_kind: str = "vandermonde") -> ReedSolomonCodec:
    if backend == "auto":
        from .rs_native import native_available
        if _tpu_present():
            backend = "tpu"
        elif native_available():
            backend = "native"
        else:
            backend = "numpy"
    if backend == "numpy":
        return NumpyCodec(data_shards, parity_shards, matrix_kind)
    if backend == "native":
        from .rs_native import NativeCodec
        return NativeCodec(data_shards, parity_shards, matrix_kind)
    if backend == "tpu":
        from .rs_tpu import TpuCodec
        return TpuCodec(data_shards, parity_shards, matrix_kind)
    if backend == "mesh":
        # SPMD over every visible device (multi-chip hosts); same
        # programs the multichip dryrun validates on a virtual mesh
        from ..parallel.mesh_codec import MeshCodec
        return MeshCodec(data_shards, parity_shards, matrix_kind)
    raise ValueError(f"unknown backend {backend!r}")


# ---------------------------------------------------------------------------
# Trace repair of a single lost shard (arxiv 2205.11015).
#
# A dual codeword g satisfies sum_i g[i]*c_i = 0 over every stripe, so
#     Tr(g[lost]*c_lost) = sum_{i != lost} Tr(g[i]*c_i).
# Pick 8 dual codewords whose values at the lost position are
# GF(2)-independent and every bit of c_lost is a GF(2) combination of
# the trace bits Tr(g_j[i]*c_i).  Helper i only has to ship
# t_i = dim_2 span{g_j[i]} bits per byte — its projection onto a
# reduced basis of that span — instead of all 8, which is where the
# sub-k*slab repair bandwidth comes from.  The rebuilder's combine is a
# {0,1}-coefficient GF(2^8) matmul (XOR of bit-planes), so the existing
# pipelined device kernels run it unchanged: one dispatch per slab.
# ---------------------------------------------------------------------------

REPAIR_MAX_SUBSETS = 400   # cap on vanish-subset enumeration (RS(20,4))
REPAIR_RESTARTS = 3        # greedy restarts with shuffled candidate order


@dataclass(frozen=True, eq=False)
class RepairPlan:
    """Single-lost-shard trace-repair scheme for one geometry.

    helpers lists the shard ids that must be contacted (t_i > 0 only);
    masks[sid] are the GF(2^8) projection masks that holder applies
    (one packed bit-plane per mask); combine is the (8, total_bits)
    {0,1} matrix that XORs the concatenated symbol planes back into
    the lost shard's 8 bit-planes, in helpers-then-mask order.
    """

    k: int
    m: int
    lost: int
    helpers: Tuple[int, ...]
    masks: Dict[int, Tuple[int, ...]] = field(hash=False)
    combine: np.ndarray = field(hash=False)
    matrix_kind: str = "vandermonde"

    @property
    def total_bits(self) -> int:
        return sum(len(v) for v in self.masks.values())

    @property
    def frac(self) -> float:
        """Repair symbol bits per stripe byte vs the k-byte baseline."""
        return self.total_bits / (8.0 * self.k)

    def bits_for(self, sid: int) -> int:
        return len(self.masks[sid])

    def wire_bytes(self, width: int) -> int:
        """Bytes on the wire for a width-byte slab range (all helpers,
        packed planes; excludes HTTP framing)."""
        return self.total_bits * ((width + 7) // 8)


def project_slab(data: np.ndarray, masks) -> np.ndarray:
    """Holder-side projection: trace bits Tr(mask * data) packed
    little-bit-first per mask. data (w,) uint8 -> (len(masks),
    ceil(w/8)) uint8. One LUT gather + packbits — cheap enough to run
    on the volume server's host CPU."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m = np.asarray(list(masks), dtype=np.uint8)
    bits = gf256.TRACE_MUL[m[:, None], data[None, :]]
    return np.packbits(bits, axis=1, bitorder="little")


def combine_planes_to_bytes(planes: np.ndarray, width: int) -> np.ndarray:
    """Rebuilder-side interleave: 8 packed output bit-planes (8,
    ceil(width/8)) -> the lost shard's bytes (width,). Plane b holds
    bit b of every output byte."""
    bits = np.unpackbits(np.ascontiguousarray(planes, dtype=np.uint8),
                         axis=1, count=width, bitorder="little")
    return np.packbits(bits, axis=0, bitorder="little").reshape(-1)


_REPAIR_PLAN_CACHE: dict = {}


def repair_plan(k: int, m: int, lost_sid: int, survivors=None,
                matrix_kind: str = "vandermonde",
                matrix: "np.ndarray | None" = None,
                seed: int = 0) -> RepairPlan:
    """Build (and cache) the trace-repair scheme for one lost shard.

    survivors: iterable of reachable shard ids (default: all others).
    Unreachable positions are handled by forcing every dual codeword to
    vanish there, which needs n - 1 - len(survivors) <= m - 1; with
    fewer survivors than k the code cannot repair at all and this
    raises ValueError.

    The scheme search enumerates dual codewords supported off an
    (m-1)-subset of positions (nullspace of the transposed generator
    restricted to the complement), scales each by all 255 nonzero
    constants, and greedily picks 8 equations minimizing the total
    per-helper GF(2) span growth — deterministic for a given seed, so
    every process derives the identical plan.
    """
    n = k + m
    if not (0 <= lost_sid < n):
        raise ValueError(f"lost shard {lost_sid} outside 0..{n - 1}")
    if survivors is None:
        survivors = [i for i in range(n) if i != lost_sid]
    helpers = sorted(set(int(s) for s in survivors) - {lost_sid})
    unavailable = [i for i in range(n) if i != lost_sid and i not in helpers]
    if len(unavailable) > m - 1:
        raise ValueError(
            f"too few survivors: {len(helpers)} reachable, need >= {k}")
    key = (k, m, lost_sid, tuple(helpers), matrix_kind,
           None if matrix is None else matrix.tobytes(), seed)
    hit = _REPAIR_PLAN_CACHE.get(key)
    if hit is not None:
        return hit
    if matrix is None:
        matrix = gf256.build_matrix(k, n, matrix_kind)

    # -- candidate dual codewords: vanish on unavailable + an
    #    (m-1-|unavailable|)-subset of helpers ---------------------------
    free = m - 1 - len(unavailable)
    subsets = list(itertools.combinations(helpers, free))
    rng = np.random.default_rng(seed)
    if len(subsets) > REPAIR_MAX_SUBSETS:
        idx = rng.choice(len(subsets), size=REPAIR_MAX_SUBSETS,
                         replace=False)
        subsets = [subsets[i] for i in sorted(idx)]
    base = []
    for sub in subsets:
        vanish = set(unavailable) | set(sub)
        support = [i for i in range(n) if i not in vanish]
        g_u = gf256.gf_nullspace(matrix[support, :].T)
        if g_u is None:
            continue
        g = np.zeros(n, dtype=np.uint8)
        g[support] = g_u
        if g[lost_sid] == 0:
            continue
        base.append(g)
    if not base:
        raise ValueError("no usable dual codewords for this geometry")
    base = np.stack(base, axis=0)
    betas = np.arange(1, 256, dtype=np.uint8)
    cand = gf256.MUL_TABLE[betas[None, :, None], base[:, None, :]]
    cand = cand.reshape(-1, n)

    # -- greedy scheme selection (restarts keep the best) ----------------
    best = None
    for r in range(REPAIR_RESTARTS):
        order = rng.permutation(cand.shape[0]) if r else \
            np.arange(cand.shape[0])
        cv = cand[order]
        chosen = []
        star_basis: list = []
        pos_basis = {i: [] for i in helpers}
        total = 0
        for _ in range(8):
            ok = gf256.gf2_reduce(cv[:, lost_sid], star_basis) != 0
            cost = np.zeros(cv.shape[0], dtype=np.int32)
            for i in helpers:
                cost += (gf256.gf2_reduce(cv[:, i], pos_basis[i]) != 0
                         ).astype(np.int32)
            c = int(np.argmin(np.where(ok, cost, np.int32(1 << 20))))
            chosen.append(cv[c].copy())
            gf256.gf2_insert(star_basis, int(cv[c, lost_sid]))
            for i in helpers:
                if gf256.gf2_insert(pos_basis[i], int(cv[c, i])):
                    total += 1
        if best is None or total < best[0]:
            best = (total, chosen, {i: list(pos_basis[i]) for i in helpers})

    _, chosen, bases = best
    active = [i for i in helpers if bases[i]]
    masks = {i: tuple(bases[i]) for i in active}

    # -- combine matrix: bits(c_lost) = inv(A) @ Lambda @ sigma ----------
    a = np.zeros((8, 8), dtype=np.uint8)
    for j, g in enumerate(chosen):
        for b in range(8):
            a[j, b] = gf256.TRACE_MUL[int(g[lost_sid]), 1 << b]
    lam = np.zeros((8, sum(len(masks[i]) for i in active)), dtype=np.uint8)
    for j, g in enumerate(chosen):
        col = 0
        for i in active:
            coords = gf256.gf2_decompose(int(g[i]), masks[i])
            lam[j, col:col + len(coords)] = coords
            col += len(coords)
    combine = (gf256.gf2_mat_inv(a).astype(np.int32) @
               lam.astype(np.int32)) % 2
    plan = RepairPlan(k=k, m=m, lost=lost_sid, helpers=tuple(active),
                      masks=masks, combine=combine.astype(np.uint8),
                      matrix_kind=matrix_kind)
    _REPAIR_PLAN_CACHE[key] = plan
    return plan


def repair_gain(plan: RepairPlan) -> float:
    """Fraction of the k*slab baseline saved by trace repair
    (0 = no gain; ec.rebuild -repair auto requires > 0)."""
    return 1.0 - plan.frac
