"""Device-dispatch counters for the EC hot paths.

The round-5 bench showed the mesh rebuild at 2 MB/s with
compute_frac=0.99 — pure dispatch overhead (per-slab bitmat re-lift +
re-upload, two matmuls per slab, no overlap), not GF math. These
counters make that overhead *observable*: every device dispatch,
bit-matrix upload and host-path small-read fallback increments a
process-global counter, and rebuild_ec_files / bench.py report the
deltas (`dispatches`, `bitmat_uploads`) so a regression back to
per-slab uploads shows up in `vs_baseline` instead of hiding inside
wall time.

Mesh-sharded dispatches additionally record which devices a put
actually landed bytes on (`mesh_dispatches`, per-device byte map).
That is the width guard: a MeshCodec built over a width-1 mesh (or a
crossover silently routing everything to the single-device path)
compiles, runs, and is bit-identical — only the per-device byte map
distinguishes it from a dispatch that saturated the mesh, so
`delta()` derives `dispatch_width_devices` / `device_busy_frac` from
it and the bench asserts on them.
"""

from __future__ import annotations

from typing import Dict

from ..util.locks import make_lock


class DispatchStats:
    """Monotonic process-global counters (thread-safe)."""

    _FIELDS = ("dispatches", "bitmat_uploads", "host_fallbacks",
               "device_bytes", "mesh_dispatches")

    def __init__(self):
        self._lock = make_lock("telemetry._lock")
        for f in self._FIELDS:
            setattr(self, f, 0)
        self._mesh_device_bytes: Dict[str, int] = {}

    def add(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def add_mesh_device_bytes(self, device: str, n: int):
        """Payload bytes a sharded put landed on one device."""
        with self._lock:
            self._mesh_device_bytes[device] = \
                self._mesh_device_bytes.get(device, 0) + n

    def snapshot(self) -> dict:
        with self._lock:
            snap = {f: getattr(self, f) for f in self._FIELDS}
            snap["mesh_device_bytes"] = dict(self._mesh_device_bytes)
            return snap


STATS = DispatchStats()


def delta(before: dict) -> dict:
    """Counter movement since a snapshot() — the per-operation report.

    Besides the raw field deltas, derives the mesh width facts the
    bench guards on: `dispatch_width_devices` (devices a sharded put
    landed bytes on during the window; 1 when only single-device
    dispatches ran, 0 when none did) and `device_busy_frac` (each
    device's byte share relative to the busiest — 1.0 everywhere means
    a perfectly even shard split)."""
    now = STATS.snapshot()
    out = {f: now[f] - before.get(f, 0) for f in DispatchStats._FIELDS}
    before_dev = before.get("mesh_device_bytes", {})
    per_dev = {}
    for dev, n in now["mesh_device_bytes"].items():
        moved = n - before_dev.get(dev, 0)
        if moved > 0:
            per_dev[dev] = moved
    out["mesh_device_bytes"] = per_dev
    if per_dev:
        peak = max(per_dev.values())
        out["dispatch_width_devices"] = len(per_dev)
        out["device_busy_frac"] = {d: round(n / peak, 4)
                                   for d, n in sorted(per_dev.items())}
    else:
        out["dispatch_width_devices"] = 1 if out["dispatches"] > 0 else 0
        out["device_busy_frac"] = {}
    return out
