"""Device-dispatch counters for the EC hot paths.

The round-5 bench showed the mesh rebuild at 2 MB/s with
compute_frac=0.99 — pure dispatch overhead (per-slab bitmat re-lift +
re-upload, two matmuls per slab, no overlap), not GF math. These
counters make that overhead *observable*: every device dispatch,
bit-matrix upload and host-path small-read fallback increments a
process-global counter, and rebuild_ec_files / bench.py report the
deltas (`dispatches`, `bitmat_uploads`) so a regression back to
per-slab uploads shows up in `vs_baseline` instead of hiding inside
wall time.
"""

from __future__ import annotations

import threading
from ..util.locks import make_lock


class DispatchStats:
    """Monotonic process-global counters (thread-safe)."""

    _FIELDS = ("dispatches", "bitmat_uploads", "host_fallbacks",
               "device_bytes")

    def __init__(self):
        self._lock = make_lock("telemetry._lock")
        for f in self._FIELDS:
            setattr(self, f, 0)

    def add(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> dict:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}


STATS = DispatchStats()


def delta(before: dict) -> dict:
    """Counter movement since a snapshot() — the per-operation report."""
    now = STATS.snapshot()
    return {f: now[f] - before.get(f, 0) for f in DispatchStats._FIELDS}
