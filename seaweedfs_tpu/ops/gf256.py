"""GF(2^8) arithmetic over the polynomial x^8+x^4+x^3+x^2+1 (0x11D).

This is the field used by the reference's Reed-Solomon dependency
(klauspost/reedsolomon, imported at reference
weed/storage/erasure_coding/ec_encoder.go:8): generator element 2,
field polynomial 0x11D. Tables are built once at import with numpy.

Matrix builders:
  * vandermonde_systematic(k, total) — the reference dependency's default
    encoding matrix: a (total x k) Vandermonde matrix right-multiplied by the
    inverse of its top square, so the top k rows are the identity (systematic
    code: data shards are stored verbatim, parity rows below).
  * cauchy(k, total) — identity on top, parity rows m[r][c] = 1/(r ^ c);
    supports any geometry with k + m <= 256 (BASELINE config 4: RS(6,3),
    RS(20,4)).
"""

from __future__ import annotations

import numpy as np

FIELD_POLY = 0x11D
GENERATOR = 2


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= FIELD_POLY
    # duplicate so exp[(log a + log b)] needs no mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = -1  # sentinel; never indexed on the hot path
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


def _build_mul_table():
    # 256x256 full multiplication table — the numpy codec's inner loop is a
    # single row-gather MUL_TABLE[coeff][data].
    a = np.arange(256, dtype=np.int32)
    la = LOG_TABLE[a][:, None]  # (256,1)
    lb = LOG_TABLE[a][None, :]  # (1,256)
    t = EXP_TABLE[(la + lb) % 255]
    t = t.astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    return t


MUL_TABLE = _build_mul_table()
INV_TABLE = np.zeros(256, dtype=np.uint8)
INV_TABLE[1:] = EXP_TABLE[255 - LOG_TABLE[np.arange(1, 256)]]


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(INV_TABLE[a])


def gf_pow(a: int, n: int) -> int:
    """a**n in GF(2^8). 0**0 == 1 (matches the reference dependency)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8) (small matrices: k+m <= 256)
# ---------------------------------------------------------------------------

def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(r x n) @ (n x c) over GF(2^8)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    r, n = a.shape
    n2, c = b.shape
    assert n == n2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        # gather-per-coefficient, XOR-accumulate
        acc = np.zeros(c, dtype=np.uint8)
        for j in range(n):
            acc ^= MUL_TABLE[a[i, j]][b[j]]
        out[i] = acc
    return out


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^8). Raises ValueError if singular."""
    m = np.array(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # find pivot
        piv = -1
        for row in range(col, n):
            if aug[row, col] != 0:
                piv = row
                break
        if piv < 0:
            raise ValueError("singular matrix over GF(2^8)")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        # scale pivot row to 1
        inv_p = INV_TABLE[aug[col, col]]
        aug[col] = MUL_TABLE[inv_p][aug[col]]
        # eliminate other rows
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= MUL_TABLE[aug[row, col]][aug[col]]
    return aug[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    v = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            v[r, c] = gf_pow(r, c)
    return v


def vandermonde_systematic(data_shards: int, total_shards: int) -> np.ndarray:
    """The reference dependency's default encode matrix (systematic form)."""
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards, :]
    return mat_mul(vm, mat_inv(top))


def cauchy(data_shards: int, total_shards: int) -> np.ndarray:
    m = np.zeros((total_shards, data_shards), dtype=np.uint8)
    for i in range(data_shards):
        m[i, i] = 1
    for r in range(data_shards, total_shards):
        for c in range(data_shards):
            m[r, c] = INV_TABLE[r ^ c]
    return m


def build_matrix(data_shards: int, total_shards: int,
                 kind: str = "vandermonde") -> np.ndarray:
    if not (0 < data_shards < total_shards <= 256):
        raise ValueError(f"bad geometry k={data_shards} total={total_shards}")
    if kind == "vandermonde":
        return vandermonde_systematic(data_shards, total_shards)
    if kind == "cauchy":
        return cauchy(data_shards, total_shards)
    raise ValueError(f"unknown matrix kind {kind!r}")


# ---------------------------------------------------------------------------
# GF(2) bit-plane expansion — the bridge to the TPU kernel.
#
# Multiplication by a constant c in GF(2^8) is linear over GF(2)^8, so the
# whole (total x k) byte matrix lifts to a (8k x 8(total-k)) binary matrix and
# RS encoding becomes a {0,1} matmul followed by mod-2 — which is exactly an
# MXU-shaped op on TPU (see ops/rs_tpu.py).
# ---------------------------------------------------------------------------

def bit_matrix(coeff_rows: np.ndarray) -> np.ndarray:
    """Lift a (rows x cols) GF(2^8) coefficient matrix to GF(2).

    Returns B of shape (cols*8, rows*8), uint8 in {0,1}, such that for input
    bits x (n, cols*8) (bit l of input byte j at column j*8+l, LSB-first) the
    output bits are (x @ B) % 2 with output byte i's bit b at column i*8+b.
    """
    coeff_rows = np.asarray(coeff_rows, dtype=np.uint8)
    rows, cols = coeff_rows.shape
    b = np.zeros((cols * 8, rows * 8), dtype=np.uint8)
    for i in range(rows):
        for j in range(cols):
            c = int(coeff_rows[i, j])
            if c == 0:
                continue
            for l in range(8):
                prod = MUL_TABLE[c, 1 << l]  # c * x^l
                for k in range(8):
                    if (prod >> k) & 1:
                        b[j * 8 + l, i * 8 + k] = 1
    return b


def pack_bit_matrix(coeff_rows: np.ndarray) -> np.ndarray:
    """bit_matrix with the input-bit axis packed into uint32 words.

    Returns P of shape (ceil(cols*8/32), rows*8) uint32 where bit
    (j % 32) of P[j // 32, o] is bit_matrix[j, o]. With payload columns
    packed the same way (4 consecutive byte rows -> one uint32, byte j
    at bit offset 8*(j % 4)), output bit o of a column is
    parity(popcount(x & P[:, o])) — the AND/popcount form of the GF(2)
    matmul that CPU backends run ~2 orders of magnitude faster than the
    8x-lifted int8 dot (ops/rs_tpu.py chooses per platform).
    """
    bm = bit_matrix(coeff_rows)
    k8, r8 = bm.shape
    packed = np.zeros(((k8 + 31) // 32, r8), dtype=np.uint32)
    for j in range(k8):
        packed[j // 32] |= bm[j].astype(np.uint32) << np.uint32(j % 32)
    return packed


# ---------------------------------------------------------------------------
# Field trace and GF(2) linear algebra — the substrate of trace repair.
#
# Tr(x) = x + x^2 + ... + x^128 maps GF(2^8) onto GF(2), and
# Tr(a*x) is GF(2)-linear in x for any fixed a.  A lost RS symbol can
# therefore be rebuilt from *bits* Tr(mask * c_i) collected from the
# survivors instead of their full bytes (arxiv 2205.11015); the masks
# come from dual codewords, found below via gf_nullspace.
# ---------------------------------------------------------------------------

def _build_trace_table():
    x = np.arange(256, dtype=np.uint8)
    acc = x.copy()
    cur = x.copy()
    for _ in range(7):
        cur = MUL_TABLE[cur, cur]
        acc ^= cur
    assert set(np.unique(acc)) <= {0, 1}
    return acc


TRACE_TABLE = _build_trace_table()
# TRACE_MUL[a, b] = Tr(a*b) in {0,1} — the survivor-side projection is a
# single row-gather of this table followed by packbits.
TRACE_MUL = TRACE_TABLE[MUL_TABLE]


def gf_trace(a: int) -> int:
    return int(TRACE_TABLE[a])


def gf_nullspace(a: np.ndarray):
    """One nullspace vector of a (r x c, r < c) matrix over GF(2^8),
    or None if the map is injective. Used by ops/codec.repair_plan to
    produce dual codewords vanishing on a chosen position subset."""
    a = np.array(a, dtype=np.uint8)
    r, c = a.shape
    piv_of_col = {}
    row = 0
    for col in range(c):
        piv = None
        for rr in range(row, r):
            if a[rr, col]:
                piv = rr
                break
        if piv is None:
            continue
        if piv != row:
            a[[row, piv]] = a[[piv, row]]
        inv = INV_TABLE[a[row, col]]
        a[row] = MUL_TABLE[inv][a[row]]
        for rr in range(r):
            if rr != row and a[rr, col]:
                a[rr] ^= MUL_TABLE[a[rr, col]][a[row]]
        piv_of_col[col] = row
        row += 1
        if row == r:
            break
    free = [col for col in range(c) if col not in piv_of_col]
    if not free:
        return None
    f = free[0]
    x = np.zeros(c, dtype=np.uint8)
    x[f] = 1
    for col, rr in piv_of_col.items():
        x[col] = a[rr, f]  # char 2: -v == v
    return x


def gf2_reduce(vals: np.ndarray, basis) -> np.ndarray:
    """Reduce uint8 values by a reduced GF(2) basis of field elements
    (distinct leading bits, descending). Vectorized over vals."""
    v = vals.copy()
    for b in basis:
        lead = b.bit_length() - 1
        mask = ((v >> lead) & 1).astype(bool)
        v[mask] ^= b
    return v


def gf2_insert(basis: list, val: int) -> bool:
    """Insert val into a reduced GF(2) basis in place; True if the
    span grew."""
    for b in basis:
        lead = b.bit_length() - 1
        if (val >> lead) & 1:
            val ^= b
    if val:
        basis.append(int(val))
        basis.sort(reverse=True)
        return True
    return False


def gf2_decompose(val: int, basis) -> list:
    """Coordinates of val over a reduced GF(2) basis (same order as
    basis). Raises ValueError when val is outside the span."""
    coords = [0] * len(basis)
    for i, b in enumerate(basis):
        lead = b.bit_length() - 1
        if (val >> lead) & 1:
            val ^= b
            coords[i] = 1
    if val:
        raise ValueError("value outside GF(2) span")
    return coords


def gf2_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse of a {0,1} matrix over GF(2)."""
    m = np.array(m, dtype=np.uint8) & 1
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = -1
        for row in range(col, n):
            if aug[row, col]:
                piv = row
                break
        if piv < 0:
            raise ValueError("singular matrix over GF(2)")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= aug[col]
    return aug[:, n:].copy()


def decode_coeff_rows(matrix: np.ndarray, k: int, survivor_rows,
                      missing_rows, inv: np.ndarray = None) -> np.ndarray:
    """Fused decode plan: (len(missing_rows), k) GF coefficients C such
    that missing = C @ stack(first k surviving shards).

    Data rows come from the inverse of the first-k-survivors submatrix,
    parity rows from matrix[row] @ that inverse — one derivation shared
    by ReedSolomonCodec.decode_plan, ec/encoder._rebuild_coeffs and
    parallel/sharded_ec.decode_bitmat, so the three call sites cannot
    drift apart.
    """
    src = list(survivor_rows)[:k]
    if inv is None:
        inv = mat_inv(matrix[src, :])
    rows = []
    for r in missing_rows:
        if r < k:
            rows.append(inv[r])
        else:
            rows.append(mat_mul(matrix[r:r + 1, :], inv)[0])
    if not rows:
        return np.zeros((0, k), dtype=np.uint8)
    return np.stack(rows, axis=0)
