"""ops — GF(2^8) arithmetic and Reed-Solomon codec backends.

Backends:
  numpy  — pure-numpy reference implementation (conformance oracle)
  native — C++ shared library (auto-vectorized), the CPU production path
  tpu    — JAX/XLA bit-plane matmul on the MXU (the north star)

All backends are bit-identical; see tests/test_rs_codec.py.
"""

from .codec import get_codec, ReedSolomonCodec  # noqa: F401
