"""C++ native codec bridge (ctypes).

The production CPU path, replacing the reference's SIMD assembly dependency
(klauspost/reedsolomon, reference go.mod:47). The shared library lives at
ops/native/libseaweed_ec.so and is built by ops/native/build.sh with g++
auto-vectorization; falls back to the numpy backend when absent.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from .codec import ReedSolomonCodec

_LIB_PATH = os.path.join(os.path.dirname(__file__), "native",
                         "libseaweed_ec.so")
_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.sw_ec_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),  # coeffs (r*k)
            ctypes.c_int,                    # r
            ctypes.c_int,                    # k
            ctypes.POINTER(ctypes.c_uint8),  # data (k*n)
            ctypes.c_longlong,               # n
            ctypes.POINTER(ctypes.c_uint8),  # out (r*n)
        ]
        lib.sw_ec_matmul.restype = None
        try:
            lib.sw_ec_matmul_mt.argtypes = (
                lib.sw_ec_matmul.argtypes + [ctypes.c_int])  # nthreads
            lib.sw_ec_matmul_mt.restype = None
        except AttributeError:
            pass  # pre-threading .so still on disk; rebuild to enable
        _lib = lib
    except OSError:
        _load_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


class NativeCodec(ReedSolomonCodec):
    """threads: 0 = hardware concurrency (matches the reference dependency's
    multi-goroutine default), 1 = single-threaded, n = exactly n."""

    backend = "native"

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde", threads: int = 0):
        super().__init__(data_shards, parity_shards, matrix_kind)
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError(
                f"native EC library not built at {_LIB_PATH}; "
                "run seaweedfs_tpu/ops/native/build.sh")
        self.threads = threads

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = coeffs.shape
        n = data.shape[1]
        out = np.zeros((r, n), dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        use_mt = self.threads != 1 and hasattr(self._lib, "sw_ec_matmul_mt")
        if use_mt:
            self._lib.sw_ec_matmul_mt(
                coeffs.ctypes.data_as(u8p), r, k,
                data.ctypes.data_as(u8p), n,
                out.ctypes.data_as(u8p), self.threads)
        else:
            self._lib.sw_ec_matmul(
                coeffs.ctypes.data_as(u8p), r, k,
                data.ctypes.data_as(u8p), n,
                out.ctypes.data_as(u8p))
        return out
