"""Pipelined host↔device streaming for the TPU EC path.

The reference's encode hot loop (reference ec_encoder.go:192-229) is a
synchronous read→GF→write cycle per 256KB batch. The TPU-first design
(SURVEY hard part #3) overlaps four stages instead:

    disk read (reader thread) → h2d + MXU dispatch (async) → d2h drain →
    shard-file write

JAX dispatch is asynchronous: ``fn(bitmat, dev)`` returns a future-like
device array immediately, so keeping a bounded deque of in-flight slabs
means the device computes slab t+1..t+depth while the host blocks on
fetching slab t's output and writing files. The reader thread overlaps
disk I/O with everything else (file reads release the GIL).

PipelinedMatmul computes ``coeffs @ data`` over GF(2^8) for a stream of
data slabs with a fixed coefficient matrix — encode (coeffs = parity
rows) and rebuild (coeffs = fused decode-plan rows vs survivors) both
reduce to this. Only the r output rows round-trip back to the host; for
encode that is m/k of the h2d traffic.

The device kernel is pluggable: pass ``codec`` and the stream runs
through ``codec.device_fn()`` — single-chip TpuCodec and the SPMD
MeshCodec (sharded payloads, replicated device-resident coefficients)
both pipeline through the same loop. Without a codec the single-device
rs_tpu kernel is used directly (bench/raw callers).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from .rs_tpu import fn_and_bitmat, width_bucket
from .telemetry import STATS
from ..util.profiling import StageTimer

_SENTINEL = object()


class PipelinedMatmul:
    """Streams (meta, data (k, w) uint8) slabs through a device GF matmul.

    stream() yields (meta, data, out (r, w)) in input order with up to
    ``depth`` slabs in flight on the device and ``prefetch`` slabs of
    read-ahead in the reader queue.
    """

    def __init__(self, coeffs: np.ndarray,
                 max_width: int = 32 << 20, depth: int = 4,
                 prefetch: int = 3, drain_threads: int = 2,
                 timer: Optional[StageTimer] = None,
                 codec=None, pieces: bool = False):
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        self.r, self.k = coeffs.shape
        self.max_width = int(max_width)
        self.depth = int(depth)
        self.prefetch = int(prefetch)
        self.drain_threads = int(drain_threads)
        self.timer = timer  # optional per-stage breakdown (bench/profiling)
        self.codec = codec  # device fn + shardings come from the codec
        # pieces mode: stream() yields (meta, data, [(col_off, piece)])
        # instead of one (r, w) array — mesh-sharded outputs drain one
        # piece per device shard (codec.drain_pieces) so consumers start
        # on the first device's stripes without the host ever staging
        # the full slab; codecs without drain_pieces yield one piece
        self.pieces = bool(pieces)
        self._coeffs = coeffs
        self._bitmat_dev = None
        self._put = None

    def _bucket(self, width: int) -> int:
        if self.codec is not None:
            return self.codec.pipeline_width_bucket(width, self.max_width)
        return width_bucket(width, self.max_width)

    def _fn(self, width: int):
        """Kernel for this width from the codec (mesh-sharded program
        with device-resident replicated coefficients, or the single-chip
        kernel) or, codec-less, the platform rs_tpu kernel (fused Pallas
        on TPU, packed-popcount XLA elsewhere). Constants upload on
        first use — the choice must happen at stream time, after the
        backend is known."""
        if self.codec is not None:
            fn, self._bitmat_dev, self._put = \
                self.codec.device_fn(self._coeffs, width)
            return fn
        fn, bitmat_np = fn_and_bitmat(self._coeffs, width)
        if self._bitmat_dev is None:
            import jax.numpy as jnp
            self._bitmat_dev = jnp.asarray(bitmat_np)
            STATS.add("bitmat_uploads")
        return fn

    def stream(self, slabs: Iterable[Tuple[object, np.ndarray]]
               ) -> Iterator[Tuple[object, np.ndarray, np.ndarray]]:
        import jax.numpy as jnp

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        err: list = []
        stop = threading.Event()

        def produce():
            try:
                for item in slabs:
                    if stop.is_set():
                        break
                    q.put(item)
            except BaseException as e:  # noqa: BLE001 - relay to consumer
                err.append(e)
            finally:
                q.put(_SENTINEL)

        reader = threading.Thread(target=produce, daemon=True,
                                  name="pipeline-producer")
        reader.start()

        # d2h runs in a small pool so fetches start the moment each
        # output is dispatched instead of serializing behind the next
        # dispatch (host↔device links degrade badly when a single thread
        # interleaves uploads and downloads)
        drain_pool = ThreadPoolExecutor(max_workers=self.drain_threads)
        pending: deque = deque()
        timer = self.timer
        drain_pieces = getattr(self.codec, "drain_pieces", None) \
            if self.pieces else None

        def fetch(out, nbytes, w):
            t = time.perf_counter() if timer is not None else 0.0
            if drain_pieces is not None:
                host = drain_pieces(out, w)
            elif self.pieces:
                full = np.asarray(out)
                host = [(0, full[:, :w] if full.shape[1] > w else full)]
            else:
                host = np.asarray(out)
            if timer is not None:
                end = time.perf_counter()
                timer.add("d2h+mxu", end - t, nbytes, interval=(t, end))
            return host

        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                if timer is not None:
                    timer.add("read_wait", time.perf_counter() - t0)
                if item is _SENTINEL:
                    break
                meta, data = item
                w = data.shape[1]
                if w > self.max_width:
                    raise ValueError(
                        f"slab width {w} exceeds max_width {self.max_width}")
                bucket = self._bucket(w)
                if w < bucket:
                    padded = np.zeros((self.k, bucket), dtype=np.uint8)
                    padded[:, :w] = data
                else:
                    padded = data
                fn = self._fn(bucket)                # also uploads bitmat
                put = self._put or jnp.asarray
                t0 = time.perf_counter()
                dev = put(padded)                    # h2d (blocking copy)
                if timer is not None:
                    end = time.perf_counter()
                    timer.add("h2d", end - t0, padded.nbytes,
                              interval=(t0, end))
                STATS.add("dispatches")
                STATS.add("device_bytes", data.nbytes)
                out = fn(self._bitmat_dev, dev)      # async dispatch
                fut = drain_pool.submit(fetch, out, self.r * bucket, w)
                pending.append((meta, data, fut, w))
                if len(pending) >= self.depth:
                    yield self._drain(pending.popleft())
            while pending:
                yield self._drain(pending.popleft())
            if err:
                raise err[0]
        finally:
            drain_pool.shutdown(wait=False)
            # stop the reader (at most one more in-flight slab) and
            # unblock it if the consumer bailed early
            stop.set()
            while reader.is_alive():
                try:
                    q.get(timeout=0.1)
                except queue.Empty:
                    pass
            reader.join(timeout=10)

    def _drain(self, entry):
        meta, data, fut, w = entry
        t0 = time.perf_counter()
        full = fut.result()  # blocks until device + d2h complete
        if self.timer is not None:
            self.timer.add("drain_wait", time.perf_counter() - t0)
        if self.pieces:
            return meta, data, full  # already clipped to w by fetch
        if full.shape[1] != w:
            full = full[:, :w]
        return meta, data, full
