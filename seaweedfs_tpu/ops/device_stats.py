"""Device-runtime observability plane: compile vs execute, split open.

Every jitted EC entry point (the `rs_tpu` factories, `rs_pallas`'s
fused kernel, `MeshCodec._fn`, the sharded encode/rebuild programs —
and `PipelinedMatmul` transitively through all of them) routes its
compiled-executable lifecycle through this module via `wrap()`:

- **Explicit compile/execute separation.** The wrapper AOT-compiles
  with `fn.lower(*args).compile()` the first time it sees an abstract
  shape signature and times exactly that call, so compile wall is a
  counter (`compiles`, `compile_seconds` per entry point) instead of a
  mystery spike folded into the first dispatch. Subsequent calls hit
  the cached executable directly.

- **The recompile sentinel.** Width-bucketing exists so one executable
  serves a whole range of slab widths; when it breaks (a caller
  bypassing `width_bucket`, an lru eviction, a dtype drift) the
  symptom used to be wall time. The wrapper re-buckets every compiled
  signature's trailing width through `canonical_width()` — a properly
  bucketed width maps to itself, so each (entry, bucket) pair compiles
  at most once. A second compile for the same pair increments
  `recompiles` and latches the `sentinel` flag with a bounded offender
  list. r05's 2 MB/s mesh rebuild would have been a nonzero counter,
  not a PR-long bisect.

- **Sampled device-time attribution.** With `SW_EC_DEVICE_TIMING=1`,
  every `SW_EC_DEVICE_TIMING_SAMPLE`th dispatch per entry point runs
  `block_until_ready` under a timer, giving an unbiased estimate of
  device seconds per entry (multiply a sample's mean by the dispatch
  count). Default-off mirrors the native plane's `SW_PLANE_STATS=0`
  discipline: the hot path increments one counter under one lock and
  performs ZERO clock reads and zero synchronizations —
  tests/test_device_stats.py proves it by monkeypatching
  `device_stats._perf_counter`.

- **Cache accounting.** `_ConstCache` (device-resident bit-matrix
  constants) reports hits/misses/evictions here and registers itself
  (weakly) so occupancy — entries and device bytes pinned — can be
  snapshotted. The `lru_cache` jit factories register via
  `register_jit_factory()`; evictions are derived as
  `misses - currsize`, because an evicted jitted fn is a silent
  recompile.

Everything lands in `snapshot()` → mirrored to `ec_xla_*` /
`ec_const_cache_*` metric families on `/metrics` (aggregated onto the
master's `/cluster/metrics`), `GET /admin/devices`, shell
`cluster.devices`, and bench.py's compile_s/steady-state split.

jax is imported lazily (sampled-timing path and device inventory
only), matching telemetry.py: this module must import on hosts with no
jax at all.
"""

from __future__ import annotations

import sys
import weakref
from time import perf_counter as _perf_counter
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import config
from ..util.locks import make_lock

#: Compiled signatures latched as sentinel offenders are capped here;
#: past that the counters still move but reprs stop accumulating.
MAX_OFFENDERS = 8


def canonical_width(n: int) -> int:
    """The width bucket `n` SHOULD have been dispatched under.

    Mirrors ops/rs_tpu.width_bucket's shape (512 floor, next pow2) so
    that a properly bucketed width is a fixed point: bucketed paths
    key one compile per bucket, while a caller jitting exact widths
    folds many widths into one bucket key and trips the sentinel on
    the second compile."""
    if n <= 0:
        return 0
    return max(512, 1 << (int(n) - 1).bit_length())


class DeviceStats:
    """Per-entry-point compile/execute accounting (thread-safe)."""

    def __init__(self):
        self._lock = make_lock("device_stats._lock")
        self.compiles: Dict[str, int] = {}
        self.compile_seconds: Dict[str, float] = {}
        self.recompiles: Dict[str, int] = {}
        self.dispatches: Dict[str, int] = {}
        self.device_samples: Dict[str, int] = {}
        self.device_seconds: Dict[str, float] = {}
        # (entry, bucket-signature) -> compile count; >1 latches.
        self._bucket_compiles: Dict[Tuple[str, Any], int] = {}
        self.sentinel = False
        self.offenders: List[str] = []
        # const-cache event counters + live instances for occupancy.
        self.const_cache: Dict[str, int] = {
            "hits": 0, "misses": 0, "evictions": 0}
        self._const_caches: "weakref.WeakSet" = weakref.WeakSet()
        self.reconfigure()

    # -- configuration -------------------------------------------------

    def reconfigure(self):
        """Re-read the timing knobs (tests flip them via monkeypatch;
        production reads them once at import)."""
        self.timing_enabled = bool(config.env_bool("SW_EC_DEVICE_TIMING"))
        self.sample_every = max(
            1, int(config.env_int("SW_EC_DEVICE_TIMING_SAMPLE")))

    # -- hot path ------------------------------------------------------

    def tick(self, entry: str) -> bool:
        """Count one dispatch; True when this one should be timed.

        This is the ONLY per-dispatch cost with timing off: one lock,
        one dict increment, no clock reads."""
        with self._lock:
            n = self.dispatches.get(entry, 0) + 1
            self.dispatches[entry] = n
        if not self.timing_enabled:
            return False
        return n % self.sample_every == 0

    # -- slow-path events ----------------------------------------------

    def note_compile(self, entry: str, bucket_key, seconds: float):
        with self._lock:
            self.compiles[entry] = self.compiles.get(entry, 0) + 1
            self.compile_seconds[entry] = \
                self.compile_seconds.get(entry, 0.0) + seconds
            key = (entry, bucket_key)
            seen = self._bucket_compiles.get(key, 0) + 1
            self._bucket_compiles[key] = seen
            if seen > 1:
                self.recompiles[entry] = self.recompiles.get(entry, 0) + 1
                self.sentinel = True
                if len(self.offenders) < MAX_OFFENDERS:
                    self.offenders.append(f"{entry}:{bucket_key!r}")

    def note_device_time(self, entry: str, seconds: float):
        with self._lock:
            self.device_samples[entry] = \
                self.device_samples.get(entry, 0) + 1
            self.device_seconds[entry] = \
                self.device_seconds.get(entry, 0.0) + seconds

    def note_const_cache(self, event: str, n: int = 1):
        with self._lock:
            self.const_cache[event] = self.const_cache.get(event, 0) + n

    def register_const_cache(self, cache):
        self._const_caches.add(cache)

    # -- reads ---------------------------------------------------------

    def const_cache_occupancy(self) -> Dict[str, int]:
        entries = 0
        nbytes = 0
        for cache in list(self._const_caches):
            occ = cache.occupancy()
            entries += occ["entries"]
            nbytes += occ["bytes"]
        return {"entries": entries, "bytes": nbytes}

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "compiles": dict(self.compiles),
                "compile_seconds": dict(self.compile_seconds),
                "recompiles": dict(self.recompiles),
                "dispatches": dict(self.dispatches),
                "device_samples": dict(self.device_samples),
                "device_seconds": dict(self.device_seconds),
                "sentinel": self.sentinel,
                "offenders": list(self.offenders),
                "const_cache": dict(self.const_cache),
                "timing_enabled": self.timing_enabled,
                "sample_every": self.sample_every,
            }
        snap["const_cache_occupancy"] = self.const_cache_occupancy()
        return snap


DEVICE_STATS = DeviceStats()


def delta(before: dict) -> dict:
    """Movement since a snapshot() — bench.py's per-phase report."""
    now = DEVICE_STATS.snapshot()
    out = {}
    for field in ("compiles", "compile_seconds", "recompiles",
                  "dispatches", "device_samples", "device_seconds"):
        prev = before.get(field, {})
        moved = {k: v - prev.get(k, 0) for k, v in now[field].items()
                 if v - prev.get(k, 0)}
        out[field] = moved
        out[field + "_total"] = sum(moved.values())
    out["sentinel"] = now["sentinel"]
    out["offenders"] = [o for o in now["offenders"]
                        if o not in before.get("offenders", [])]
    return out


# ---------------------------------------------------------------------------
# the instrumented jit wrapper
# ---------------------------------------------------------------------------

class InstrumentedJit:
    """Wraps a `jax.jit`-ed callable with AOT compile accounting.

    First call per abstract signature pays a timed
    `lower(*args).compile()`; later calls dispatch the cached
    executable. The sentinel key re-buckets the data argument's
    trailing width through canonical_width(), so per-bucket compiles
    are idempotent and exact-width churn latches."""

    __slots__ = ("_jit", "entry", "_stats", "_compiled", "_lock")

    def __init__(self, jfn, entry: str, stats: Optional[DeviceStats] = None):
        self._jit = jfn
        self.entry = entry
        self._stats = stats if stats is not None else DEVICE_STATS
        self._compiled: Dict[Any, Callable] = {}
        self._lock = make_lock(f"device_stats.wrap[{entry}]")

    @property
    def raw_jit(self):
        """The unwrapped `jax.jit` result, for consumers that need the
        genuine `stages.Wrapped` object (jax.export, serialization)."""
        return self._jit

    @staticmethod
    def _signature(args) -> tuple:
        return tuple((tuple(getattr(a, "shape", ())),
                      str(getattr(a, "dtype", type(a).__name__)))
                     for a in args)

    @staticmethod
    def _bucket_key(sig) -> tuple:
        """Signature with the LAST axis of the LAST array re-bucketed —
        the width axis every EC entry point varies."""
        if not sig:
            return sig
        head, (shape, dtype) = sig[:-1], sig[-1]
        if shape:
            shape = shape[:-1] + (canonical_width(shape[-1]),)
        return head + ((shape, dtype),)

    def _compile(self, sig, args):
        with self._lock:
            exe = self._compiled.get(sig)
            if exe is not None:  # lost the race; already compiled
                return exe
            t0 = _perf_counter()
            try:
                exe = self._jit.lower(*args).compile()
            except Exception:
                # Backends without AOT lowering (or non-array leaves)
                # still get counted; jit's own tracing then compiles
                # on first dispatch inside the timed window.
                exe = self._jit
            dt = _perf_counter() - t0
            self._compiled[sig] = exe
        self._stats.note_compile(self.entry, self._bucket_key(sig), dt)
        return exe

    def __call__(self, *args):
        sig = self._signature(args)
        exe = self._compiled.get(sig)
        if exe is None:
            exe = self._compile(sig, args)
        if self._stats.tick(self.entry):
            import jax
            t0 = _perf_counter()
            out = exe(*args)
            jax.block_until_ready(out)
            self._stats.note_device_time(self.entry,
                                         _perf_counter() - t0)
            return out
        return exe(*args)


def wrap(jfn, entry: str, stats: Optional[DeviceStats] = None):
    """Instrument a jitted callable under an entry-point name."""
    return InstrumentedJit(jfn, entry, stats)


# ---------------------------------------------------------------------------
# lru_cache jit-factory registry
# ---------------------------------------------------------------------------

_JIT_FACTORIES: Dict[str, Callable] = {}


def register_jit_factory(name: str, fn) -> None:
    """Register an `lru_cache`-decorated jit factory for cache_info()
    export; an evicted entry is a silent recompile, so evictions are
    first-class (misses - currsize)."""
    _JIT_FACTORIES[name] = fn


def jit_factory_snapshot() -> Dict[str, dict]:
    out = {}
    for name, fn in sorted(_JIT_FACTORIES.items()):
        info = fn.cache_info()
        out[name] = {
            "hits": info.hits,
            "misses": info.misses,
            "maxsize": info.maxsize,
            "currsize": info.currsize,
            "evictions": max(0, info.misses - info.currsize),
        }
    return out


# ---------------------------------------------------------------------------
# device inventory
# ---------------------------------------------------------------------------

def device_inventory(force: bool = False) -> dict:
    """Platform, device kind×count, and memory_stats() gauges.

    A metrics scrape must never be the thing that boots an XLA
    backend: unless `force` or jax is already imported, this reports
    initialized=False and touches nothing."""
    if not force and "jax" not in sys.modules:
        return {"initialized": False, "platform": None,
                "device_kinds": {}, "devices": []}
    try:
        import jax
        devices = jax.devices()
        platform = jax.default_backend()
    except Exception as exc:  # pragma: no cover - no backend at all
        return {"initialized": False, "platform": None,
                "device_kinds": {}, "devices": [],
                "error": str(exc)}
    kinds: Dict[str, int] = {}
    per_device = []
    for d in devices:
        kind = getattr(d, "device_kind", "unknown")
        kinds[kind] = kinds.get(kind, 0) + 1
        mem = None
        try:
            mem = d.memory_stats()
        except Exception:
            mem = None
        per_device.append({"id": d.id, "kind": kind,
                           "memory_stats": mem or {}})
    return {"initialized": True, "platform": platform,
            "device_kinds": kinds, "devices": per_device}


def admin_snapshot() -> dict:
    """The GET /admin/devices payload: full stats + factories +
    inventory (forces backend init — this endpoint is explicitly for
    humans asking about devices)."""
    return {
        "stats": DEVICE_STATS.snapshot(),
        "jit_factories": jit_factory_snapshot(),
        "inventory": device_inventory(force=True),
    }
