"""TPU Reed-Solomon backend — GF(2^8) coding as an MXU bit-plane matmul.

The north star (BASELINE.json): the reference's EC hot loop
(reference ec_encoder.go:118-134 -> klauspost AVX2 GF multiply) becomes a
single batched matmul per chunk on TPU.

Math: multiplication by a GF(2^8) constant is linear over GF(2)^8, so the
(r x k) byte coefficient matrix lifts to a (k*8 x r*8) binary matrix B
(ops/gf256.bit_matrix). With input bytes unpacked to bit-planes
X (k*8, n) in {0,1}, the coded output is

    Y = (B^T @ X) mod 2        -- int8 matmul on the MXU, ~896 MACs/byte
    out = pack_bits(Y)         -- VPU shifts/adds

This is exact integer arithmetic (row sums <= k*8 = 160 < 2^31), so the
result is bit-identical to the numpy/native backends. No gathers, no
data-dependent control flow; everything is static-shaped for XLA.

Chunking: the bit-plane expansion is 8x the payload, so a whole 30GB volume
cannot be lifted at once; the codec streams fixed-size chunks (default 32MB
per shard-row) through one compiled executable (one compilation per
(r, k, chunk) shape; tails are zero-padded to the chunk width, and GF
linearity makes zero-padding exact).
"""

from __future__ import annotations

import functools

import numpy as np

from .codec import ReedSolomonCodec
from . import gf256


def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


@functools.lru_cache(maxsize=64)
def _coded_fn(k: int, r: int, n: int):
    """Jitted (bitmat (k*8, r*8) int8, data (k, n) uint8) -> (r, n) uint8."""
    jax, jnp = _jax()

    def fn(bitmat, data):
        shifts = jnp.arange(8, dtype=jnp.uint8)
        # unpack to bit-planes: row j*8+l is bit l of input shard j
        bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
        x = bits.reshape(k * 8, n).astype(jnp.int8)
        # MXU: (r*8, k*8) @ (k*8, n) with int32 accumulation
        y = jax.lax.dot_general(
            bitmat.T, x,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        ybits = (y & 1).astype(jnp.uint8).reshape(r, 8, n)
        weights = (jnp.uint8(1) << shifts)[None, :, None]
        return (ybits * weights).sum(axis=1, dtype=jnp.uint8)

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _bitmat_cached(coeff_bytes: bytes, r: int, k: int):
    coeffs = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(r, k)
    return gf256.bit_matrix(coeffs).astype(np.int8)


def on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def fn_and_bitmat(coeffs: np.ndarray, n: int):
    """Pick the device kernel for this platform: the fused Pallas kernel
    on real TPU (ops/rs_pallas — unpack/matmul/pack in VMEM, no HBM
    temporaries), the plain XLA program elsewhere (the CPU test mesh,
    where Pallas would have to interpret). Returns (jitted fn, host
    bitmat) with matching layouts; both are bit-identical to the numpy
    oracle."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    r, k = coeffs.shape
    if on_tpu():
        from .rs_pallas import _fused_fn, fuse_bitmat, pick_tile
        return (_fused_fn(k, r, n, pick_tile(k, r, n), False),
                fuse_bitmat(coeffs))
    return _coded_fn(k, r, n), _bitmat_cached(coeffs.tobytes(), r, k)


def width_bucket(n: int, cap: int) -> int:
    """Pad widths up to power-of-two buckets (capped) so varied payload
    widths reuse compiled executables instead of jitting per exact n."""
    return min(max(512, 1 << (n - 1).bit_length()), cap)


class TpuCodec(ReedSolomonCodec):
    """JAX backend. Runs on whatever jax.devices() offers (TPU in prod,
    virtual CPU mesh in tests) — output is bit-identical everywhere."""

    backend = "tpu"

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde",
                 chunk_bytes: int = 32 << 20):
        super().__init__(data_shards, parity_shards, matrix_kind)
        self.chunk_bytes = int(chunk_bytes)

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = coeffs.shape
        n = data.shape[1]
        if n == 0:
            return np.zeros((r, 0), dtype=np.uint8)
        if n <= self.chunk_bytes:
            bucket = width_bucket(n, self.chunk_bytes)
            fn, bitmat = fn_and_bitmat(coeffs, bucket)
            if n < bucket:
                pad = np.zeros((k, bucket), dtype=np.uint8)
                pad[:, :n] = data
                return np.asarray(fn(bitmat, pad))[:, :n]
            return np.asarray(fn(bitmat, data))
        out = np.empty((r, n), dtype=np.uint8)
        fn, bitmat = fn_and_bitmat(coeffs, self.chunk_bytes)
        for off in range(0, n, self.chunk_bytes):
            end = min(off + self.chunk_bytes, n)
            chunk = data[:, off:end]
            if end - off < self.chunk_bytes:
                pad = np.zeros((k, self.chunk_bytes), dtype=np.uint8)
                pad[:, : end - off] = chunk
                out[:, off:end] = np.asarray(fn(bitmat, pad))[:, : end - off]
            else:
                out[:, off:end] = np.asarray(fn(bitmat, chunk))
        return out


# ---------------------------------------------------------------------------
# Raw jax-level entry points (used by bench.py, __graft_entry__, parallel/)
# ---------------------------------------------------------------------------

def make_encode_fn(k: int, m: int, n: int, matrix_kind: str = "vandermonde"):
    """Returns (jitted_fn, bitmat): jitted_fn(bitmat, data (k, n)) -> (m, n).

    This is the single-device flagship kernel (fused Pallas on TPU, XLA
    elsewhere); parallel/sharded_ec wraps the XLA variant in a mesh for
    multi-chip encode.
    """
    matrix = gf256.build_matrix(k, k + m, matrix_kind)
    return fn_and_bitmat(matrix[k:], n)
