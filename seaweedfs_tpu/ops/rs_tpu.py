"""TPU Reed-Solomon backend — GF(2^8) coding as an MXU bit-plane matmul.

The north star (BASELINE.json): the reference's EC hot loop
(reference ec_encoder.go:118-134 -> klauspost AVX2 GF multiply) becomes a
single batched matmul per chunk on TPU.

Math: multiplication by a GF(2^8) constant is linear over GF(2)^8, so the
(r x k) byte coefficient matrix lifts to a (k*8 x r*8) binary matrix B
(ops/gf256.bit_matrix). With input bytes unpacked to bit-planes
X (k*8, n) in {0,1}, the coded output is

    Y = (B^T @ X) mod 2        -- int8 matmul on the MXU, ~896 MACs/byte
    out = pack_bits(Y)         -- VPU shifts/adds

This is exact integer arithmetic (row sums <= k*8 = 160 < 2^31), so the
result is bit-identical to the numpy/native backends. No gathers, no
data-dependent control flow; everything is static-shaped for XLA.

Chunking: the bit-plane expansion is 8x the payload, so a whole 30GB volume
cannot be lifted at once; the codec streams fixed-size chunks (default 32MB
per shard-row) through one compiled executable (one compilation per
(r, k, chunk) shape; tails are zero-padded to the chunk width, and GF
linearity makes zero-padding exact).
"""

from __future__ import annotations

import functools

import numpy as np

from .codec import ReedSolomonCodec
from . import device_stats
from . import gf256
from ..util import config

#: lru maxsize for the jit factories below — read once at import, a
#: registered knob so eviction pressure (a silent recompile source) is
#: tunable and visible in ec_xla_jit_cache_total.
_JIT_CACHE_SIZE = config.env_int("SW_EC_JIT_CACHE_SIZE")

#: trace-size crossover for _packed_fn: matrices with r*8*nw at or
#: below this unroll fully (constant indices, ms traces); above it the
#: rolled lax.scan form keeps the graph O(1) in the matrix dims (the
#: piggyback emat would otherwise unroll to ~10^5 ops and stall XLA
#: CPU compilation for minutes).
_PACKED_UNROLL_LIMIT = 4096


def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


@functools.lru_cache(maxsize=_JIT_CACHE_SIZE)
def _coded_fn(k: int, r: int, n: int):
    """Jitted (bitmat (k*8, r*8) int8, data (k, n) uint8) -> (r, n) uint8."""
    jax, jnp = _jax()

    def fn(bitmat, data):
        shifts = jnp.arange(8, dtype=jnp.uint8)
        # unpack to bit-planes: row j*8+l is bit l of input shard j
        bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
        x = bits.reshape(k * 8, n).astype(jnp.int8)
        # MXU: (r*8, k*8) @ (k*8, n) with int32 accumulation
        y = jax.lax.dot_general(
            bitmat.T, x,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        ybits = (y & 1).astype(jnp.uint8).reshape(r, 8, n)
        weights = (jnp.uint8(1) << shifts)[None, :, None]
        return (ybits * weights).sum(axis=1, dtype=jnp.uint8)

    return device_stats.wrap(jax.jit(fn), "rs_tpu._coded_fn")


@functools.lru_cache(maxsize=_JIT_CACHE_SIZE)
def _bitmat_cached(coeff_bytes: bytes, r: int, k: int):
    coeffs = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(r, k)
    return gf256.bit_matrix(coeffs).astype(np.int8)


@functools.lru_cache(maxsize=_JIT_CACHE_SIZE)
def _packed_fn(k: int, r: int, n: int):
    """Jitted (packed bitmat (ceil(k*8/32), r*8) uint32, data (k, n)
    uint8) -> (r, n) uint8 — the AND/popcount form of the GF(2) matmul.

    The bit-plane dot lifts the payload 8x and feeds the CPU a
    (r*8, k*8) @ (k*8, n) int8 gemm with a tiny M — memory-bound and
    ~2 MB/s/core in practice (the round-5 mesh rebuild). Packing the
    k*8 contraction bits into <=8 uint32 words turns each output bit
    into a handful of vectorized AND + popcount + parity ops: ~64x
    less arithmetic, no 8x intermediate, and seconds -> sub-second
    compile times. Exact (popcount parity == mod-2 dot), so output is
    bit-identical to every other backend. TPU keeps the MXU dot /
    fused Pallas kernel (rs_pallas) where the matmul IS the fast path.
    """
    jax, jnp = _jax()
    nw = (k * 8 + 31) // 32

    if r * 8 * nw <= _PACKED_UNROLL_LIMIT:
        # flat-geometry matrices (parity rows, decode coeffs, repair
        # rows: r*8*nw in the hundreds): full unroll traces in
        # milliseconds and lets XLA see every constant index
        def fn(bmp, data):
            d32 = data.astype(jnp.uint32)
            words = []
            for wi in range(nw):
                acc = jnp.zeros((n,), jnp.uint32)
                for b in range(4):
                    j = wi * 4 + b
                    if j < k:
                        acc = acc | (d32[j] << (8 * b))
                words.append(acc)
            outs = []
            for i in range(r):
                byte = jnp.zeros((n,), jnp.uint32)
                for bit in range(8):
                    col = i * 8 + bit
                    ones = jnp.zeros((n,), jnp.uint32)
                    for wi in range(nw):
                        ones = ones + jax.lax.population_count(
                            words[wi] & bmp[wi, col])
                    byte = byte | ((ones & 1) << bit)
                outs.append(byte.astype(jnp.uint8))
            return jnp.stack(outs)
    else:
        # sub-chunk matrices (the piggyback emat is (m*alpha, k*alpha):
        # r*8*nw ~ 10^5) would make the unrolled trace an XLA compile
        # bomb — tens of minutes on CPU. Same math, rolled: lax.scan
        # over output bytes keeps the graph O(1) in r and k, and the
        # per-step live set at nw*n words.
        def fn(bmp, data):
            d32 = data.astype(jnp.uint32)
            pad = nw * 4 - k
            if pad:
                d32 = jnp.concatenate(
                    [d32, jnp.zeros((pad, n), jnp.uint32)])
            lanes = d32.reshape(nw, 4, n)
            words = (lanes[:, 0] | (lanes[:, 1] << 8)
                     | (lanes[:, 2] << 16) | (lanes[:, 3] << 24))

            def row(carry, cols):  # cols: (8, nw) one output byte
                byte = jnp.zeros((n,), jnp.uint32)
                for bit in range(8):
                    ones = jax.lax.population_count(
                        words & cols[bit][:, None]).sum(axis=0)
                    byte = byte | ((ones & 1) << bit)
                return carry, byte.astype(jnp.uint8)

            # bmp is (nw, r*8) with column i*8+bit; transpose/reshape
            # regroups it as (r, 8, nw) scan steps
            _, out = jax.lax.scan(
                row, None, bmp.T.reshape(r, 8, nw))
            return out

    return device_stats.wrap(jax.jit(fn), "rs_tpu._packed_fn")


@functools.lru_cache(maxsize=_JIT_CACHE_SIZE)
def _packed_bitmat(coeff_bytes: bytes, r: int, k: int):
    coeffs = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(r, k)
    return gf256.pack_bit_matrix(coeffs)


for _name, _factory in (("rs_tpu._coded_fn", _coded_fn),
                        ("rs_tpu._bitmat_cached", _bitmat_cached),
                        ("rs_tpu._packed_fn", _packed_fn),
                        ("rs_tpu._packed_bitmat", _packed_bitmat)):
    device_stats.register_jit_factory(_name, _factory)
del _name, _factory


def on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def fn_and_bitmat(coeffs: np.ndarray, n: int):
    """Pick the device kernel for this platform: the fused Pallas kernel
    on real TPU (ops/rs_pallas — unpack/matmul/pack in VMEM, no HBM
    temporaries), the packed AND/popcount XLA program elsewhere (the
    CPU test mesh, where the 8x bit-plane gemm is the bottleneck and
    Pallas would have to interpret). Returns (jitted fn, host constant
    — fused bitmat on TPU, packed uint32 bitmat off it) with matching
    layouts; both are bit-identical to the numpy oracle."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    r, k = coeffs.shape
    if on_tpu():
        from .rs_pallas import _fused_fn, fuse_bitmat, pick_tile
        return (_fused_fn(k, r, n, pick_tile(k, r, n), False),
                fuse_bitmat(coeffs))
    return _packed_fn(k, r, n), _packed_bitmat(coeffs.tobytes(), r, k)


def width_bucket(n: int, cap: int) -> int:
    """Pad widths up to power-of-two buckets (capped) so varied payload
    widths reuse compiled executables instead of jitting per exact n."""
    return min(max(512, 1 << (n - 1).bit_length()), cap)


class TpuCodec(ReedSolomonCodec):
    """JAX backend. Runs on whatever jax.devices() offers (TPU in prod,
    virtual CPU mesh in tests) — output is bit-identical everywhere."""

    backend = "tpu"

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde",
                 chunk_bytes: int = 32 << 20,
                 small_dispatch_bytes: int = None):
        super().__init__(data_shards, parity_shards, matrix_kind)
        self.chunk_bytes = int(chunk_bytes)
        from .codec import _ConstCache, small_dispatch_default
        self.small_dispatch_bytes = (
            small_dispatch_default() if small_dispatch_bytes is None
            else int(small_dispatch_bytes))
        self._consts = _ConstCache()

    def device_fn(self, coeffs: np.ndarray, width: int):
        """(fn, device-resident constant, put) for `width`-wide slabs;
        the constant (fused/packed bitmat) uploads once per coefficient
        matrix and stays device-resident across the stream."""
        import jax.numpy as jnp
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        fn, const_host = fn_and_bitmat(coeffs, width)
        const_dev = self._consts.get(coeffs.tobytes(),
                                     lambda: jnp.asarray(const_host))
        return fn, const_dev, jnp.asarray

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        from .telemetry import STATS
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = coeffs.shape
        n = data.shape[1]
        if n == 0:
            return np.zeros((r, 0), dtype=np.uint8)
        if n <= self.chunk_bytes:
            bucket = width_bucket(n, self.chunk_bytes)
            fn, bitmat, put = self.device_fn(coeffs, bucket)
            STATS.add("dispatches")
            STATS.add("device_bytes", data.nbytes)
            if n < bucket:
                pad = np.zeros((k, bucket), dtype=np.uint8)
                pad[:, :n] = data
                return np.asarray(fn(bitmat, put(pad)))[:, :n]
            return np.asarray(fn(bitmat, put(data)))
        out = np.empty((r, n), dtype=np.uint8)
        fn, bitmat, put = self.device_fn(coeffs, self.chunk_bytes)
        # dispatch every chunk before draining any: JAX dispatch is
        # async, so the device crunches chunk t+1 while chunk t copies
        # back — blocking np.asarray inside the dispatch loop would
        # serialize the two
        pending = []
        for off in range(0, n, self.chunk_bytes):
            end = min(off + self.chunk_bytes, n)
            chunk = data[:, off:end]
            STATS.add("dispatches")
            STATS.add("device_bytes", chunk.nbytes)
            if end - off < self.chunk_bytes:
                pad = np.zeros((k, self.chunk_bytes), dtype=np.uint8)
                pad[:, : end - off] = chunk
                chunk = pad
            pending.append((off, end, fn(bitmat, put(chunk))))
        for off, end, dev in pending:
            out[:, off:end] = np.asarray(dev)[:, : end - off]
        return out


# ---------------------------------------------------------------------------
# Raw jax-level entry points (used by bench.py, __graft_entry__, parallel/)
# ---------------------------------------------------------------------------

def make_encode_fn(k: int, m: int, n: int, matrix_kind: str = "vandermonde"):
    """Returns (jitted_fn, bitmat): jitted_fn(bitmat, data (k, n)) -> (m, n).

    This is the single-device flagship kernel (fused Pallas on TPU, XLA
    elsewhere); parallel/sharded_ec wraps the XLA variant in a mesh for
    multi-chip encode.
    """
    matrix = gf256.build_matrix(k, k + m, matrix_kind)
    return fn_and_bitmat(matrix[k:], n)
