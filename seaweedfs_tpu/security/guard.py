"""Request guard: IP whitelist (reference weed/security/guard.go:13-45).

Wraps handlers; a non-empty whitelist restricts callers by source IP
(exact match or prefix like "10.0." — the reference also accepts CIDRs,
which we support via ipaddress networks).
"""

from __future__ import annotations

import ipaddress
from typing import Iterable, List


class Guard:
    def __init__(self, whitelist: Iterable[str] = ()):
        self.exact: List[str] = []
        self.networks = []
        for item in whitelist:
            item = item.strip()
            if not item:
                continue
            if "/" in item:
                self.networks.append(ipaddress.ip_network(item,
                                                         strict=False))
            else:
                self.exact.append(item)

    @property
    def enabled(self) -> bool:
        return bool(self.exact or self.networks)

    def allows(self, ip: str) -> bool:
        if not self.enabled:
            return True
        if ip in self.exact:
            return True
        for e in self.exact:  # prefix form "10.0."
            if e.endswith(".") and ip.startswith(e):
                return True
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)
