"""Security: JWT write tokens + request guard.

Reference weed/security/: jwt.go (per-fid HS256 write tokens minted by
the master, verified by volume servers), guard.go (IP whitelist + jwt
enforcement wrapper). gRPC mTLS has no analog here (stdlib HTTP);
transport security is deployment-level.
"""

from .jwt import GenJwt, VerifyError, decode_jwt, encode_jwt  # noqa: F401
from .guard import Guard  # noqa: F401
