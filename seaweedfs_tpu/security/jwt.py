"""HS256 JWT for per-fid write authorization.

Reference weed/security/jwt.go:21-58: the master mints a short-lived
token bound to the file id when handing out an assignment; volume
servers verify it before accepting writes/deletes. Standard JWT wire
format (base64url header.payload.signature) so external tooling can
inspect tokens.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional


class VerifyError(Exception):
    pass


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def encode_jwt(key: str, claims: dict) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"},
                             separators=(",", ":")).encode())
    payload = _b64(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(key.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64(sig)}"


def decode_jwt(key: str, token: str) -> dict:
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        raise VerifyError("malformed token") from None
    signing_input = f"{header}.{payload}".encode()
    want = hmac.new(key.encode(), signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(want, _unb64(sig)):
        raise VerifyError("bad signature")
    claims = json.loads(_unb64(payload))
    exp = claims.get("exp")
    if exp is not None and time.time() > exp:
        raise VerifyError("token expired")
    return claims


def GenJwt(key: str, fid: str, expires_seconds: int = 10) -> str:
    """Mint a write token bound to one fid (reference GenJwt)."""
    return encode_jwt(key, {"fid": fid,
                            "exp": int(time.time()) + expires_seconds})


def verify_fid_jwt(key: str, token: str, fid: str) -> None:
    claims = decode_jwt(key, token)
    if claims.get("fid") != fid:
        raise VerifyError(f"token not valid for {fid}")


def jwt_from_request(headers, query: dict) -> Optional[str]:
    """Authorization: Bearer <t> header, or ?jwt=<t> (reference
    GetJwt request parsing order)."""
    auth = headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):].strip()
    return query.get("jwt") or None
