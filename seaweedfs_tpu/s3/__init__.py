"""S3-compatible gateway over the filer.

Reference weed/s3api/: REST router (s3api_server.go:35-100), AWS
SigV4/V2 authentication incl. streaming chunked payloads
(auth_signature_v4.go, chunked_reader_v4.go), bucket/object/multipart
handlers (filer_multipart.go), IAM credentials (auth_credentials.go).
"""

from .auth import Iam, Identity, S3AuthError, sign_request_v4  # noqa: F401
from .s3_server import S3ApiServer  # noqa: F401
