"""S3 REST gateway.

Reference weed/s3api/s3api_server.go (router), s3api_bucket_handlers.go,
s3api_object_handlers.go, s3api_objects_list_handlers.go,
filer_multipart.go. Serves path-style requests over an in-process Filer
(the reference gateway talks to the filer over gRPC; here the gateway is
hosted by the filer process — `weed server -s3` style).

Objects live at <buckets_folder>/<bucket>/<key>; multipart parts are
staged under a hidden ".uploads/<uploadId>/" prefix inside the bucket
and composed zero-copy on complete (chunk lists are re-based, not
re-uploaded — the reference does the same).
"""

from __future__ import annotations

import hashlib
import posixpath
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

from ..filer import Attr, Entry, FileChunk, Filer
from ..filer.filer import FilerError, NotFoundError
from ..filer.stream import read_chunked
from ..filer.upload import split_and_upload
from ..server.http_util import (HttpError, HttpServer, Request, Response,
                                Router)
from .auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ, ACTION_WRITE,
                   STREAMING_PAYLOAD, Iam, S3AuthError, authenticate,
                   decode_aws_chunked)

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
UPLOADS_PREFIX = ".uploads"


def _xml(root: ET.Element) -> Response:
    body = b'<?xml version="1.0" encoding="UTF-8"?>' + \
        ET.tostring(root)
    return Response(body, 200, "application/xml")


def _err(status: int, code: str, message: str = "",
         resource: str = "") -> Response:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = message or code
    ET.SubElement(root, "Resource").text = resource
    return Response(
        b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root),
        status, "application/xml")


class S3ApiServer:
    def __init__(self, filer: Filer, master_url: str,
                 port: int = 8333, host: str = "127.0.0.1",
                 iam: Optional[Iam] = None,
                 chunk_size: int = 8 << 20,
                 fetcher=None):
        self.filer = filer
        self.master_url = master_url
        self.iam = iam or Iam()
        self.chunk_size = chunk_size
        self._fetch = fetcher
        router = Router()
        router.set_fallback(self.dispatch)
        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        self.host = host

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    # -- plumbing -----------------------------------------------------------

    def _bucket_path(self, bucket: str) -> str:
        return f"{self.filer.buckets_folder}/{bucket}"

    def _object_path(self, bucket: str, key: str) -> str:
        return f"{self._bucket_path(bucket)}/{key}".rstrip("/")

    def _chunk_fetcher(self):
        if self._fetch is None:
            from ..filer.stream import default_fetcher
            self._fetch = default_fetcher(self.master_url)
        return self._fetch

    def dispatch(self, req: Request):
        parsed = urllib.parse.urlparse(req.handler.path)
        query_pairs = urllib.parse.parse_qsl(parsed.query,
                                             keep_blank_values=True)
        path = urllib.parse.unquote(parsed.path)
        # normalize before extracting bucket/key: auth is bucket-scoped,
        # so '..' segments must not let a key escape into another bucket
        # (the filer normpaths server-side; match it here)
        if path != "/":
            trail = "/" if path.endswith("/") else ""
            path = posixpath.normpath(path)
            if path == "/":
                trail = ""
            path += trail
        body = req.body
        try:
            ident = authenticate(self.iam, req.method, parsed.path,
                                 query_pairs, dict(req.headers), body)
        except S3AuthError as e:
            return _err(e.status, e.code, str(e), path)
        # aws-chunked streaming payload (aws cli default for puts)
        sha_hdr = req.headers.get("x-amz-content-sha256", "")
        if sha_hdr.startswith(STREAMING_PAYLOAD) and body:
            try:
                seed, scope, amz_date, secret = "", "", "", ""
                if ident is not None:
                    auth_hdr = req.headers.get("Authorization", "")
                    seed = auth_hdr.rpartition("Signature=")[2].strip()
                    cred = auth_hdr.partition("Credential=")[2]
                    parts = cred.split("/")
                    scope = "/".join(parts[1:5]).split(",")[0]
                    amz_date = req.headers.get("x-amz-date", "")
                    secret = ident.secret_key
                body = decode_aws_chunked(
                    body, secret_key=secret, seed_signature=seed,
                    scope=scope, amz_date=amz_date,
                    verify=ident is not None)
            except S3AuthError as e:
                return _err(e.status, e.code, str(e), path)

        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        q = dict(query_pairs)
        try:
            return self._route(req, ident, bucket, key, q, body, path)
        except S3AuthError as e:
            return _err(e.status, e.code, str(e), path)
        except NotFoundError:
            code = "NoSuchKey" if key else "NoSuchBucket"
            return _err(404, code, path, path)
        except FilerError as e:
            return _err(409, "OperationAborted", str(e), path)

    def _check(self, ident, action: str, bucket: str):
        if ident is None:  # anonymous mode (iam disabled)
            return
        if not ident.can(action, bucket):
            raise S3AuthError(403, "AccessDenied",
                              f"{action} denied on {bucket}")

    def _route(self, req, ident, bucket, key, q, body, path):
        m = req.method
        if not bucket:
            if m == "GET":
                return self.list_buckets(ident)
            raise S3AuthError(405, "MethodNotAllowed")
        if not key:
            if m == "PUT":
                self._check(ident, ACTION_ADMIN, bucket)
                return self.put_bucket(bucket)
            if m == "DELETE":
                self._check(ident, ACTION_ADMIN, bucket)
                return self.delete_bucket(bucket)
            if m == "HEAD":
                self._check(ident, ACTION_READ, bucket)
                self.filer.find_entry(self._bucket_path(bucket))
                return Response(b"", 200)
            if m == "GET":
                if "location" in q:
                    # GetBucketLocation: clients (SDK region probes)
                    # expect an empty LocationConstraint for us-east-1
                    self._check(ident, ACTION_READ, bucket)
                    self.filer.find_entry(self._bucket_path(bucket))
                    root = ET.Element("LocationConstraint")
                    return _xml(root)
                if "uploads" in q:
                    self._check(ident, ACTION_LIST, bucket)
                    return self.list_multipart_uploads(bucket)
                self._check(ident, ACTION_LIST, bucket)
                return self.list_objects(bucket, q)
            if m == "POST" and "delete" in q:
                self._check(ident, ACTION_WRITE, bucket)
                return self.delete_multiple(bucket, body)
            raise S3AuthError(405, "MethodNotAllowed")
        # object-level
        if m == "GET" and "uploadId" in q:
            self._check(ident, ACTION_READ, bucket)
            return self.list_parts(bucket, key, q["uploadId"])
        if m in ("GET", "HEAD"):
            self._check(ident, ACTION_READ, bucket)
            return self.get_object(req, bucket, key, head=(m == "HEAD"))
        if m == "PUT":
            self._check(ident, ACTION_WRITE, bucket)
            if "partNumber" in q and "uploadId" in q:
                return self.upload_part(bucket, key, q, body)
            src = req.headers.get("x-amz-copy-source", "")
            if src:
                # normalize before extracting the source bucket so '..'
                # segments can't smuggle a read from another bucket
                src = posixpath.normpath(
                    "/" + urllib.parse.unquote(src).lstrip("/"))
                src_bucket = src.lstrip("/").partition("/")[0]
                self._check(ident, ACTION_READ, src_bucket)
                return self.copy_object(bucket, key, src)
            return self.put_object(req, bucket, key, body)
        if m == "POST":
            if "uploads" in q:
                self._check(ident, ACTION_WRITE, bucket)
                return self.initiate_multipart(bucket, key)
            if "uploadId" in q:
                self._check(ident, ACTION_WRITE, bucket)
                return self.complete_multipart(bucket, key, q["uploadId"],
                                               body)
            raise S3AuthError(405, "MethodNotAllowed")
        if m == "DELETE":
            self._check(ident, ACTION_WRITE, bucket)
            if "uploadId" in q:
                return self.abort_multipart(bucket, key, q["uploadId"])
            return self.delete_object(bucket, key)
        raise S3AuthError(405, "MethodNotAllowed")

    # -- buckets ------------------------------------------------------------

    def list_buckets(self, ident):
        root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = \
            ident.name if ident else "anonymous"
        buckets = ET.SubElement(root, "Buckets")
        for b in self.filer.list_buckets():
            if ident is not None and not ident.can(ACTION_LIST, b.name):
                continue
            el = ET.SubElement(buckets, "Bucket")
            ET.SubElement(el, "Name").text = b.name
            ET.SubElement(el, "CreationDate").text = _iso(b.attr.crtime)
        return _xml(root)

    def put_bucket(self, bucket: str):
        if self.filer.exists(self._bucket_path(bucket)):
            return _err(409, "BucketAlreadyExists", bucket)
        self.filer.create_bucket(bucket)
        return Response(b"", 200, headers={"Location": f"/{bucket}"})

    def delete_bucket(self, bucket: str):
        self.filer.find_entry(self._bucket_path(bucket))
        # S3 requires the bucket to be empty (hidden upload staging
        # doesn't count)
        for e in self.filer.list_entries(self._bucket_path(bucket),
                                         limit=16):
            if not e.name.startswith("."):
                return _err(409, "BucketNotEmpty", bucket)
        self.filer.delete_bucket(bucket)
        return Response(b"", 204)

    # -- objects ------------------------------------------------------------

    def put_object(self, req: Request, bucket: str, key: str, body: bytes):
        self.filer.find_entry(self._bucket_path(bucket))
        if key.endswith("/"):  # folder marker
            from ..filer.entry import new_dir_entry
            self.filer.create_entry(
                new_dir_entry(self._object_path(bucket, key)))
            return Response(b"", 200, headers={"ETag": '"folder"'})
        ctype = req.headers.get("Content-Type",
                                "application/octet-stream")
        chunks, md5_hex = split_and_upload(
            self.master_url, body, posixpath.basename(key),
            self.chunk_size, collection=bucket, content_type=ctype)
        now = time.time()
        entry = Entry(full_path=self._object_path(bucket, key),
                      attr=Attr(mtime=now, crtime=now, mime=ctype,
                                collection=bucket, md5=md5_hex),
                      chunks=chunks)
        self.filer.create_entry(entry)
        return Response(b"", 200, headers={"ETag": f'"{md5_hex}"'})

    def get_object(self, req: Request, bucket: str, key: str,
                   head: bool = False):
        entry = self.filer.find_entry(self._object_path(bucket, key))
        if entry.is_directory:
            if key.endswith("/"):
                return Response(b"", 200, "application/octet-stream")
            raise NotFoundError(key)
        size = entry.size()
        offset, length, status = 0, size, 200
        headers = {"ETag": f'"{entry.attr.md5}"',
                   "Last-Modified": _http_date(entry.attr.mtime),
                   "Accept-Ranges": "bytes"}
        from ..server.http_util import parse_range
        rng = req.headers.get("Range", "")
        try:
            parsed = parse_range(rng, size)
        except HttpError:
            return _err(416, "InvalidRange", rng)
        if parsed is not None:
            offset, length = parsed
            headers["Content-Range"] = \
                f"bytes {offset}-{offset+length-1}/{size}"
            status = 206
        body = b"" if head else read_chunked(
            entry.chunks, offset, length, self._chunk_fetcher())
        return Response(body, status,
                        entry.attr.mime or "application/octet-stream",
                        headers,
                        content_length=length if head else None)

    def delete_object(self, bucket: str, key: str):
        try:
            self.filer.delete_entry(self._object_path(bucket, key),
                                    recursive=True,
                                    ignore_recursive_error=True)
        except NotFoundError:
            pass  # S3 delete is idempotent
        return Response(b"", 204)

    def copy_object(self, bucket: str, key: str, src: str):
        # src arrives unquoted + normalized from dispatch
        src_bucket, _, src_key = src.lstrip("/").partition("/")
        entry = self.filer.find_entry(self._object_path(src_bucket,
                                                        src_key))
        data = read_chunked(entry.chunks, 0, entry.size(),
                            self._chunk_fetcher())
        chunks, md5_hex = split_and_upload(
            self.master_url, data, posixpath.basename(key),
            self.chunk_size, collection=bucket,
            content_type=entry.attr.mime or "application/octet-stream")
        now = time.time()
        self.filer.create_entry(Entry(
            full_path=self._object_path(bucket, key),
            attr=Attr(mtime=now, crtime=now, mime=entry.attr.mime,
                      collection=bucket, md5=md5_hex), chunks=chunks))
        root = ET.Element("CopyObjectResult", xmlns=XMLNS)
        ET.SubElement(root, "ETag").text = f'"{md5_hex}"'
        ET.SubElement(root, "LastModified").text = _iso(now)
        return _xml(root)

    def delete_multiple(self, bucket: str, body: bytes):
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            return _err(400, "MalformedXML")
        root = ET.Element("DeleteResult", xmlns=XMLNS)
        for obj in tree.iter():
            if not obj.tag.endswith("Object"):
                continue
            key_el = next((c for c in obj if c.tag.endswith("Key")), None)
            if key_el is None or not key_el.text:
                continue
            self.delete_object(bucket, key_el.text)
            el = ET.SubElement(root, "Deleted")
            ET.SubElement(el, "Key").text = key_el.text
        return _xml(root)

    # -- listing (reference s3api_objects_list_handlers.go) -----------------

    def _walk_keys(self, dir_path: str, rel_prefix: str, prefix: str,
                   marker: str, collected: List[Tuple[str, Entry]],
                   limit: int):
        """DFS in sorted order, collecting keys > marker that match
        prefix; subtrees that cannot contain a match are pruned so a
        prefixed listing touches only the matching directories."""
        for e in self.filer.list_entries(dir_path, limit=1 << 20):
            if len(collected) > limit:
                return
            if e.name.startswith("."):
                continue
            rel = f"{rel_prefix}{e.name}"
            if e.is_directory:
                d = rel + "/"
                # prune: subtree keys all start with d; they can match
                # only if d and prefix are prefixes of each other, and
                # some key > marker can exist under d
                if not (d.startswith(prefix) or prefix.startswith(d)):
                    continue
                if marker and not (marker < d or marker.startswith(d)):
                    continue
                self._walk_keys(e.full_path, d, prefix, marker, collected,
                                limit)
            elif rel > marker and rel.startswith(prefix):
                collected.append((rel, e))

    def list_objects(self, bucket: str, q: dict):
        self.filer.find_entry(self._bucket_path(bucket))
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", 1000))
        marker = q.get("continuation-token") or q.get("start-after") or \
            q.get("marker", "")
        collected: List[Tuple[str, Entry]] = []
        self._walk_keys(self._bucket_path(bucket), "", prefix, marker,
                        collected, max_keys * 4 + 16)
        keys = sorted(collected)
        contents: List[Tuple[str, Entry]] = []
        common: List[str] = []
        for k, e in keys:
            if delimiter:
                rest = k[len(prefix):]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = prefix + rest[:d + len(delimiter)]
                    if not common or common[-1] != cp:
                        common.append(cp)
                    continue
            contents.append((k, e))
        truncated = len(contents) + len(common) > max_keys
        contents = contents[:max_keys]
        root = ET.Element("ListBucketResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = prefix
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        ET.SubElement(root, "KeyCount").text = \
            str(len(contents) + len(common))
        ET.SubElement(root, "IsTruncated").text = \
            "true" if truncated else "false"
        if truncated and contents:
            ET.SubElement(root, "NextContinuationToken").text = \
                contents[-1][0]
        for k, e in contents:
            el = ET.SubElement(root, "Contents")
            ET.SubElement(el, "Key").text = k
            ET.SubElement(el, "LastModified").text = _iso(e.attr.mtime)
            ET.SubElement(el, "ETag").text = f'"{e.attr.md5}"'
            ET.SubElement(el, "Size").text = str(e.size())
            ET.SubElement(el, "StorageClass").text = "STANDARD"
        for cp in common[:max_keys]:
            el = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(el, "Prefix").text = cp
        return _xml(root)

    # -- multipart (reference filer_multipart.go) ---------------------------

    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{self._bucket_path(bucket)}/{UPLOADS_PREFIX}/{upload_id}"

    def initiate_multipart(self, bucket: str, key: str):
        self.filer.find_entry(self._bucket_path(bucket))
        upload_id = uuid.uuid4().hex
        from ..filer.entry import new_dir_entry
        d = new_dir_entry(self._upload_dir(bucket, upload_id))
        d.extended["key"] = key.encode()
        self.filer.create_entry(d)
        root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        return _xml(root)

    def upload_part(self, bucket: str, key: str, q: dict, body: bytes):
        part_num = int(q["partNumber"])
        upload_id = q["uploadId"]
        updir = self._upload_dir(bucket, upload_id)
        self.filer.find_entry(updir)  # NoSuchUpload if missing
        chunks, md5_hex = split_and_upload(
            self.master_url, body, f"part{part_num}", self.chunk_size,
            collection=bucket)
        now = time.time()
        self.filer.create_entry(Entry(
            full_path=f"{updir}/{part_num:05d}.part",
            attr=Attr(mtime=now, crtime=now, md5=md5_hex),
            chunks=chunks))
        return Response(b"", 200, headers={"ETag": f'"{md5_hex}"'})

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           body: bytes):
        updir = self._upload_dir(bucket, upload_id)
        self.filer.find_entry(updir)
        parts = sorted(
            (e for e in self.filer.list_entries(updir, limit=100000)
             if e.name.endswith(".part")),
            key=lambda e: e.name)
        # compose zero-copy: re-base every part's chunks at the part's
        # cumulative offset (reference filer_multipart.go:63-103)
        offset = 0
        all_chunks: List[FileChunk] = []
        etags = hashlib.md5()
        for p in parts:
            for c in p.chunks:
                all_chunks.append(FileChunk(
                    fid=c.fid, offset=offset + c.offset, size=c.size,
                    mtime=c.mtime, etag=c.etag))
            offset += p.size()
            etags.update(bytes.fromhex(p.attr.md5))
        etag = f"{etags.hexdigest()}-{len(parts)}"
        now = time.time()
        self.filer.create_entry(Entry(
            full_path=self._object_path(bucket, key),
            attr=Attr(mtime=now, crtime=now, collection=bucket,
                      mime="application/octet-stream", md5=etag),
            chunks=all_chunks))
        # drop staging metadata only — chunks now belong to the object
        for p in parts:
            p.chunks = []
            self.filer.update_entry(p)
        self.filer.delete_entry(updir, recursive=True,
                                ignore_recursive_error=True)
        root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = f'"{etag}"'
        return _xml(root)

    def abort_multipart(self, bucket: str, key: str, upload_id: str):
        try:
            self.filer.delete_entry(self._upload_dir(bucket, upload_id),
                                    recursive=True,
                                    ignore_recursive_error=True)
        except NotFoundError:
            return _err(404, "NoSuchUpload", upload_id)
        return Response(b"", 204)

    def list_multipart_uploads(self, bucket: str):
        root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        base = f"{self._bucket_path(bucket)}/{UPLOADS_PREFIX}"
        try:
            ups = self.filer.list_entries(base, limit=10000)
        except NotFoundError:
            ups = []
        for u in ups:
            el = ET.SubElement(root, "Upload")
            ET.SubElement(el, "UploadId").text = u.name
            ET.SubElement(el, "Key").text = \
                u.extended.get("key", b"").decode()
            ET.SubElement(el, "Initiated").text = _iso(u.attr.crtime)
        return _xml(root)

    def list_parts(self, bucket: str, key: str, upload_id: str):
        updir = self._upload_dir(bucket, upload_id)
        try:
            parts = self.filer.list_entries(updir, limit=100000)
        except NotFoundError:
            return _err(404, "NoSuchUpload", upload_id)
        root = ET.Element("ListPartsResult", xmlns=XMLNS)
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        for p in sorted(parts, key=lambda e: e.name):
            if not p.name.endswith(".part"):
                continue
            el = ET.SubElement(root, "Part")
            ET.SubElement(el, "PartNumber").text = \
                str(int(p.name.split(".")[0]))
            ET.SubElement(el, "ETag").text = f'"{p.attr.md5}"'
            ET.SubElement(el, "Size").text = str(p.size())
        return _xml(root)


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


def _http_date(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))
