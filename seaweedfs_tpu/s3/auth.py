"""AWS signature authentication (SigV4 incl. presigned + streaming
chunks, SigV2 legacy) and IAM identity config.

Reference weed/s3api/auth_signature_v4.go (doesSignatureMatch,
doesPresignedSignatureMatch), auth_signature_v2.go,
auth_credentials.go (Iam/Identity/Credential/actions).

Verification recomputes the canonical request exactly as AWS documents;
the client-side signer (sign_request_v4) exists for tests and for the
replication S3 sink.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_ADMIN = "Admin"
ACTION_LIST = "List"

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"


class S3AuthError(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(message or code)
        self.status = status
        self.code = code


class Identity:
    def __init__(self, name: str, access_key: str, secret_key: str,
                 actions: Optional[List[str]] = None):
        self.name = name
        self.access_key = access_key
        self.secret_key = secret_key
        self.actions = actions or [ACTION_ADMIN]

    def can(self, action: str, bucket: str) -> bool:
        """Actions may be global ("Write") or bucket-scoped
        ("Write:bucketname") — reference auth_credentials.go canDo."""
        for a in self.actions:
            if a == ACTION_ADMIN or a == f"{ACTION_ADMIN}:{bucket}":
                return True
            if a == action or a == f"{action}:{bucket}":
                return True
        return False


class Iam:
    """Identity store (reference s3api IdentityAccessManagement)."""

    def __init__(self, identities: Optional[List[Identity]] = None):
        self.identities = identities or []

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> Optional[Identity]:
        for ident in self.identities:
            if ident.access_key == access_key:
                return ident
        return None

    @classmethod
    def from_config(cls, cfg: dict) -> "Iam":
        """Parse the reference's s3 config JSON shape
        ({"identities": [{name, credentials: [{accessKey, secretKey}],
        actions: [...]}]})."""
        idents = []
        for i in cfg.get("identities", []):
            for cred in i.get("credentials", []):
                idents.append(Identity(
                    i.get("name", cred["accessKey"]),
                    cred["accessKey"], cred["secretKey"],
                    i.get("actions")))
        return cls(idents)


# -- SigV4 core -------------------------------------------------------------

def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def derive_signing_key(secret: str, date: str, region: str,
                       service: str = "s3") -> bytes:
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query_pairs: List[Tuple[str, str]],
                    skip: Tuple[str, ...] = ()) -> str:
    pairs = sorted((k, v) for k, v in query_pairs if k not in skip)
    return "&".join(f"{_uri_encode(k)}={_uri_encode(v)}"
                    for k, v in pairs)


def canonical_request(method: str, path: str,
                      query_pairs: List[Tuple[str, str]],
                      headers: Dict[str, str], signed_headers: List[str],
                      payload_hash: str,
                      skip_query: Tuple[str, ...] = ()) -> str:
    """`path` must be the request path exactly as sent on the wire
    (already percent-encoded). For S3, SigV4 uses it as-is — re-encoding
    here would double-encode keys with spaces etc. and break real AWS
    clients (SDKs sign with UriEscapePath=false for S3)."""
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    return "\n".join([
        method,
        path or "/",
        canonical_query(query_pairs, skip=skip_query),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canon_req.encode()).hexdigest()])


def authorization_header_v4(method: str, path: str,
                            headers: Dict[str, str], payload_hash: str,
                            access_key: str, secret_key: str,
                            region: str, service: str = "s3",
                            amz_date: str = None) -> str:
    """Client-side SigV4: returns the Authorization header value for a
    request whose lowercase `headers` (must include host, x-amz-date,
    x-amz-content-sha256) will ALL be signed. Shared by the S3 tier
    backend and the SQS publisher so the signing recipe lives once."""
    amz_date = amz_date or headers["x-amz-date"]
    date = amz_date[:8]
    signed = sorted(headers)
    canon = canonical_request(method, path, [], headers, signed,
                              payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    sig = _hmac(derive_signing_key(secret_key, date, region, service),
                string_to_sign(amz_date, scope, canon)).hex()
    return (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")


def _parse_auth_header(auth: str) -> Tuple[str, str, str, List[str], str]:
    """-> (access_key, date, region, signed_headers, signature)"""
    if not auth.startswith("AWS4-HMAC-SHA256"):
        raise S3AuthError(400, "AuthorizationHeaderMalformed")
    fields: Dict[str, str] = {}
    for part in auth[len("AWS4-HMAC-SHA256"):].split(","):
        part = part.strip()
        if "=" in part:
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
    try:
        cred = fields["Credential"].split("/")
        access_key, date, region = cred[0], cred[1], cred[2]
        signed = fields["SignedHeaders"].split(";")
        sig = fields["Signature"]
    except (KeyError, IndexError):
        raise S3AuthError(400, "AuthorizationHeaderMalformed") from None
    return access_key, date, region, signed, sig


def _check_date_window(amz_date: str, window_s: int = 15 * 60):
    """Reject requests signed outside the ±15-minute skew window
    (AWS RequestTimeTooSkewed; the reference enforces it in
    auth_signature_v4.go). Presigned requests expire via
    X-Amz-Expires instead."""
    import calendar
    import time as _time
    try:
        ts = calendar.timegm(_time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        raise S3AuthError(403, "AccessDenied",
                          f"bad x-amz-date {amz_date!r}") from None
    if abs(_time.time() - ts) > window_s:
        raise S3AuthError(403, "RequestTimeTooSkewed",
                          "request signature timestamp outside the "
                          "allowed window")


def verify_v4(iam: Iam, method: str, path: str,
              query_pairs: List[Tuple[str, str]], headers: Dict[str, str],
              body: bytes) -> Identity:
    """Header-based SigV4 check (reference doesSignatureMatch)."""
    lower = {k.lower(): v for k, v in headers.items()}
    access_key, date, region, signed, given_sig = \
        _parse_auth_header(lower.get("authorization", ""))
    ident = iam.lookup(access_key)
    if ident is None:
        raise S3AuthError(403, "InvalidAccessKeyId")
    payload_hash = lower.get("x-amz-content-sha256", UNSIGNED_PAYLOAD)
    if payload_hash not in (UNSIGNED_PAYLOAD,) and \
            not payload_hash.startswith(STREAMING_PAYLOAD):
        actual = hashlib.sha256(body).hexdigest()
        if actual != payload_hash:
            raise S3AuthError(403, "XAmzContentSHA256Mismatch")
    amz_date = lower.get("x-amz-date", "")
    _check_date_window(amz_date)
    scope = f"{date}/{region}/s3/aws4_request"
    canon = canonical_request(method, path, query_pairs, lower, signed,
                              payload_hash)
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(ident.secret_key, date, region)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, given_sig):
        raise S3AuthError(403, "SignatureDoesNotMatch")
    return ident


def verify_v4_presigned(iam: Iam, method: str, path: str,
                        query_pairs: List[Tuple[str, str]],
                        headers: Dict[str, str]) -> Identity:
    """Query-string SigV4 (reference doesPresignedSignatureMatch)."""
    q = dict(query_pairs)
    try:
        cred = q["X-Amz-Credential"].split("/")
        access_key, date, region = cred[0], cred[1], cred[2]
        signed = q["X-Amz-SignedHeaders"].split(";")
        given_sig = q["X-Amz-Signature"]
        amz_date = q["X-Amz-Date"]
    except (KeyError, IndexError):
        raise S3AuthError(400, "AuthorizationQueryParametersError") \
            from None
    import calendar
    expires = int(q.get("X-Amz-Expires", "900"))
    t0 = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    if time.time() - t0 > expires:
        raise S3AuthError(403, "AccessDenied", "request expired")
    ident = iam.lookup(access_key)
    if ident is None:
        raise S3AuthError(403, "InvalidAccessKeyId")
    lower = {k.lower(): v for k, v in headers.items()}
    scope = f"{date}/{region}/s3/aws4_request"
    canon = canonical_request(method, path, query_pairs, lower, signed,
                              UNSIGNED_PAYLOAD,
                              skip_query=("X-Amz-Signature",))
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(ident.secret_key, date, region)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, given_sig):
        raise S3AuthError(403, "SignatureDoesNotMatch")
    return ident


def verify_v2(iam: Iam, method: str, path: str, headers: Dict[str, str],
              ) -> Identity:
    """Legacy SigV2 (reference auth_signature_v2.go): HMAC-SHA1 over
    method/md5/type/date/canonicalized-amz-headers+resource."""
    import base64
    lower = {k.lower(): v for k, v in headers.items()}
    auth = lower.get("authorization", "")
    if not auth.startswith("AWS "):
        raise S3AuthError(400, "AuthorizationHeaderMalformed")
    try:
        access_key, given = auth[4:].split(":", 1)
    except ValueError:
        raise S3AuthError(400, "AuthorizationHeaderMalformed") from None
    ident = iam.lookup(access_key)
    if ident is None:
        raise S3AuthError(403, "InvalidAccessKeyId")
    amz = sorted((k, v) for k, v in lower.items()
                 if k.startswith("x-amz-"))
    canon_amz = "".join(f"{k}:{v}\n" for k, v in amz)
    sts = (f"{method}\n{lower.get('content-md5', '')}\n"
           f"{lower.get('content-type', '')}\n{lower.get('date', '')}\n"
           f"{canon_amz}{path}")
    want = base64.b64encode(
        hmac.new(ident.secret_key.encode(), sts.encode(),
                 hashlib.sha1).digest()).decode()
    if not hmac.compare_digest(want, given):
        raise S3AuthError(403, "SignatureDoesNotMatch")
    return ident


def authenticate(iam: Iam, method: str, path: str,
                 query_pairs: List[Tuple[str, str]],
                 headers: Dict[str, str], body: bytes) -> Optional[Identity]:
    """Dispatch on auth style; None = anonymous allowed (iam disabled)."""
    if not iam.enabled:
        return None
    lower = {k.lower(): v for k, v in headers.items()}
    q = dict(query_pairs)
    auth = lower.get("authorization", "")
    if auth.startswith("AWS4-HMAC-SHA256"):
        return verify_v4(iam, method, path, query_pairs, headers, body)
    if "X-Amz-Signature" in q:
        return verify_v4_presigned(iam, method, path, query_pairs, headers)
    if auth.startswith("AWS "):
        return verify_v2(iam, method, path, headers)
    raise S3AuthError(403, "AccessDenied", "no credentials")


# -- streaming aws-chunked payload (reference chunked_reader_v4.go) ---------

def decode_aws_chunked(body: bytes, *, secret_key: str = "",
                       seed_signature: str = "", scope: str = "",
                       amz_date: str = "", verify: bool = False) -> bytes:
    """Decode STREAMING-AWS4-HMAC-SHA256-PAYLOAD framing:
    <hex-size>;chunk-signature=<sig>\r\n<data>\r\n ... 0;chunk-signature=...
    With verify=True, each chunk signature is checked against the rolling
    chunk string-to-sign chain."""
    if verify and scope.count("/") < 3:
        # sigv2 / presigned auth cannot carry a chunk-signature chain —
        # AWS requires header-based SigV4 for streaming payloads
        raise S3AuthError(403, "AccessDenied",
                          "streaming chunked payload requires "
                          "header-based SigV4 authentication")
    out = bytearray()
    pos = 0
    prev_sig = seed_signature
    while pos < len(body):
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise S3AuthError(400, "IncompleteBody", "bad chunk header")
        header = body[pos:nl].decode("ascii", "replace")
        size_s, _, ext = header.partition(";")
        try:
            size = int(size_s, 16)
        except ValueError:
            raise S3AuthError(400, "IncompleteBody",
                              f"bad chunk size {size_s!r}") from None
        data = body[nl + 2:nl + 2 + size]
        if len(data) < size:
            raise S3AuthError(400, "IncompleteBody", "short chunk")
        if verify:
            sig = ext.partition("chunk-signature=")[2]
            sts = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev_sig,
                hashlib.sha256(b"").hexdigest(),
                hashlib.sha256(data).hexdigest()])
            date, region = scope.split("/")[0:2]
            key = derive_signing_key(secret_key, date, region)
            want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, sig):
                raise S3AuthError(403, "SignatureDoesNotMatch",
                                  "chunk signature mismatch")
            prev_sig = sig
        out += data
        pos = nl + 2 + size + 2  # skip trailing \r\n
        if size == 0:
            break
    return bytes(out)


# -- client-side signer (tests + S3 replication sink) -----------------------

def sign_request_v4(method: str, url: str, headers: Dict[str, str],
                    body: bytes, access_key: str, secret_key: str,
                    region: str = "us-east-1",
                    amz_time: Optional[float] = None) -> Dict[str, str]:
    """Sign; returns the headers dict with Authorization et al added."""
    parsed = urllib.parse.urlparse(url)
    now = time.gmtime(amz_time if amz_time is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    date = amz_date[:8]
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = dict(headers)
    headers["Host"] = parsed.netloc
    headers["X-Amz-Date"] = amz_date
    headers["X-Amz-Content-Sha256"] = payload_hash
    lower = {k.lower(): v for k, v in headers.items()}
    signed = sorted(lower)
    query_pairs = urllib.parse.parse_qsl(parsed.query,
                                         keep_blank_values=True)
    scope = f"{date}/{region}/s3/aws4_request"
    canon = canonical_request(method, parsed.path or "/", query_pairs,
                              lower, signed, payload_hash)
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(secret_key, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


def presign_url_v4(method: str, url: str, access_key: str,
                   secret_key: str, expires: int = 900,
                   region: str = "us-east-1",
                   amz_time: Optional[float] = None) -> str:
    parsed = urllib.parse.urlparse(url)
    now = time.gmtime(amz_time if amz_time is not None else time.time())
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    q += [("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
          ("X-Amz-Credential", f"{access_key}/{scope}"),
          ("X-Amz-Date", amz_date),
          ("X-Amz-Expires", str(expires)),
          ("X-Amz-SignedHeaders", "host")]
    headers = {"host": parsed.netloc}
    canon = canonical_request(method, parsed.path or "/", q, headers,
                              ["host"], UNSIGNED_PAYLOAD)
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(secret_key, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    q.append(("X-Amz-Signature", sig))
    return urllib.parse.urlunparse(parsed._replace(
        query=urllib.parse.urlencode(q)))
