"""Prometheus-compatible metrics (reference weed/stats/metrics.go).

The reference registers counters/histograms/gauges into per-role
gatherers (FilerGather, VolumeServerGather) and pushes them to a
pushgateway on an interval the master broadcasts; this build exposes the
same families on a pull `/metrics` endpoint (the modern deployment
shape) and keeps an optional push loop for parity.
"""

from __future__ import annotations

import bisect
import threading
from ..util.locks import make_lock
import time
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1,
                    0.3, 1.0, 3.0, 10.0)


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double quote,
    and line feed must be escaped inside the quoted label value."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text) -> str:
    """HELP lines escape only backslash and line feed (the value is not
    quoted, so double quotes pass through verbatim)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _unescape_help(text: str) -> str:
    out = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _unescape_label_value(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ('"', "\\"):
                out.append(nxt)
            else:           # unknown escape: keep verbatim
                out.append(c)
                out.append(nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt_value(v) -> str:
    """Render a sample value so that parse(render(v)) == v exactly.

    Integral values print without a decimal point (matching the plain
    int rendering of histogram bucket counts); everything else uses
    repr(), Python's shortest round-trip float representation.  The
    %g formatting this replaces silently truncated to 6 significant
    digits, which broke the render->parse->render fixed point for
    large counters."""
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _parse_value(text: str) -> float:
    t = text.strip()
    if t in ("+Inf", "Inf"):
        return float("inf")
    if t == "-Inf":
        return float("-inf")
    if t == "NaN":
        return float("nan")
    return float(t)


def _fmt_exemplar(labels, value, ts) -> str:
    """OpenMetrics-style exemplar suffix for a sample line:
    `` # {trace_id="..."} <observed value> <unix ts>``. Appended to
    ``_bucket`` series so a tail-latency bucket carries the trace id of
    the request that landed in it."""
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return f" # {{{body}}} {_fmt_value(value)} {_fmt_value(ts)}"


def _label_block_end(line: str, start: int) -> int:
    """Index just past the ``}`` closing the label block whose ``{`` is
    at ``start``, honoring quoted and escaped label values."""
    i = start + 1
    n = len(line)
    in_q = False
    while i < n:
        c = line[i]
        if in_q:
            if c == "\\":
                i += 1
            elif c == '"':
                in_q = False
        elif c == '"':
            in_q = True
        elif c == "}":
            return i + 1
        i += 1
    return -1


def _split_exemplar(line: str):
    """Split a sample line into (sample part, exemplar or None).

    The exemplar tail is `` # {labels} value ts``. The marker search
    starts AFTER the sample's own label block, so a label VALUE
    containing " # {" never mis-splits."""
    i = 0
    n = len(line)
    while i < n and line[i] not in "{ ":
        i += 1
    if i < n and line[i] == "{":
        i = _label_block_end(line, i)
        if i < 0:
            raise ValueError(f"unterminated label block in {line!r}")
    idx = line.find(" # {", i)
    if idx < 0:
        return line, None
    open_b = idx + 3
    close = _label_block_end(line, open_b)
    if close < 0:
        raise ValueError(f"malformed exemplar in {line!r}")
    labels = _parse_labels(line[open_b + 1:close - 1])
    rest = line[close:].split()
    if len(rest) != 2:
        raise ValueError(f"malformed exemplar in {line!r}")
    return line[:idx], (labels, _parse_value(rest[0]),
                        _parse_value(rest[1]))


def _parse_labels(body: str) -> Tuple[Tuple[str, str], ...]:
    """Parse the inside of a {...} label block, honoring escapes."""
    pairs = []
    i = 0
    n = len(body)
    while i < n:
        while i < n and body[i] in ", ":
            i += 1
        if i >= n:
            break
        eq = body.index("=", i)
        name = body[i:eq].strip()
        i = eq + 1
        if i >= n or body[i] != '"':
            raise ValueError(f"unquoted label value in {body!r}")
        i += 1
        raw = []
        while i < n:
            c = body[i]
            if c == "\\" and i + 1 < n:
                raw.append(body[i:i + 2])
                i += 2
                continue
            if c == '"':
                break
            raw.append(c)
            i += 1
        if i >= n:
            raise ValueError(f"unterminated label value in {body!r}")
        i += 1  # closing quote
        pairs.append((name, _unescape_label_value("".join(raw))))
    return tuple(pairs)


def parse_prometheus_text(text: str) -> List[Dict]:
    """Parse a Prometheus text exposition back into sample families.

    Returns an ordered list of dicts:
        {"name": family name, "kind": counter|gauge|histogram|untyped,
         "help": help text,
         "samples": [(sample_name, ((label, value), ...), float), ...]}

    Histogram child series (`_bucket`/`_sum`/`_count`) are grouped under
    their family.  Exemplar tails (`` # {trace_id="..."} v ts``) are
    kept out-of-band — samples stay 3-tuples for every existing
    consumer — in the family's ``"exemplars"`` dict, keyed by
    ``(sample_name, labels)``.  Designed as the exact inverse of
    Registry.render(): render -> parse -> render_families is a fixed
    point, so the cluster aggregator can merge scraped text without
    dropping samples (or their exemplars)."""
    families: List[Dict] = []
    by_name: Dict[str, Dict] = {}

    def family_for_sample(sample_name: str) -> Dict:
        # histogram children carry suffixes; try the longest prefix
        for cand in (sample_name, sample_name.rsplit("_bucket", 1)[0],
                     sample_name.rsplit("_sum", 1)[0],
                     sample_name.rsplit("_count", 1)[0]):
            fam = by_name.get(cand)
            if fam is not None:
                return fam
        fam = {"name": sample_name, "kind": "untyped", "help": "",
               "samples": []}
        families.append(fam)
        by_name[sample_name] = fam
        return fam

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            help_text = _unescape_help(help_text)
            fam = by_name.get(name)
            if fam is None:
                fam = {"name": name, "kind": "untyped", "help": help_text,
                       "samples": []}
                families.append(fam)
                by_name[name] = fam
            else:
                fam["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) >= 2:
                name, kind = parts[0], parts[1]
                fam = by_name.get(name)
                if fam is None:
                    fam = {"name": name, "kind": kind, "help": "",
                           "samples": []}
                    families.append(fam)
                    by_name[name] = fam
                else:
                    fam["kind"] = kind
            continue
        if line.startswith("#"):
            continue
        # sample line: name[{labels}] value [# {exemplar} v ts]
        line, exemplar = _split_exemplar(line)
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ValueError(f"malformed sample line: {line!r}")
            sample_name = line[:brace]
            labels = _parse_labels(line[brace + 1:close])
            value = _parse_value(line[close + 1:])
        else:
            sample_name, _, value_text = line.partition(" ")
            labels = ()
            value = _parse_value(value_text)
        fam = family_for_sample(sample_name)
        fam["samples"].append((sample_name, labels, value))
        if exemplar is not None:
            fam.setdefault("exemplars", {})[(sample_name, labels)] = \
                exemplar
    return families


def render_families(families: List[Dict]) -> str:
    """Render parsed families back to exposition text — the inverse of
    parse_prometheus_text, and line-identical to Registry.render() for
    text that originated there."""
    lines: List[str] = []
    for fam in families:
        lines.append(f"# HELP {fam['name']} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {fam['name']} {fam['kind']}")
        exemplars = fam.get("exemplars") or {}
        for sample_name, labels, value in fam["samples"]:
            if labels:
                body = ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in labels)
                line = f"{sample_name}{{{body}}} {_fmt_value(value)}"
            else:
                line = f"{sample_name} {_fmt_value(value)}"
            ex = exemplars.get((sample_name, labels))
            if ex is not None:
                line += _fmt_exemplar(*ex)
            lines.append(line)
    return "\n".join(lines) + "\n"


def _fmt_labels(label_names, label_values) -> str:
    if not label_names:
        return ""
    pairs = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in
                     zip(label_names, label_values))
    return "{" + pairs + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = "",
                 labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(labels)
        self._lock = make_lock("metrics.Metric._lock")

    def header(self) -> List[str]:
        return [f"# HELP {self.name} {_escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}"]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text="", labels=()):
        super().__init__(name, help_text, labels)
        self._values: Dict[tuple, float] = {}

    def inc(self, *label_values, amount: float = 1.0):
        with self._lock:
            self._values[label_values] = \
                self._values.get(label_values, 0.0) + amount

    def set_total(self, value: float, *label_values):
        """Snapshot-mirror a monotonic count maintained elsewhere (the
        native read plane keeps its own atomics); semantically still a
        counter — the source only ever increases within a process."""
        with self._lock:
            self._values[label_values] = value

    def value(self, *label_values) -> float:
        with self._lock:
            return self._values.get(label_values, 0.0)

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for lv, v in sorted(self._values.items()):
                out.append(
                    f"{self.name}"
                    f"{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text="", labels=()):
        super().__init__(name, help_text, labels)
        self._values: Dict[tuple, float] = {}

    def set(self, value: float, *label_values):
        with self._lock:
            self._values[label_values] = value

    def value(self, *label_values) -> float:
        with self._lock:
            return self._values.get(label_values, 0.0)

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for lv, v in sorted(self._values.items()):
                out.append(
                    f"{self.name}"
                    f"{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text="", labels=(),
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[tuple, List[int]] = {}
        self._sums: Dict[tuple, float] = {}
        self._totals: Dict[tuple, int] = {}
        # label_values -> bucket index -> (labels, value, ts); index
        # len(self.buckets) is the +Inf bucket. Newest observation wins.
        self._exemplars: Dict[tuple, Dict[int, tuple]] = {}

    def observe(self, value: float, *label_values,
                trace_id: Optional[str] = None):
        with self._lock:
            counts = self._counts.setdefault(
                label_values, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[label_values] = \
                self._sums.get(label_values, 0.0) + value
            self._totals[label_values] = \
                self._totals.get(label_values, 0) + 1
            if trace_id:
                # one exemplar per bucket, newest wins: a p99 outlier
                # lands in a top bucket and stays referable until a
                # slower request replaces it
                self._exemplars.setdefault(label_values, {})[i] = (
                    (("trace_id", str(trace_id)),), float(value),
                    time.time())

    def set_buckets(self, counts, total: int, sum_value: float,
                    *label_values):
        """Snapshot-mirror a histogram maintained elsewhere (the native
        read plane keeps per-bucket atomics): ``counts`` are
        NON-cumulative per-bucket counts aligned with ``self.buckets``
        (any overflow beyond the last bound is implied by ``total``),
        plus the observation count and value sum."""
        with self._lock:
            store = [0] * len(self.buckets)
            for i, c in enumerate(counts[:len(store)]):
                store[i] = int(c)
            self._counts[label_values] = store
            self._totals[label_values] = int(total)
            self._sums[label_values] = float(sum_value)

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for lv in sorted(self._counts):
                ex_map = self._exemplars.get(lv, {})
                cumulative = 0
                for i, (bound, c) in enumerate(
                        zip(self.buckets, self._counts[lv])):
                    cumulative += c
                    labels = _fmt_labels(
                        self.label_names + ("le",),
                        lv + (f"{bound:g}",))
                    line = f"{self.name}_bucket{labels} {cumulative}"
                    ex = ex_map.get(i)
                    if ex is not None:
                        line += _fmt_exemplar(*ex)
                    out.append(line)
                labels = _fmt_labels(self.label_names + ("le",),
                                     lv + ("+Inf",))
                line = f"{self.name}_bucket{labels} {self._totals[lv]}"
                ex = ex_map.get(len(self.buckets))
                if ex is not None:
                    line += _fmt_exemplar(*ex)
                out.append(line)
                base = _fmt_labels(self.label_names, lv)
                out.append(f"{self.name}_sum{base} "
                           f"{_fmt_value(self._sums[lv])}")
                out.append(f"{self.name}_count{base} "
                           f"{self._totals[lv]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self._lock = make_lock("metrics.Registry._lock")

    def register(self, metric: _Metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def counter(self, name, help_text="", labels=()) -> Counter:
        return self.register(Counter(name, help_text, labels))

    def gauge(self, name, help_text="", labels=()) -> Gauge:
        return self.register(Gauge(name, help_text, labels))

    def histogram(self, name, help_text="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, labels, buckets))

    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# -- per-role gatherers (reference metrics.go:14-107) -----------------------

MASTER_GATHER = Registry()
VOLUME_SERVER_GATHER = Registry()
FILER_GATHER = Registry()

VOLUME_REQUEST_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_request_total",
    "Counter of volume server requests.", labels=("type",))
VOLUME_REQUEST_HISTOGRAM = VOLUME_SERVER_GATHER.histogram(
    "SeaweedFS_volumeServer_request_seconds",
    "Bucketed histogram of volume server request processing time.",
    labels=("type",))
VOLUME_COUNT_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_volumes",
    "Number of volumes or EC shards.",
    labels=("collection", "type"))
VOLUME_DISK_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_total_disk_size",
    "Actual disk size used by volumes.",
    labels=("collection", "type"))
FAST_PLANE_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_fast_plane_request_total",
    "Requests handled by the native C++ read plane.",
    labels=("outcome",))

FILER_REQUEST_COUNTER = FILER_GATHER.counter(
    "SeaweedFS_filer_request_total",
    "Counter of filer requests.", labels=("type",))
FILER_REQUEST_HISTOGRAM = FILER_GATHER.histogram(
    "SeaweedFS_filer_request_seconds",
    "Bucketed histogram of filer request processing time.",
    labels=("type",))

MASTER_REQUEST_COUNTER = MASTER_GATHER.counter(
    "SeaweedFS_master_request_total",
    "Counter of master requests.", labels=("type",))
MASTER_REQUEST_HISTOGRAM = MASTER_GATHER.histogram(
    "SeaweedFS_master_request_seconds",
    "Bucketed histogram of master request processing time.",
    labels=("type",))

# -- fleet health plane: cluster scrape (stats/aggregate.py) -----------------

CLUSTER_SCRAPE_COUNTER = MASTER_GATHER.counter(
    "SeaweedFS_master_cluster_scrape_total",
    "Cluster /metrics scrape attempts by outcome (ok, error).",
    labels=("outcome",))
CLUSTER_SCRAPE_SECONDS = MASTER_GATHER.histogram(
    "SeaweedFS_master_cluster_scrape_seconds",
    "Bucketed duration of one full cluster scrape sweep.")
CLUSTER_NODE_UP_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_cluster_node_up",
    "1 if the node's last /metrics scrape succeeded, 0 if it is stale.",
    labels=("node",))
CLUSTER_NODES_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_cluster_scraped_nodes",
    "Nodes currently held by the cluster aggregator, by freshness "
    "(fresh, stale).",
    labels=("state",))

# -- hot→warm tiering (server/tiering.py) ------------------------------------

MASTER_TIER_DEMOTIONS = MASTER_GATHER.counter(
    "SeaweedFS_master_tier_demotions_total",
    "Volume demotions finished by the background tierer, by result "
    "(ok, failed).",
    labels=("result",))
MASTER_TIER_SECONDS = MASTER_GATHER.counter(
    "SeaweedFS_master_tier_demotion_seconds_total",
    "Cumulative wall seconds spent demoting volumes to EC warm "
    "storage.")
MASTER_TIER_BYTES = MASTER_GATHER.counter(
    "SeaweedFS_master_tier_demoted_bytes_total",
    "Hot .dat bytes converted to EC warm storage by the tierer.")
MASTER_TIER_MBPS_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_tier_mbps",
    "Effective demotion bandwidth of the last completed demotion "
    "(hot bytes / wall seconds — the rate cap should show here).")
MASTER_TIER_VOLUMES_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_tier_volumes",
    "Volumes currently tracked by the tierer, by lifecycle state "
    "(candidate, demoting, warm, failed).",
    labels=("state",))

# -- EC phase spans (fed by util/tracing via observe_span) -------------------

EC_PHASE_NAMES = ("gather", "plan", "dispatch", "drain", "write")

VOLUME_EC_PHASE_HISTOGRAM = VOLUME_SERVER_GATHER.histogram(
    "SeaweedFS_volumeServer_ec_phase_seconds",
    "Bucketed histogram of per-phase EC span durations.",
    labels=("phase",))
VOLUME_EC_PHASE_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_phase_seconds_total",
    "Cumulative seconds spent in each EC phase.",
    labels=("phase",))
DEVICE_TELEMETRY_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_device_telemetry_total",
    "Process-global device codec telemetry (ops/telemetry.STATS).",
    labels=("kind",))
SMALL_DISPATCH_SUGGESTED_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_small_dispatch_suggested_bytes",
    "Suggested SW_EC_SMALL_DISPATCH_BYTES fitted from the first "
    "reconstruct spans (0 until enough samples).")

# -- streaming gather (ec/gather.py via observe_gather) ----------------------

VOLUME_EC_GATHER_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_gather_total",
    "Streaming-rebuild gather events by kind (bytes, fetches, stripes, "
    "retries, hedges_fired, hedges_won, hedges_lost).",
    labels=("kind",))
VOLUME_EC_GATHER_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_gather_seconds_total",
    "Cumulative gather busy time (union of in-flight fetch intervals) "
    "across streaming rebuilds.")
VOLUME_EC_GATHER_MBPS_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_gather_mbps",
    "Effective gather bandwidth of the last streaming rebuild "
    "(fetched bytes / busy seconds).")
VOLUME_EC_OVERLAP_FRAC_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_overlap_frac",
    "Gather/compute overlap of the last streaming rebuild: "
    "(serialized_estimate - wall) / serialized_estimate, 0..1.")
HTTP_POOL_CHURN_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_http_pool_churn_total",
    "Keep-alive connection pool events (created, reused, "
    "evicted_stale, evicted_idle, evicted_overflow).",
    labels=("event",))


def observe_gather(stats: Dict):
    """Export one streaming rebuild's gather stats (the dict filled by
    ec.encoder.rebuild_ec_files_streaming) onto the volume registry."""
    if not stats:
        return
    for kind, key in (("bytes", "gather_bytes"),
                      ("fetches", "gather_fetches"),
                      ("stripes", "gather_stripes"),
                      ("retries", "gather_retries"),
                      ("hedges_fired", "hedges_fired"),
                      ("hedges_won", "hedges_won"),
                      ("hedges_lost", "hedges_lost")):
        n = stats.get(key)
        if n:
            VOLUME_EC_GATHER_COUNTER.inc(kind, amount=n)
    busy = stats.get("gather_busy_s")
    if busy:
        VOLUME_EC_GATHER_SECONDS.inc(amount=busy)
    if "gather_mbps" in stats:
        VOLUME_EC_GATHER_MBPS_GAUGE.set(stats["gather_mbps"])
    if "overlap_frac" in stats:
        VOLUME_EC_OVERLAP_FRAC_GAUGE.set(stats["overlap_frac"])


# -- mesh-sharded dispatch (ops/telemetry deltas via observe_mesh) -----------

VOLUME_EC_MESH_DISPATCH_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_mesh_dispatches_total",
    "Mesh-sharded device dispatches: one jit call whose payload width "
    "axis spans the device mesh (single-device crossover dispatches "
    "are counted under ec_device_telemetry_total only).")
VOLUME_EC_MESH_WIDTH_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_mesh_dispatch_width_devices",
    "Devices the last mesh EC operation's dispatches landed bytes on "
    "(1 = silent fall-back to width-1 dispatch — the r05 regression "
    "mode this gauge exists to catch).")
VOLUME_EC_MESH_DEVICE_BYTES = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_mesh_device_bytes_total",
    "Payload bytes landed on each mesh device by sharded dispatches.",
    labels=("device",))
VOLUME_EC_MESH_BUSY_FRAC_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_mesh_device_busy_frac",
    "Per-device byte share of the last mesh EC operation relative to "
    "the busiest device (1.0 everywhere = even shard split).",
    labels=("device",))


def observe_mesh(stats: Dict):
    """Export one EC operation's mesh-dispatch telemetry (the
    ops/telemetry.delta keys inside the stats dict filled by the
    encode/rebuild paths) onto the volume registry."""
    if not stats:
        return
    n = stats.get("mesh_dispatches")
    if n:
        VOLUME_EC_MESH_DISPATCH_COUNTER.inc(amount=n)
    for dev, nbytes in (stats.get("mesh_device_bytes") or {}).items():
        if nbytes:
            VOLUME_EC_MESH_DEVICE_BYTES.inc(str(dev), amount=nbytes)
    width = stats.get("dispatch_width_devices")
    if width:
        VOLUME_EC_MESH_WIDTH_GAUGE.set(width)
    for dev, frac in (stats.get("device_busy_frac") or {}).items():
        VOLUME_EC_MESH_BUSY_FRAC_GAUGE.set(frac, str(dev))


# -- device-runtime plane (ops/device_stats via observe_device_stats) --------

VOLUME_EC_XLA_COMPILES = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_compiles_total",
    "XLA executables compiled per instrumented jit entry point "
    "(ops/device_stats.wrap: one AOT lower().compile() per abstract "
    "shape signature).",
    labels=("entry",))
VOLUME_EC_XLA_COMPILE_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_compile_seconds_total",
    "Wall seconds spent inside timed lower().compile() calls per "
    "entry point — the warmup cost bench.py splits out of every "
    "headline.",
    labels=("entry",))
VOLUME_EC_XLA_RECOMPILES = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_recompiles_total",
    "Compiles beyond the first for the same (entry, width-bucket) "
    "pair — broken width-bucketing as a counter, not a wall-time "
    "mystery. Steady state is 0.",
    labels=("entry",))
VOLUME_EC_XLA_RECOMPILE_SENTINEL = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_xla_recompile_sentinel",
    "Latches to 1 the first time any (entry, width-bucket) pair "
    "compiles twice in this process; never resets.")
VOLUME_EC_XLA_DISPATCHES = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_dispatches_total",
    "Instrumented jit dispatches per entry point.",
    labels=("entry",))
VOLUME_EC_XLA_DEVICE_SAMPLES = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_device_samples_total",
    "Dispatches timed through block_until_ready under "
    "SW_EC_DEVICE_TIMING (every SW_EC_DEVICE_TIMING_SAMPLE'th).",
    labels=("entry",))
VOLUME_EC_XLA_DEVICE_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_device_seconds_total",
    "Summed sampled device seconds per entry point; multiply the "
    "per-sample mean by ec_xla_dispatches_total for the estimated "
    "total.",
    labels=("entry",))
VOLUME_EC_XLA_JIT_CACHE = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_xla_jit_cache_total",
    "lru_cache jit-factory events (hits, misses, evictions); an "
    "evicted jitted fn is a silent recompile on next use.",
    labels=("factory", "event"))
VOLUME_EC_XLA_JIT_CACHE_ENTRIES = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_xla_jit_cache_entries",
    "Live entries per lru_cache jit factory (cache_info().currsize; "
    "maxsize is SW_EC_JIT_CACHE_SIZE).",
    labels=("factory",))
VOLUME_EC_XLA_DEVICE_MEMORY = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_xla_device_memory_bytes",
    "device.memory_stats() gauges where the backend exposes them "
    "(bytes_in_use, peak_bytes_in_use, ... per device).",
    labels=("device", "kind"))
VOLUME_EC_CONST_CACHE_EVENTS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_const_cache_events_total",
    "_ConstCache device-constant events (hits, misses, evictions); a "
    "miss is one bit-matrix lift + upload, an eviction forces a "
    "re-upload on next use.",
    labels=("event",))
VOLUME_EC_CONST_CACHE_ENTRIES = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_const_cache_entries",
    "Device-resident coefficient constants held across all live "
    "_ConstCache instances.")
VOLUME_EC_CONST_CACHE_BYTES = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_const_cache_bytes",
    "Device bytes pinned by cached coefficient constants across all "
    "live _ConstCache instances.")


def observe_device_stats(snap: Dict, factories: Dict = None,
                         inventory: Dict = None):
    """Mirror an ops/device_stats snapshot (plus optional jit-factory
    cache_info and device inventory) onto the volume registry. Uses
    set_total: the plane's counters are process-global monotonic, so
    each scrape overwrites rather than accumulates."""
    if not snap:
        return
    for entry, n in snap.get("compiles", {}).items():
        VOLUME_EC_XLA_COMPILES.set_total(n, entry)
    for entry, s in snap.get("compile_seconds", {}).items():
        VOLUME_EC_XLA_COMPILE_SECONDS.set_total(s, entry)
    for entry, n in snap.get("recompiles", {}).items():
        VOLUME_EC_XLA_RECOMPILES.set_total(n, entry)
    VOLUME_EC_XLA_RECOMPILE_SENTINEL.set(
        1 if snap.get("sentinel") else 0)
    for entry, n in snap.get("dispatches", {}).items():
        VOLUME_EC_XLA_DISPATCHES.set_total(n, entry)
    for entry, n in snap.get("device_samples", {}).items():
        VOLUME_EC_XLA_DEVICE_SAMPLES.set_total(n, entry)
    for entry, s in snap.get("device_seconds", {}).items():
        VOLUME_EC_XLA_DEVICE_SECONDS.set_total(s, entry)
    for event, n in snap.get("const_cache", {}).items():
        VOLUME_EC_CONST_CACHE_EVENTS.set_total(n, event)
    occ = snap.get("const_cache_occupancy") or {}
    VOLUME_EC_CONST_CACHE_ENTRIES.set(occ.get("entries", 0))
    VOLUME_EC_CONST_CACHE_BYTES.set(occ.get("bytes", 0))
    for factory, info in (factories or {}).items():
        for event in ("hits", "misses", "evictions"):
            VOLUME_EC_XLA_JIT_CACHE.set_total(
                info.get(event, 0), factory, event)
        VOLUME_EC_XLA_JIT_CACHE_ENTRIES.set(
            info.get("currsize", 0), factory)
    for dev in (inventory or {}).get("devices", []):
        name = f"{(inventory or {}).get('platform')}:{dev.get('id')}"
        for kind, val in (dev.get("memory_stats") or {}).items():
            if isinstance(val, (int, float)):
                VOLUME_EC_XLA_DEVICE_MEMORY.set(val, name, str(kind))


# -- trace repair (ec/decoder.rebuild_ec_file_repair via observe_repair) -----

VOLUME_EC_REPAIR_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_repair_total",
    "Single-shard repair events by kind (trace_rebuilds, "
    "full_rebuilds, fallbacks, symbol_bytes, baseline_bytes).",
    labels=("kind",))
VOLUME_EC_REPAIR_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_repair_seconds_total",
    "Cumulative symbol-gather busy time across trace repairs.")
VOLUME_EC_REPAIR_BYTES_FRAC_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_repair_bytes_frac",
    "Repair traffic of the last trace repair as a fraction of the "
    "k*shard baseline the full gather would move (lower is better; "
    "1.0 means no gain).")
VOLUME_EC_REPAIR_SYMBOL_BITS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_repair_symbol_bits_total",
    "Per-survivor repair symbol widths: how many survivors shipped "
    "each bits-per-byte projection width across trace repairs.",
    labels=("bits",))


# -- piggyback plane repair (ec/decoder.rebuild_ec_file_piggyback) -----------

VOLUME_EC_PIGGYBACK_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_piggyback_total",
    "Piggyback-layout plane repair events by kind (plane_rebuilds, "
    "plane_bytes, baseline_bytes).",
    labels=("kind",))
VOLUME_EC_PIGGYBACK_BYTES_FRAC_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_piggyback_bytes_frac",
    "Repair traffic of the last piggyback plane repair as a fraction "
    "of the k*shard baseline the full gather would move (the coupled "
    "layout's floor is (k+1)/(2k); lower is better).")


def observe_repair(stats: Dict):
    """Export one rebuild's repair-mode stats (the dict filled by
    ec.decoder.rebuild_ec_file_repair / rebuild_ec_file_piggyback, or
    the fallback markers left by storage/store) onto the volume
    registry."""
    if not stats or "repair_mode" not in stats:
        return
    if stats.get("repair_fallback"):
        VOLUME_EC_REPAIR_COUNTER.inc("fallbacks")
    mode = stats["repair_mode"]
    if mode == "piggyback":
        VOLUME_EC_PIGGYBACK_COUNTER.inc("plane_rebuilds")
        for kind, key in (("plane_bytes", "repair_bytes"),
                          ("baseline_bytes", "repair_baseline_bytes")):
            n = stats.get(key)
            if n:
                VOLUME_EC_PIGGYBACK_COUNTER.inc(kind, amount=n)
        busy = stats.get("gather_busy_s")
        if busy:
            VOLUME_EC_REPAIR_SECONDS.inc(amount=busy)
        if "repair_bytes_frac" in stats:
            VOLUME_EC_PIGGYBACK_BYTES_FRAC_GAUGE.set(
                stats["repair_bytes_frac"])
        return
    if mode != "trace":
        VOLUME_EC_REPAIR_COUNTER.inc("full_rebuilds")
        return
    VOLUME_EC_REPAIR_COUNTER.inc("trace_rebuilds")
    for kind, key in (("symbol_bytes", "repair_bytes"),
                      ("baseline_bytes", "repair_baseline_bytes")):
        n = stats.get(key)
        if n:
            VOLUME_EC_REPAIR_COUNTER.inc(kind, amount=n)
    busy = stats.get("gather_busy_s")
    if busy:
        VOLUME_EC_REPAIR_SECONDS.inc(amount=busy)
    if "repair_bytes_frac" in stats:
        VOLUME_EC_REPAIR_BYTES_FRAC_GAUGE.set(stats["repair_bytes_frac"])
    for bits in (stats.get("repair_bits") or {}).values():
        VOLUME_EC_REPAIR_SYMBOL_BITS.inc(str(bits), amount=bits)


# -- EC plan caches (ops/codec plan_cache_stats via observe_plan_cache) ------

VOLUME_EC_PLAN_CACHE_EVENTS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_plan_cache_events_total",
    "Cumulative LRU events across the repair/piggyback plan caches "
    "(hits, misses, evictions). SW_EC_PLAN_CACHE_SIZE bounds each "
    "cache.",
    labels=("event",))
VOLUME_EC_PLAN_CACHE_ENTRIES = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_plan_cache_entries",
    "Current entry count per plan cache (repair, piggyback, "
    "piggyback_repair, piggyback_decode).",
    labels=("cache",))


def observe_plan_cache(snap: Dict = None):
    """Mirror the codec plan-cache snapshot onto the volume registry
    (process-global monotonic events -> set_total, entry counts ->
    gauge). Called on scrape; pass a snapshot to override (tests)."""
    if snap is None:
        from ..ops.codec import plan_cache_stats
        snap = plan_cache_stats()
    for event, total in (snap.get("events") or {}).items():
        VOLUME_EC_PLAN_CACHE_EVENTS.set_total(total, event)
    for cache, n in (snap.get("entries") or {}).items():
        VOLUME_EC_PLAN_CACHE_ENTRIES.set(n, cache)


# -- streaming spread (ec/spread.py via observe_spread) ----------------------

VOLUME_EC_SPREAD_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_spread_total",
    "Streaming-encode spread events by kind (bytes, sends, stripes, "
    "retries, failovers).",
    labels=("kind",))
VOLUME_EC_SPREAD_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_spread_seconds_total",
    "Cumulative spread busy time (union of in-flight send intervals) "
    "across streaming encodes.")
VOLUME_EC_SPREAD_MBPS_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_spread_mbps",
    "Effective shard placement bandwidth of the last streaming encode "
    "(pushed bytes / busy seconds).")
VOLUME_EC_ENCODE_OVERLAP_FRAC_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_encode_overlap_frac",
    "Encode/spread overlap of the last streaming encode: "
    "(serialized_estimate - wall) / serialized_estimate, 0..1.")


def observe_spread(stats: Dict):
    """Export one streaming encode's spread stats (the dict filled by
    ec.encoder.write_ec_files_spread) onto the volume registry."""
    if not stats:
        return
    for kind, key in (("bytes", "spread_bytes"),
                      ("sends", "spread_sends"),
                      ("stripes", "spread_stripes"),
                      ("retries", "spread_retries"),
                      ("failovers", "spread_failovers")):
        n = stats.get(key)
        if n:
            VOLUME_EC_SPREAD_COUNTER.inc(kind, amount=n)
    busy = stats.get("spread_busy_s")
    if busy:
        VOLUME_EC_SPREAD_SECONDS.inc(amount=busy)
    if "spread_mbps" in stats:
        VOLUME_EC_SPREAD_MBPS_GAUGE.set(stats["spread_mbps"])
    if "overlap_frac" in stats:
        VOLUME_EC_ENCODE_OVERLAP_FRAC_GAUGE.set(stats["overlap_frac"])


# -- unified stripe transport (ec/transport.py via observe_transport) --------

VOLUME_EC_TRANSPORT_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_transport_total",
    "Shared stripe-transport events by role (pull, push) and kind "
    "(bytes, transfers, stripes, retries, failovers, hedges_fired, "
    "hedges_won, hedges_lost) — one family across gather, spread, "
    "repair and tier demotion.",
    labels=("role", "kind"))
VOLUME_EC_TRANSPORT_SECONDS = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_transport_seconds_total",
    "Cumulative transport busy time (union of in-flight transfer "
    "intervals) by role.",
    labels=("role",))
VOLUME_EC_TRANSPORT_WINDOW_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_transport_window_stripes",
    "Configured in-flight stripe window of the last transport run, "
    "by role.",
    labels=("role",))
VOLUME_EC_TRANSPORT_PEAK_BUFFER_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_transport_peak_buffer_bytes",
    "Peak in-flight buffered bytes of the last transport run, by role "
    "(window occupancy ceiling: must stay O(window * shards * slab)).",
    labels=("role",))


def observe_transport(role: str, stats, window: int = 0):
    """Export one transport run (a ``TransportStats`` from either side
    of ec/transport.py) onto the volume registry under the unified
    ``ec_transport_*`` family. ``role`` is "pull" or "push"."""
    if stats is None:
        return
    for kind, n in (("bytes", stats.bytes),
                    ("transfers", stats.fetches + stats.sends),
                    ("stripes", stats.stripes),
                    ("retries", stats.retries),
                    ("failovers", stats.failovers),
                    ("hedges_fired", stats.hedges_fired),
                    ("hedges_won", stats.hedges_won),
                    ("hedges_lost", stats.hedges_lost)):
        if n:
            VOLUME_EC_TRANSPORT_COUNTER.inc(role, kind, amount=n)
    busy = stats.busy_s()
    if busy:
        VOLUME_EC_TRANSPORT_SECONDS.inc(role, amount=busy)
    if window:
        VOLUME_EC_TRANSPORT_WINDOW_GAUGE.set(window, role)
    VOLUME_EC_TRANSPORT_PEAK_BUFFER_GAUGE.set(stats.peak_buffered, role)


# -- per-holder health scoreboard (stats/health.py) --------------------------

HOLDER_HEALTH_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_holder_health",
    "0..1 health score per shard holder as seen by this node's reader "
    "stack (1.0 = healthy / no data; latency, error and hedge-loss "
    "EWMAs folded in).",
    labels=("holder",))
HOLDER_LATENCY_EWMA_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_holder_latency_ewma_ms",
    "EWMA of per-fetch latency against each holder, by read kind "
    "(shard_read, repair_read, degraded_read).",
    labels=("holder", "kind"))
HOLDER_EVENT_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_holder_events_total",
    "Per-holder reader-stack events (reads, errors, hedges_lost, "
    "hedges_won_against).",
    labels=("holder", "event"))


def observe_health(snapshot: Dict):
    """Mirror one HolderHealthBoard snapshot (stats/health.py) onto the
    volume registry; called on every /metrics scrape so the master-side
    aggregator sees fresh per-holder scores."""
    if not snapshot:
        return
    for holder, h in snapshot.items():
        HOLDER_HEALTH_GAUGE.set(h["score"], holder)
        for kind, ewma_ms in h.get("latency_ewma_ms", {}).items():
            HOLDER_LATENCY_EWMA_GAUGE.set(ewma_ms, holder, kind)
        for event, n in h.get("events", {}).items():
            HOLDER_EVENT_COUNTER.set_total(n, holder, event)


# -- degraded reads (ec/degraded.py via observe_degraded) --------------------

VOLUME_EC_DEGRADED_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_degraded_total",
    "Degraded-read engine events by kind (reads, batches, "
    "batched_requests, cache_hits, cache_misses, survivor_bytes, "
    "remote_bytes, host_dispatches, device_dispatches, errors).",
    labels=("kind",))
DEGRADED_READ_HISTOGRAM = VOLUME_SERVER_GATHER.histogram(
    "SeaweedFS_volumeServer_ec_degraded_read_seconds",
    "Bucketed latency of reconstruct-on-read requests (the degraded "
    "p99 lives here).")
VOLUME_EC_DEGRADED_BATCH_WIDTH_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_degraded_batch_width",
    "Concurrent reconstruct requests coalesced into the most recent "
    "fused degraded-read dispatch.")
VOLUME_EC_DEGRADED_HIT_RATIO_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_degraded_cache_hit_ratio",
    "Reconstructed-slab LRU hit ratio since process start, 0..1.")
VOLUME_EC_DEGRADED_READAHEAD_RATIO_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_degraded_readahead_hit_ratio",
    "Fraction of readahead-reconstructed slabs later served from the "
    "LRU, 0..1 (SW_EC_DEGRADED_READAHEAD_SLABS).")


def observe_degraded(snap: Dict):
    """Mirror one DegradedReadEngine snapshot onto the volume registry
    (engine counters are process-monotonic, so set_total like the
    telemetry/pool-churn mirrors)."""
    if not snap:
        return
    for kind in ("reads", "batches", "batched_requests", "cache_hits",
                 "cache_misses", "survivor_bytes", "remote_bytes",
                 "host_dispatches", "device_dispatches", "errors",
                 "readahead_slabs", "readahead_hits"):
        VOLUME_EC_DEGRADED_COUNTER.set_total(snap.get(kind, 0), kind)
    VOLUME_EC_DEGRADED_BATCH_WIDTH_GAUGE.set(
        snap.get("last_batch_requests", 0))
    VOLUME_EC_DEGRADED_HIT_RATIO_GAUGE.set(
        snap.get("cache_hit_ratio", 0.0))
    VOLUME_EC_DEGRADED_READAHEAD_RATIO_GAUGE.set(
        snap.get("readahead_hit_ratio", 0.0))


# -- EC integrity scrub (ec/scrub.py via observe_scrub) ----------------------

VOLUME_EC_SCRUB_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_ec_scrub_total",
    "Syndrome-scrub engine events by kind (passes, volumes_scrubbed, "
    "slabs, bytes_verified, corrupt_slabs, corrupt_columns, findings, "
    "host_dispatches, device_dispatches, errors).",
    labels=("kind",))
VOLUME_EC_SCRUB_MBPS_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_scrub_mbps",
    "Gather bandwidth of the most recent scrub pass, MB/s (paced by "
    "SW_EC_SCRUB_RATE_MBPS).")
VOLUME_EC_SCRUB_LAST_PASS_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_ec_scrub_last_pass_unixtime",
    "Wall-clock time the last scrub pass finished; staleness alarm "
    "feed.")


def observe_scrub(snap: Dict):
    """Mirror one ScrubEngine snapshot onto the volume registry."""
    if not snap:
        return
    for kind in ("passes", "volumes_scrubbed", "slabs", "bytes_verified",
                 "remote_bytes", "corrupt_slabs", "corrupt_columns",
                 "findings", "report_failures", "skipped_missing",
                 "skipped_not_owner", "host_dispatches",
                 "device_dispatches", "errors"):
        VOLUME_EC_SCRUB_COUNTER.set_total(snap.get(kind, 0), kind)
    VOLUME_EC_SCRUB_MBPS_GAUGE.set(snap.get("last_pass_mbps", 0.0))
    VOLUME_EC_SCRUB_LAST_PASS_GAUGE.set(snap.get("last_pass_at", 0.0))


# -- native read plane telemetry (server/native_plane.py via observe_plane) --

# Mirror of kLatBoundsUs in server/native/http_plane.cc, in seconds.
# test_observability pins this against swhp_lat_bounds so the two can
# never drift silently.
PLANE_LAT_BUCKETS_S = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                       0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0)

PLANE_REQUEST_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_request_total",
    "Native-plane requests by status class (1xx..5xx).",
    labels=("class",))
PLANE_BYTES_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_bytes_total",
    "Bytes written to sockets by the native plane (headers + bodies).")
PLANE_EVENT_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_events_total",
    "Native-plane off-fast-path events by kind (redirects to the "
    "Python server, index misses).",
    labels=("kind",))
PLANE_REQUEST_HISTOGRAM = VOLUME_SERVER_GATHER.histogram(
    "SeaweedFS_volumeServer_plane_request_seconds",
    "Bucketed latency of native-plane requests, measured request-parse "
    "to response-written inside the C++ plane.",
    buckets=PLANE_LAT_BUCKETS_S)
PLANE_SLOW_RING_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_slow_ring_depth",
    "Entries currently held in the native slow-request ring "
    "(GET /admin/plane/slow; threshold SW_PLANE_SLOW_US).")
PLANE_BUILD_FAILED_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_build_failed",
    "1 if the one-time g++ build of the native plane failed and reads "
    "fell back to the Python path (stderr logged at warning).")


def observe_plane(snap: Optional[Dict], slow_depth: int = 0,
                  build_failed: bool = False):
    """Mirror one native-plane stats snapshot (NativeReadPlane.stats())
    onto the volume registry; plane counters are process-monotonic so
    set_total, and the native bucket counts snapshot-replace the
    histogram via set_buckets."""
    PLANE_BUILD_FAILED_GAUGE.set(1 if build_failed else 0)
    if not snap:
        return
    for cls in ("1xx", "2xx", "3xx", "4xx", "5xx"):
        PLANE_REQUEST_COUNTER.set_total(
            snap.get(f"status_{cls}", 0), cls)
    PLANE_BYTES_COUNTER.set_total(snap.get("bytes_sent", 0))
    PLANE_EVENT_COUNTER.set_total(snap.get("redirects", 0), "redirect")
    PLANE_EVENT_COUNTER.set_total(
        snap.get("index_misses", 0), "index_miss")
    buckets = snap.get("buckets") or ()
    PLANE_REQUEST_HISTOGRAM.set_buckets(
        [c for _bound, c in buckets[:len(PLANE_LAT_BUCKETS_S)]],
        snap.get("lat_count", 0),
        snap.get("lat_sum_us", 0) / 1e6)
    PLANE_SLOW_RING_GAUGE.set(slow_depth)


# -- in-plane degraded serving + reconstructed-slab cache --------------------

PLANE_DEGRADED_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_degraded_total",
    "Native-plane EC read outcomes by result: served (lost-shard bytes "
    "filled from the slab cache, zero redirects), redirected (slabs "
    "absent or stale — Python reconstructs), local (all shards local).",
    labels=("result",))
PLANE_CACHE_EVENT_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_cache_events_total",
    "Reconstructed-slab cache flow by event (puts, hits, misses, "
    "evictions, invalidated).",
    labels=("event",))
PLANE_CACHE_BYTES_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_cache_put_bytes_total",
    "Slab bytes published into the native plane's cache.")
PLANE_CACHE_ENTRIES_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_cache_entries",
    "Slabs currently resident in the native plane's cache.")
PLANE_CACHE_BYTES_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_cache_bytes",
    "Bytes currently resident in the native plane's cache (bounded by "
    "SW_PLANE_CACHE_BYTES).")
PLANE_CACHE_MAX_BYTES_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_cache_max_bytes",
    "Configured byte budget of the native plane's slab cache "
    "(SW_PLANE_CACHE_BYTES; 0 = in-plane degraded path disabled).")


def observe_plane_cache(snap: Optional[Dict]):
    """Mirror one NativeReadPlane.cache_stats() snapshot onto the
    volume registry (same set_total mirror pattern as observe_plane)."""
    if not snap:
        return
    PLANE_DEGRADED_COUNTER.set_total(
        snap.get("degraded_served", 0), "served")
    PLANE_DEGRADED_COUNTER.set_total(
        snap.get("degraded_redirected", 0), "redirected")
    PLANE_DEGRADED_COUNTER.set_total(
        snap.get("ec_local_served", 0), "local")
    for event in ("puts", "hits", "misses", "evictions", "invalidated"):
        PLANE_CACHE_EVENT_COUNTER.set_total(snap.get(event, 0), event)
    PLANE_CACHE_BYTES_COUNTER.set_total(snap.get("put_bytes", 0))
    PLANE_CACHE_ENTRIES_GAUGE.set(snap.get("entries", 0))
    PLANE_CACHE_BYTES_GAUGE.set(snap.get("bytes", 0))
    PLANE_CACHE_MAX_BYTES_GAUGE.set(snap.get("max_bytes", 0))


# -- group-commit write durability (native_plane.sync_stats) -----------------

PLANE_FSYNC_BATCH_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_fsync_batches_total",
    "Group commits issued by the native plane: one fdatasync pair "
    "(.dat + .idx) covering every rider in the batch; 'always' mode "
    "counts each per-append fsync as a batch of one.")
PLANE_FSYNC_RIDER_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_fsync_riders_total",
    "Appends whose ack was covered by a group commit; riders/batches "
    "is the fsync amortization ratio (1.0 = no batching win).")
PLANE_FSYNC_FAILURE_COUNTER = VOLUME_SERVER_GATHER.counter(
    "SeaweedFS_volumeServer_plane_fsync_failures_total",
    "fdatasync errors: the batch poisoned (-5 to every waiting append, "
    "nothing acked) and the writer fail-stopped — Python demoted the "
    "volume to its own append path.")
PLANE_FSYNC_HISTOGRAM = VOLUME_SERVER_GATHER.histogram(
    "SeaweedFS_volumeServer_plane_fsync_seconds",
    "Bucketed duration of the committer's covering fdatasync pair "
    "(populated only while SW_PLANE_STATS is on — stats off keeps the "
    "committer clock-free).",
    buckets=PLANE_LAT_BUCKETS_S)
PLANE_FSYNC_PENDING_GAUGE = VOLUME_SERVER_GATHER.gauge(
    "SeaweedFS_volumeServer_plane_fsync_pending",
    "Appends currently parked awaiting their covering group commit "
    "(bounded by SW_PLANE_FSYNC_MAX_PENDING per batch).")


def observe_plane_sync(snap: Optional[Dict]):
    """Mirror one NativeReadPlane.sync_stats() snapshot onto the volume
    registry (same set_total mirror pattern as observe_plane)."""
    if not snap:
        return
    PLANE_FSYNC_BATCH_COUNTER.set_total(snap.get("batches", 0))
    PLANE_FSYNC_RIDER_COUNTER.set_total(snap.get("riders", 0))
    PLANE_FSYNC_FAILURE_COUNTER.set_total(snap.get("failures", 0))
    buckets = snap.get("buckets") or ()
    PLANE_FSYNC_HISTOGRAM.set_buckets(
        [c for _bound, c in buckets[:len(PLANE_LAT_BUCKETS_S)]],
        sum(c for _bound, c in buckets),
        snap.get("fsync_us_sum", 0) / 1e6)
    PLANE_FSYNC_PENDING_GAUGE.set(snap.get("pending", 0))


# -- repair queue (stats/repair_queue.py via observe_repair_queue) -----------

MASTER_REPAIR_QUEUE_COUNTER = MASTER_GATHER.counter(
    "SeaweedFS_master_repair_queue_incidents_total",
    "Repair-queue incident flow by kind and event (reported, resolved, "
    "attempts, attempt_failures, duplicates).",
    labels=("kind", "event"))
MASTER_REPAIR_QUEUE_OPEN_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_repair_queue_open",
    "Open incidents by kind (corruption, lost_shard, at_risk_holder).",
    labels=("kind",))
MASTER_REPAIR_QUEUE_TTR_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_repair_queue_ttr_seconds",
    "Time-to-re-protection over recent resolved incidents (quantile "
    "label: p50, p99, max).",
    labels=("quantile",))
MASTER_REPAIR_QUEUE_UNATTRIBUTED_GAUGE = MASTER_GATHER.gauge(
    "SeaweedFS_master_repair_queue_unattributed",
    "Open scrub findings with no attributable shard (shard=-1): "
    "visible at /cluster/repairs, excluded from the drain loop until "
    "an operator or a later scrub attributes them.")


def observe_repair_queue(snap: Dict):
    """Mirror one RepairQueue snapshot onto the master registry."""
    if not snap:
        return
    counters = snap.get("counters", {})
    for event in ("reported", "resolved", "attempts",
                  "attempt_failures", "duplicates"):
        MASTER_REPAIR_QUEUE_COUNTER.set_total(
            counters.get(event, 0), "all", event)
    for kind, depth in snap.get("depth", {}).items():
        MASTER_REPAIR_QUEUE_OPEN_GAUGE.set(depth, kind)
    MASTER_REPAIR_QUEUE_UNATTRIBUTED_GAUGE.set(
        snap.get("unattributed", 0))
    ttr = snap.get("time_to_re_protection", {})
    MASTER_REPAIR_QUEUE_TTR_GAUGE.set(ttr.get("p50_s", 0.0), "p50")
    MASTER_REPAIR_QUEUE_TTR_GAUGE.set(ttr.get("p99_s", 0.0), "p99")
    MASTER_REPAIR_QUEUE_TTR_GAUGE.set(ttr.get("max_s", 0.0), "max")


class SmallDispatchTuner:
    """Fits the host/device crossover from the first-N reconstruct
    spans: device dispatch time is modeled as a + b*bytes (fixed
    dispatch+transfer latency plus per-byte cost), the host path as a
    flat rate, and the suggested threshold is the width where the
    device line dips below the host line.  Published as a gauge so the
    open SW_EC_SMALL_DISPATCH_BYTES auto-tuning item has its signal."""

    MIN_SAMPLES = 4          # per path, before suggesting anything
    MAX_SAMPLES = 64         # "first few calls" — stop learning after
    CLAMP = (64 << 10, 8 << 20)

    def __init__(self):
        self._lock = make_lock("metrics.SmallDispatchTuner._lock")
        self._host: List[Tuple[float, float]] = []    # (bytes, seconds)
        self._device: List[Tuple[float, float]] = []

    def add(self, path: str, nbytes: float, seconds: float):
        if nbytes <= 0 or seconds <= 0:
            return None
        with self._lock:
            samples = self._host if path == "host" else self._device
            if len(samples) >= self.MAX_SAMPLES:
                return None
            samples.append((float(nbytes), float(seconds)))
        return self.suggest()

    def suggest(self) -> Optional[int]:
        with self._lock:
            host = list(self._host)
            device = list(self._device)
        if len(host) < self.MIN_SAMPLES or len(device) < self.MIN_SAMPLES:
            return None
        host_rate = sum(b for b, _ in host) / sum(s for _, s in host)
        # least-squares fit t = a + b*x over the device samples
        n = len(device)
        mx = sum(b for b, _ in device) / n
        my = sum(s for _, s in device) / n
        sxx = sum((b - mx) ** 2 for b, _ in device)
        if sxx <= 0:            # all widths identical — can't fit slope
            return None
        b_fit = sum((x - mx) * (y - my) for x, y in device) / sxx
        a_fit = my - b_fit * mx
        denom = 1.0 / host_rate - b_fit
        if a_fit <= 0 or denom <= 0:
            # device never wins (or fit degenerate) in the sampled range
            return self.CLAMP[1]
        cross = a_fit / denom
        return int(min(max(cross, self.CLAMP[0]), self.CLAMP[1]))


SMALL_DISPATCH_TUNER = SmallDispatchTuner()


def observe_span(span_dict: Dict):
    """Export hook called by util/tracing for every finished span."""
    name = span_dict.get("name")
    dur = span_dict.get("duration_s")
    if dur is None:
        return
    if name in EC_PHASE_NAMES:
        VOLUME_EC_PHASE_HISTOGRAM.observe(dur, name)
        VOLUME_EC_PHASE_COUNTER.inc(name, amount=dur)
    elif name == "reconstruct":
        tags = span_dict.get("tags") or {}
        path = tags.get("path")
        nbytes = tags.get("bytes")
        if path in ("host", "device") and nbytes:
            suggestion = SMALL_DISPATCH_TUNER.add(path, nbytes, dur)
            if suggestion:
                SMALL_DISPATCH_SUGGESTED_GAUGE.set(suggestion)
                # opt-in auto-apply: feed the fitted crossover back
                # into the live hybrid threshold instead of only
                # publishing it
                from ..ops.codec import maybe_auto_apply_small_dispatch
                maybe_auto_apply_small_dispatch(suggestion)


def start_push_loop(registry: Registry, gateway_url: str,
                    job: str, interval_s: float = 15.0,
                    stop_event: Optional[threading.Event] = None
                    ) -> threading.Thread:
    """Push-gateway parity (reference LoopPushingMetric,
    metrics.go:109-137): POST the text exposition on an interval."""
    from ..server.http_util import HttpError, http_call
    stop = stop_event or threading.Event()

    def loop():
        while not stop.wait(interval_s):
            try:
                # external endpoint: exempt from the cluster TLS URL
                # rewrite (a plain-HTTP pushgateway must stay reachable
                # when the cluster itself runs TLS)
                http_call(
                    "POST",
                    f"{gateway_url.rstrip('/')}/metrics/job/{job}",
                    registry.render().encode(),
                    {"Content-Type": "text/plain"}, external=True)
            except Exception:  # noqa: BLE001 - a flaky gateway (bad
                # status line, reset, DNS) must never kill the loop:
                # nothing would ever restart it
                pass

    t = threading.Thread(target=loop, daemon=True, name="metrics-push")
    t.stop_event = stop
    t.start()
    return t
