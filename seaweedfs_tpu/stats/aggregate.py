"""Cluster metrics aggregation (fleet health plane, half one).

The master already knows every live node from heartbeats; this module
closes the loop by scraping each node's `/metrics` on an interval
(`SW_CLUSTER_SCRAPE_S`, default 15 s), parsing the Prometheus text back
into samples (stats.metrics.parse_prometheus_text — round-trip tested
against the renderer), and serving one merged exposition at
`GET /cluster/metrics`:

  * counters and histogram series are summed per label-set (histogram
    buckets carry their `le` label, so bucket-wise merging falls out of
    the same rule);
  * gauges (and untyped families) are kept per-node under an added
    `node=` label — a per-node bandwidth gauge averaged across the
    fleet would be meaningless;
  * nodes whose scrapes stop succeeding are marked stale (a synthetic
    `cluster_node_up` gauge leads the merged view) and aged out of the
    merge entirely after `age_out_s`.

`GET /cluster/health` is served from the same snapshots: the
`ec_holder_*` families each node exports are folded into one per-holder
view (worst observer score wins — a holder slow for anyone is slow).
"""

from __future__ import annotations

import os
import threading
from ..util import config
from ..util.locks import make_lock
import time
from typing import Callable, Dict, List, Optional, Sequence

from .metrics import (CLUSTER_NODE_UP_GAUGE, CLUSTER_NODES_GAUGE,
                      CLUSTER_SCRAPE_COUNTER, CLUSTER_SCRAPE_SECONDS,
                      parse_prometheus_text, render_families)

DEFAULT_SCRAPE_S = 15.0

_HEALTH_SUFFIX = "_ec_holder_health"
_HEALTH_LAT_SUFFIX = "_ec_holder_latency_ewma_ms"
_HEALTH_EVENTS_SUFFIX = "_ec_holder_events_total"


def scrape_interval_s() -> float:
    return config.env_float("SW_CLUSTER_SCRAPE_S")


class _NodeSnapshot:
    __slots__ = ("url", "families", "last_success", "last_attempt",
                 "last_error")

    def __init__(self, url: str):
        self.url = url
        self.families: List[Dict] = []
        self.last_success = 0.0
        self.last_attempt = 0.0
        self.last_error = ""


class ClusterMetricsAggregator:
    """Master-side scraper + merger over the heartbeating node set."""

    def __init__(self, list_nodes: Callable[[], Sequence[str]],
                 interval_s: Optional[float] = None,
                 fetch: Optional[Callable[[str], str]] = None):
        self.list_nodes = list_nodes
        self.interval_s = (scrape_interval_s() if interval_s is None
                           else float(interval_s))
        # one missed sweep is jitter; two means the node is gone
        self.stale_after_s = max(2.5 * self.interval_s, 1.0)
        self.age_out_s = 4 * self.stale_after_s
        self._fetch = fetch or self._http_fetch
        self._lock = make_lock("aggregate._lock")
        self._nodes: Dict[str, _NodeSnapshot] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _http_fetch(url: str) -> str:
        from ..server.http_util import http_call
        return http_call("GET", f"http://{url}/metrics",
                         timeout=10.0).decode("utf-8", "replace")

    # -- scrape loop ---------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cluster-metrics-scraper")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - a scrape sweep must
                # never kill the loop; per-node errors are already
                # caught, this guards list_nodes itself
                pass

    def scrape_once(self) -> int:
        """One synchronous sweep over the current node set; returns how
        many nodes scraped clean.  Also the test/`?refresh=1` path."""
        t0 = time.monotonic()
        ok = 0
        for url in list(self.list_nodes()):
            snap = self._snap(url)
            snap.last_attempt = t0
            try:
                text = self._fetch(url)
                families = parse_prometheus_text(text)
            except Exception as e:  # noqa: BLE001 - any transport or
                # parse failure marks the node, never aborts the sweep
                snap.last_error = f"{type(e).__name__}: {e}"
                CLUSTER_SCRAPE_COUNTER.inc("error")
                continue
            with self._lock:
                snap.families = families
                snap.last_success = time.monotonic()
                snap.last_error = ""
            CLUSTER_SCRAPE_COUNTER.inc("ok")
            ok += 1
        self._age_out()
        self._export_node_gauges()
        CLUSTER_SCRAPE_SECONDS.observe(time.monotonic() - t0)
        return ok

    def _snap(self, url: str) -> _NodeSnapshot:
        with self._lock:
            snap = self._nodes.get(url)
            if snap is None:
                snap = self._nodes[url] = _NodeSnapshot(url)
            return snap

    def _age_out(self):
        now = time.monotonic()
        with self._lock:
            dead = [u for u, s in self._nodes.items()
                    if now - (s.last_success or s.last_attempt)
                    > self.age_out_s]
            for u in dead:
                del self._nodes[u]

    def _is_stale(self, snap: _NodeSnapshot) -> bool:
        if not snap.last_success:
            return True
        return time.monotonic() - snap.last_success > self.stale_after_s

    def _export_node_gauges(self):
        with self._lock:
            snaps = list(self._nodes.values())
        fresh = stale = 0
        for s in snaps:
            is_stale = self._is_stale(s)
            CLUSTER_NODE_UP_GAUGE.set(0.0 if is_stale else 1.0, s.url)
            if is_stale:
                stale += 1
            else:
                fresh += 1
        CLUSTER_NODES_GAUGE.set(fresh, "fresh")
        CLUSTER_NODES_GAUGE.set(stale, "stale")

    # -- merged views --------------------------------------------------------

    def node_status(self) -> List[Dict]:
        with self._lock:
            snaps = sorted(self._nodes.values(), key=lambda s: s.url)
        return [{"node": s.url, "stale": self._is_stale(s),
                 "last_error": s.last_error} for s in snaps]

    def merged_families(self) -> List[Dict]:
        """Merge every non-aged-out node's parsed families."""
        with self._lock:
            per_node = [(s.url, s.families, self._is_stale(s))
                        for s in sorted(self._nodes.values(),
                                        key=lambda s: s.url)]
        up = {"name": "cluster_node_up", "kind": "gauge",
              "help": "1 if the node's last scrape is fresh, 0 if "
                      "stale (aged-out nodes are dropped).",
              "samples": [("cluster_node_up", (("node", url),),
                           0.0 if stale else 1.0)
                          for url, _, stale in per_node]}
        merged: List[Dict] = [up]
        by_name: Dict[str, Dict] = {}
        # summed series accumulate here: family name -> (sample_name,
        # labels) -> value
        sums: Dict[str, Dict[tuple, float]] = {}
        for url, families, _stale in per_node:
            for fam in families:
                out = by_name.get(fam["name"])
                if out is None:
                    out = {"name": fam["name"], "kind": fam["kind"],
                           "help": fam["help"], "samples": []}
                    by_name[fam["name"]] = out
                    merged.append(out)
                if fam["kind"] in ("counter", "histogram"):
                    acc = sums.setdefault(fam["name"], {})
                    for sample_name, labels, value in fam["samples"]:
                        key = (sample_name, labels)
                        acc[key] = acc.get(key, 0.0) + value
                    # newest exemplar per merged series wins — a fresh
                    # trace id beats a stale one from another node
                    for key, ex in (fam.get("exemplars") or {}).items():
                        held = out.setdefault("exemplars", {}).get(key)
                        if held is None or ex[2] >= held[2]:
                            out["exemplars"][key] = ex
                else:   # gauge / untyped: keep per-node
                    for sample_name, labels, value in fam["samples"]:
                        out["samples"].append(
                            (sample_name, labels + (("node", url),),
                             value))
        for name, acc in sums.items():
            by_name[name]["samples"] = [
                (sample_name, labels, value)
                for (sample_name, labels), value in acc.items()]
        return merged

    def render(self) -> str:
        return render_families(self.merged_families())

    def holder_health(self) -> Dict:
        """Fold each node's `ec_holder_*` families into one per-holder
        cluster view.  Worst observer score wins; latency EWMAs take the
        worst observer per kind; event counters sum."""
        with self._lock:
            per_node = [(s.url, s.families)
                        for s in sorted(self._nodes.values(),
                                        key=lambda s: s.url)
                        if not self._is_stale(s)]
        holders: Dict[str, Dict] = {}

        def ensure(holder: str) -> Dict:
            return holders.setdefault(holder, {
                "score": 1.0, "observers": {},
                "latency_ewma_ms": {}, "events": {}})

        for url, families in per_node:
            for fam in families:
                name = fam["name"]
                if name.endswith(_HEALTH_SUFFIX):
                    for _sn, labels, value in fam["samples"]:
                        ld = dict(labels)
                        h = ensure(ld.get("holder", "?"))
                        h["observers"][url] = value
                        h["score"] = min(h["score"], value)
                elif name.endswith(_HEALTH_LAT_SUFFIX):
                    for _sn, labels, value in fam["samples"]:
                        ld = dict(labels)
                        h = ensure(ld.get("holder", "?"))
                        kind = ld.get("kind", "?")
                        h["latency_ewma_ms"][kind] = max(
                            h["latency_ewma_ms"].get(kind, 0.0), value)
                elif name.endswith(_HEALTH_EVENTS_SUFFIX):
                    for _sn, labels, value in fam["samples"]:
                        ld = dict(labels)
                        h = ensure(ld.get("holder", "?"))
                        ev = ld.get("event", "?")
                        h["events"][ev] = h["events"].get(ev, 0) + value
        return {"holders": holders, "nodes": self.node_status()}
