"""Per-holder health scoreboard (fleet health plane, half two).

The streaming reader stack (ec/gather.py, ec/degraded.py) already
measures every range read it issues: per-fetch latency, failures, and
— since hedge losers are now attributed instead of silently drained —
which holder lost each hedge race.  This module folds those signals
into a 0..1 health score per holder:

    lat_score   = ref_ms / (ref_ms + latency_ewma_ms)     # 1.0 at 0ms,
                                                          # 0.5 at ref
    score       = lat_score * (1 - err_ewma)
                            * (1 - 0.5 * hedge_loss_ewma)

clipped to [0, 1]; a holder with no data scores 1.0 (healthy until
proven otherwise, so fresh clusters don't demote everyone).  Scores are
exported as the `ec_holder_health` gauge family on every /metrics
scrape (stats.metrics.observe_health), aggregated by the master at
/cluster/health, and — behind SW_EC_HEALTH_ROUTING=1 — consulted by the
gather rotation to demote unhealthy holders to the back of the
failover/hedge order.
"""

from __future__ import annotations

import os
import threading
from ..util import config
from ..util.locks import make_lock
from typing import Dict, List, Optional, Sequence

# EWMA smoothing: each observation moves the average 20% of the way to
# the new value, so ~10 observations forget an old regime.
_ALPHA = 0.2

# Latency yielding a 0.5 lat_score; overridable for tests/deployments
# with a different healthy-fetch baseline.
_DEF_REF_MS = 50.0


def _ref_ms() -> float:
    return config.env_float("SW_EC_HEALTH_REF_MS")


def routing_enabled() -> bool:
    return config.env_bool("SW_EC_HEALTH_ROUTING")


class HolderHealthBoard:
    """Thread-safe EWMA scoreboard keyed by holder URL."""

    def __init__(self):
        self._lock = make_lock("health._lock")
        # holder -> kind -> latency EWMA (seconds)
        self._lat: Dict[str, Dict[str, float]] = {}
        # holder -> error-rate EWMA (0..1)
        self._err: Dict[str, float] = {}
        # holder -> hedge-loss-rate EWMA (0..1)
        self._hedge: Dict[str, float] = {}
        # holder -> event -> monotonic count
        self._events: Dict[str, Dict[str, int]] = {}

    # -- feeds (called from the reader stack) --------------------------------

    def _bump(self, holder: str, event: str, n: int = 1):
        ev = self._events.setdefault(holder, {})
        ev[event] = ev.get(event, 0) + n

    def record_latency(self, holder: str, kind: str, seconds: float):
        """One successful range read against `holder` took `seconds`."""
        if not holder or seconds < 0:
            return
        with self._lock:
            kinds = self._lat.setdefault(holder, {})
            prev = kinds.get(kind)
            kinds[kind] = (seconds if prev is None
                           else prev + _ALPHA * (seconds - prev))
            self._err[holder] = (1 - _ALPHA) * self._err.get(holder, 0.0)
            self._hedge[holder] = \
                (1 - _ALPHA) * self._hedge.get(holder, 0.0)
            self._bump(holder, "reads")

    def record_error(self, holder: str, kind: str = "shard_read"):
        """A range read against `holder` failed or timed out."""
        if not holder:
            return
        with self._lock:
            prev = self._err.get(holder, 0.0)
            self._err[holder] = prev + _ALPHA * (1.0 - prev)
            self._bump(holder, "errors")

    def record_hedge_loss(self, loser: str, winner: str,
                          loser_latency_s: Optional[float] = None):
        """A hedged read raced `loser` against `winner` and the loser's
        response arrived second (or never).  The loser's full latency —
        measured when the drained duplicate finally completes — feeds
        its latency EWMA too, so chronic hedge losers look slow even if
        every fetch eventually succeeds."""
        if not loser:
            return
        with self._lock:
            prev = self._hedge.get(loser, 0.0)
            self._hedge[loser] = prev + _ALPHA * (1.0 - prev)
            self._bump(loser, "hedges_lost")
            if winner:
                self._bump(winner, "hedges_won_against")
            if loser_latency_s is not None and loser_latency_s >= 0:
                kinds = self._lat.setdefault(loser, {})
                prev_lat = kinds.get("shard_read")
                kinds["shard_read"] = (
                    loser_latency_s if prev_lat is None
                    else prev_lat + _ALPHA * (loser_latency_s - prev_lat))

    # -- reads ---------------------------------------------------------------

    def score(self, holder: str) -> float:
        with self._lock:
            return self._score_locked(holder)

    def _score_locked(self, holder: str) -> float:
        kinds = self._lat.get(holder)
        err = self._err.get(holder, 0.0)
        hedge = self._hedge.get(holder, 0.0)
        if not kinds and not err and not hedge:
            return 1.0
        ref = _ref_ms()
        worst_ms = max(kinds.values()) * 1000.0 if kinds else 0.0
        lat_score = ref / (ref + worst_ms) if worst_ms > 0 else 1.0
        score = lat_score * (1.0 - err) * (1.0 - 0.5 * hedge)
        return min(1.0, max(0.0, score))

    def snapshot(self) -> Dict[str, Dict]:
        """Per-holder view for /metrics export and shell rendering."""
        with self._lock:
            holders = (set(self._lat) | set(self._err) | set(self._hedge)
                       | set(self._events))
            out = {}
            for h in sorted(holders):
                out[h] = {
                    "score": round(self._score_locked(h), 4),
                    "latency_ewma_ms": {
                        kind: round(s * 1000.0, 3)
                        for kind, s in self._lat.get(h, {}).items()},
                    "error_ewma": round(self._err.get(h, 0.0), 4),
                    "hedge_loss_ewma": round(self._hedge.get(h, 0.0), 4),
                    "events": dict(self._events.get(h, {})),
                }
            return out

    def order_by_health(self, holders: Sequence[str],
                        threshold: float = 0.5) -> List[str]:
        """Stable-partition `holders` into healthy-first order: holders
        scoring below `threshold` keep their relative order but move to
        the back of the failover/hedge rotation."""
        with self._lock:
            scores = {h: self._score_locked(h) for h in holders}
        healthy = [h for h in holders if scores[h] >= threshold]
        unhealthy = [h for h in holders if scores[h] < threshold]
        return healthy + unhealthy

    def reset(self):
        with self._lock:
            self._lat.clear()
            self._err.clear()
            self._hedge.clear()
            self._events.clear()


# Process-global board: every reader in this process (rebuild gather,
# trace repair, degraded engine) feeds the same scoreboard, mirroring
# the module-global metric registries.
BOARD = HolderHealthBoard()


def export_board():
    """Push the current board onto the ec_holder_* metric families;
    called from /metrics handlers so scrapes always see fresh scores."""
    from .metrics import observe_health
    observe_health(BOARD.snapshot())
