"""Metrics (reference weed/stats/metrics.go) — Prometheus-compatible
counters/gauges/histograms with a text exposition endpoint."""

from .metrics import (Counter, Gauge, Histogram, Registry,  # noqa: F401
                      VOLUME_SERVER_GATHER, FILER_GATHER, MASTER_GATHER,
                      start_push_loop)
