"""Telemetry-prioritized repair queue with time-to-re-protection accounting.

Master-side. Three incident sources feed it: scrub syndrome findings
(``POST /cluster/scrub_report``), missing shards observed in the
heartbeat-built topology, and at-risk holders flagged by the fleet
health plane (PR 8's ``HolderHealthBoard`` scores).  Priority is fixed
by what the incident says about durability, not arrival order:

    corruption (0) > lost_shard (1) > at_risk_holder (2)

A corrupt shard is *silently* wrong — reads that touch it decode
garbage until it is rebuilt — while a lost shard merely spends margin,
and an at-risk holder is advisory (it prioritizes nothing by itself,
but earlier scans of its volumes).  The drain loop on the master pops
``next_incident()`` and drives the existing rebuild paths
(``/admin/ec/scrub_repair`` for corruption, ``/admin/ec/rebuild`` +
mount for loss).

**Time-to-re-protection** for an incident is ``resolved_at -
detected_at``: the window during which the affected volume ran below
its configured redundancy (or above it but silently wrong).  It is the
integrity plane's headline SLO — p50/p99 over recent incidents are
exported as ``repair_queue_ttr_seconds`` and reported by the
``bench.py cluster_scrub_repair`` drill.
"""

import threading
from ..util.locks import make_lock
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

PRIORITIES = {"corruption": 0, "lost_shard": 1, "at_risk_holder": 2}

# Failed repair attempts back off linearly so one unreachable holder
# cannot spin the drain loop.
RETRY_BACKOFF_S = 30.0

_RESOLVED_KEEP = 256


class Incident:
    __slots__ = ("id", "kind", "volume", "shard", "holder", "source",
                 "detail", "detected_at", "resolved_at", "attempts",
                 "not_before", "status", "via", "last_error")

    def __init__(self, iid: int, kind: str, volume: Optional[int],
                 shard: Optional[int], holder: str, source: str,
                 detail: dict, detected_at: float):
        self.id = iid
        self.kind = kind
        self.volume = volume
        self.shard = shard
        self.holder = holder
        self.source = source
        self.detail = detail
        self.detected_at = detected_at
        self.resolved_at = 0.0
        self.attempts = 0
        self.not_before = 0.0
        self.status = "open"
        self.via = ""
        self.last_error = ""

    def key(self) -> tuple:
        return (self.kind, self.volume, self.shard, self.holder)

    def to_dict(self) -> dict:
        out = {"id": self.id, "kind": self.kind,
               "priority": PRIORITIES.get(self.kind, 9),
               "volume": self.volume, "shard": self.shard,
               "holder": self.holder, "source": self.source,
               "detail": self.detail, "detected_at": self.detected_at,
               "attempts": self.attempts, "status": self.status}
        if self.status == "resolved":
            out["resolved_at"] = self.resolved_at
            out["via"] = self.via
            out["time_to_re_protection_s"] = \
                round(self.resolved_at - self.detected_at, 6)
        if self.last_error:
            out["last_error"] = self.last_error
        return out


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class RepairQueue:
    """Deduplicated priority queue of durability incidents."""

    def __init__(self):
        self._lock = make_lock("repair_queue._lock")
        self._open: Dict[tuple, Incident] = {}
        self._resolved: deque = deque(maxlen=_RESOLVED_KEEP)
        self._next_id = 1
        self._c = {"reported": 0, "duplicates": 0, "resolved": 0,
                   "attempts": 0, "attempt_failures": 0}

    # -- intake ------------------------------------------------------

    def report(self, kind: str, volume: Optional[int] = None,
               shard: Optional[int] = None, holder: str = "",
               source: str = "", detail: Optional[dict] = None,
               detected_at: Optional[float] = None) -> Incident:
        """Open (or refresh) an incident.  Repeat reports of the same
        (kind, volume, shard, holder) collapse onto the open incident —
        detection time stays at FIRST sighting, so time-to-re-protection
        measures the full exposure window."""
        if kind not in PRIORITIES:
            raise ValueError(f"unknown incident kind {kind!r}")
        key = (kind, volume, shard, holder)
        with self._lock:
            inc = self._open.get(key)
            if inc is not None:
                self._c["duplicates"] += 1
                if detail:
                    inc.detail = detail
                return inc
            inc = Incident(self._next_id, kind, volume, shard, holder,
                           source, detail or {},
                           detected_at if detected_at is not None
                           else time.time())
            if shard is not None and shard < 0:
                # scrub finding with no attributable shard (shard=-1):
                # there is nothing to rebuild yet, so the drain loop
                # must skip it — but it stays VISIBLE at
                # /cluster/repairs instead of parking silently (and
                # spinning the drain with "no holder for corrupt
                # shard" backoffs, which is what it used to do)
                inc.status = "unattributed"
            self._next_id += 1
            self._open[key] = inc
            self._c["reported"] += 1
            return inc

    def resolve(self, kind: str, volume: Optional[int] = None,
                shard: Optional[int] = None, holder: str = "",
                via: str = "") -> Optional[Incident]:
        """Close an open incident; stamps time-to-re-protection."""
        key = (kind, volume, shard, holder)
        with self._lock:
            inc = self._open.pop(key, None)
            if inc is None:
                return None
            inc.status = "resolved"
            inc.resolved_at = time.time()
            inc.via = via
            self._resolved.append(inc)
            self._c["resolved"] += 1
            return inc

    def open_for_volume(self, volume: int,
                        kind: Optional[str] = None) -> List[Incident]:
        with self._lock:
            return [i for i in self._open.values()
                    if i.volume == volume
                    and (kind is None or i.kind == kind)]

    # -- drain -------------------------------------------------------

    def next_incident(self) -> Optional[Incident]:
        """Highest-priority open incident that is actionable now.
        ``at_risk_holder`` incidents are advisory — they surface in the
        snapshot and nudge scan order but have no repair action, so the
        drain never pops them."""
        now = time.time()
        with self._lock:
            best: Optional[Incident] = None
            for inc in self._open.values():
                if inc.kind == "at_risk_holder":
                    continue
                if inc.status == "unattributed":
                    # no shard to rebuild — actionable only once a
                    # later scrub (or an operator) attributes it
                    continue
                if inc.not_before > now:
                    continue
                if best is None or \
                        (PRIORITIES[inc.kind], inc.detected_at) < \
                        (PRIORITIES[best.kind], best.detected_at):
                    best = inc
            if best is not None:
                best.attempts += 1
                self._c["attempts"] += 1
            return best

    def attempt_failed(self, inc: Incident, error: str):
        with self._lock:
            inc.last_error = str(error)[:200]
            inc.not_before = time.time() + RETRY_BACKOFF_S * inc.attempts
            self._c["attempt_failures"] += 1

    # -- export ------------------------------------------------------

    def ttr_stats(self) -> dict:
        with self._lock:
            vals = sorted(i.resolved_at - i.detected_at
                          for i in self._resolved)
        return {"count": len(vals),
                "p50_s": round(_quantile(vals, 0.50), 6),
                "p99_s": round(_quantile(vals, 0.99), 6),
                "max_s": round(vals[-1], 6) if vals else 0.0}

    def depth_by_kind(self) -> Dict[str, int]:
        with self._lock:
            out = {k: 0 for k in PRIORITIES}
            for inc in self._open.values():
                out[inc.kind] += 1
            return out

    def snapshot(self) -> dict:
        with self._lock:
            open_incidents = sorted(
                (i.to_dict() for i in self._open.values()),
                key=lambda d: (d["priority"], d["detected_at"]))
            resolved = [i.to_dict() for i in self._resolved]
            counters = dict(self._c)
            unattributed = sum(1 for i in self._open.values()
                               if i.status == "unattributed")
        return {"open": open_incidents,
                "resolved_recent": resolved[-32:],
                "counters": counters,
                "depth": self.depth_by_kind(),
                "unattributed": unattributed,
                "time_to_re_protection": self.ttr_stats()}

    def summary(self) -> dict:
        """Compact form folded into /cluster/health."""
        with self._lock:
            n_open = len(self._open)
        out = {"open": n_open, "depth": self.depth_by_kind(),
               "time_to_re_protection": self.ttr_stats()}
        return out
