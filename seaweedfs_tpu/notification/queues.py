"""Notification publisher implementations + registry."""

from __future__ import annotations

import sys
import threading
from ..util.locks import make_lock
from typing import Callable, Dict, List, Type
from ..util import config


class Publisher:
    name = "abstract"

    def initialize(self, **options):
        pass

    def send(self, key: str, event: dict) -> None:
        raise NotImplementedError

    def close(self):
        pass


PUBLISHERS: Dict[str, Type[Publisher]] = {}


def register(cls: Type[Publisher]) -> Type[Publisher]:
    PUBLISHERS[cls.name] = cls
    return cls


def make_publisher(name: str, **options) -> Publisher:
    cls = PUBLISHERS.get(name)
    if cls is None:
        raise ValueError(f"unknown notification backend {name!r}; "
                         f"have {sorted(PUBLISHERS)}")
    p = cls()
    p.initialize(**options)
    return p


@register
class LogPublisher(Publisher):
    """Reference notification/log/log_queue.go — print each event."""

    name = "log"

    def initialize(self, stream=None, **options):
        self._stream = stream or sys.stderr

    def send(self, key: str, event: dict) -> None:
        print(f"[notify] {key}: {event}", file=self._stream)


@register
class MemoryPublisher(Publisher):
    """In-process pub-sub used by tests and the local replicator."""

    name = "memory"

    def initialize(self, **options):
        self._subs: List[Callable[[str, dict], None]] = []
        self._lock = make_lock("queues._lock")
        self.events: List[tuple] = []

    def subscribe(self, fn: Callable[[str, dict], None]):
        with self._lock:
            self._subs.append(fn)

    def send(self, key: str, event: dict) -> None:
        with self._lock:
            self.events.append((key, event))
            subs = list(self._subs)
        for fn in subs:
            fn(key, event)


def _post_with_retries(url: str, body: bytes, headers: dict,
                       timeout: float, retries: int, label: str) -> None:
    """Shared external-POST discipline for HTTP-backed publishers:
    retry with capped exponential backoff; 4xx (bar 429) short-circuits
    — it can never succeed on retry."""
    import time as _time
    from ..server.http_util import HttpError, http_call
    last = None
    for attempt in range(retries):
        try:
            return http_call("POST", url, body, headers,
                             timeout=timeout, external=True)
        except HttpError as e:
            last = e
            if 400 <= e.status < 500 and e.status != 429:
                break
        except Exception as e:  # noqa: BLE001 - network: retried
            last = e
        if attempt + 1 < retries:
            _time.sleep(config.retry_backoff_s(
                min(0.2 * (2 ** attempt), 2.0)))
    # chain the last HttpError so callers can classify by status
    # (google_pub_sub re-auths on 401)
    raise RuntimeError(f"{label} {url} failed after "
                       f"{attempt + 1} attempts: {last}") from last


@register
class WebhookPublisher(Publisher):
    """POST each metadata event as JSON to an HTTP endpoint — the
    broker-neutral external integration (any Kafka/SQS bridge, serverless
    consumer, or audit collector can sit behind a URL). Plays the role of
    the reference's external notification backends
    (weed/notification/) without requiring their cloud SDKs.

    Options: url (required), timeout (s), retries (attempts per event),
    hmac_key (optional — adds an X-Seaweed-Signature hex-HMAC-SHA256 of
    the body so the receiver can authenticate the sender).
    """

    name = "webhook"

    def initialize(self, url: str = "", timeout: float = 10.0,
                   retries: int = 3, hmac_key: str = "", **options):
        if not url:
            raise ValueError("webhook publisher needs a url")
        self.url = url
        self.timeout = float(timeout)
        self.retries = max(1, int(retries))
        self.hmac_key = hmac_key

    def send(self, key: str, event: dict) -> None:
        import hashlib
        import hmac
        import json
        body = json.dumps({"key": key, "event": event}).encode()
        headers = {"Content-Type": "application/json"}
        if self.hmac_key:
            headers["X-Seaweed-Signature"] = hmac.new(
                self.hmac_key.encode(), body, hashlib.sha256).hexdigest()
        _post_with_retries(self.url, body, headers, self.timeout,
                           self.retries, "webhook")


@register
class KafkaPublisher(Publisher):
    """Publish events to a Kafka topic over the classic binary protocol —
    a from-scratch produce client (notification/kafka.py), no SDK.
    Mirrors reference weed/notification/kafka/kafka_queue.go (sarama):
    event key = file path (so per-path ordering holds within a
    partition), value = JSON event."""

    name = "kafka"

    def initialize(self, hosts: str = "", topic: str = "seaweedfs_filer",
                   timeout: float = 10.0, retries: int = 3, **options):
        if not hosts:
            raise ValueError("kafka publisher needs hosts (host:port[,..])")
        from .kafka import KafkaProducer
        self.topic = topic
        self._producer = KafkaProducer(hosts, timeout=timeout,
                                       retries=retries)

    def send(self, key: str, event: dict) -> None:
        import json
        self._producer.send(self.topic, key.encode(),
                            json.dumps({"key": key, "event": event},
                                       sort_keys=True).encode())

    def close(self):
        self._producer.close()


@register
class SqsPublisher(Publisher):
    """Publish events to an AWS SQS queue via the query API with SigV4
    signing (reference weed/notification/aws_sqs/sqs_queue.go via the
    AWS SDK; same SendMessage wire call, signed by our own s3/auth
    primitives with service='sqs')."""

    name = "aws_sqs"

    def initialize(self, queue_url: str = "", access_key: str = "",
                   secret_key: str = "", region: str = "us-east-1",
                   timeout: float = 10.0, retries: int = 3, **options):
        if not queue_url:
            raise ValueError("aws_sqs publisher needs queue_url")
        self.queue_url = queue_url
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = float(timeout)
        self.retries = max(1, int(retries))

    def send(self, key: str, event: dict) -> None:
        import datetime
        import hashlib
        import json
        import urllib.parse
        from ..s3.auth import authorization_header_v4
        body = urllib.parse.urlencode({
            "Action": "SendMessage",
            "MessageBody": json.dumps({"key": key, "event": event},
                                      sort_keys=True),
            "Version": "2012-11-05",
        }).encode()
        parsed = urllib.parse.urlparse(self.queue_url)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            "content-type": "application/x-www-form-urlencoded",
            "host": parsed.netloc,
            "x-amz-content-sha256": hashlib.sha256(body).hexdigest(),
            "x-amz-date": now.strftime("%Y%m%dT%H%M%SZ"),
        }
        headers["Authorization"] = authorization_header_v4(
            "POST", parsed.path or "/", headers,
            headers["x-amz-content-sha256"], self.access_key,
            self.secret_key, self.region, "sqs")
        _post_with_retries(self.queue_url, body, headers, self.timeout,
                           self.retries, "sqs")


@register
class GocdkPubSubPublisher(Publisher):
    """URL-dispatching meta-publisher — the reference's gocdk_pub_sub
    slot (weed/notification/gocdk_pub_sub/gocdk_pub_sub.go): one
    `topic_url` whose scheme selects the broker, like the Go CDK's
    `pubsub.OpenTopic`. Every scheme routes to a native from-scratch
    publisher in this package (no SDKs):

    - ``kafka://my-topic`` — brokers from the `hosts` option or the Go
      CDK's `KAFKA_BROKERS` env var;
    - ``awssqs://sqs.<region>.amazonaws.com/<acct>/<queue>[?region=..]``;
    - ``gcppubsub://projects/<project>/topics/<topic>`` (or the
      shorthand ``gcppubsub://<project>/<topic>``);
    - ``mem://<topic>`` — the in-process memory publisher;
    - ``http(s)://...`` — the webhook publisher (an extension: the Go
      CDK has no HTTP driver, but a URL-shaped catch-all belongs here).

    Schemes the Go CDK supports with no wire analog in this
    environment (rabbit, nats, azuresb) fail loudly at initialize.
    Remaining options pass through to the wrapped publisher
    (credentials, timeouts, retries).
    """

    name = "gocdk_pub_sub"

    def initialize(self, topic_url: str = "", **options):
        import os
        import urllib.parse
        if not topic_url:
            raise ValueError("gocdk_pub_sub needs a topic_url")
        parsed = urllib.parse.urlsplit(topic_url)
        scheme = parsed.scheme.lower()
        query = dict(urllib.parse.parse_qsl(parsed.query))
        # the URL always wins over a same-named option (otherwise the
        # wrapped make_publisher gets the kwarg twice and TypeErrors)
        if scheme == "kafka":
            hosts = options.pop("hosts", "") \
                or os.environ.get("KAFKA_BROKERS", "")
            if not hosts:
                raise ValueError(
                    "gocdk_pub_sub kafka:// needs brokers via the "
                    "'hosts' option or KAFKA_BROKERS")
            topic = (parsed.netloc + parsed.path).strip("/")
            options.pop("topic", None)
            self._inner = make_publisher("kafka", hosts=hosts,
                                         topic=topic, **options)
        elif scheme == "awssqs":
            opt_region = options.pop("region", "")
            region = query.get("region", "") or opt_region
            if not region:
                host_parts = parsed.netloc.split(".")
                if len(host_parts) >= 2 and host_parts[0] == "sqs":
                    region = host_parts[1]
            if not region:
                raise ValueError(
                    "gocdk_pub_sub awssqs:// needs ?region= (host is "
                    f"not sqs.<region>...: {parsed.netloc!r})")
            queue_url = f"https://{parsed.netloc}{parsed.path}"
            options.pop("queue_url", None)
            self._inner = make_publisher("aws_sqs", queue_url=queue_url,
                                         region=region, **options)
        elif scheme == "gcppubsub":
            parts = [p for p in
                     (parsed.netloc + parsed.path).split("/") if p]
            if len(parts) == 4 and parts[0] == "projects" \
                    and parts[2] == "topics":
                project, topic = parts[1], parts[3]
            elif len(parts) == 2:
                project, topic = parts
            else:
                raise ValueError(
                    "gocdk_pub_sub gcppubsub:// wants "
                    "projects/<project>/topics/<topic>, got "
                    f"{topic_url!r}")
            options.pop("project_id", None)
            options.pop("topic", None)
            self._inner = make_publisher("google_pub_sub",
                                         project_id=project,
                                         topic=topic, **options)
        elif scheme == "mem":
            self._inner = make_publisher("memory")
        elif scheme in ("http", "https"):
            options.pop("url", None)
            self._inner = make_publisher("webhook", url=topic_url,
                                         **options)
        else:
            raise ValueError(
                f"gocdk_pub_sub: no driver for scheme {scheme!r} "
                "(have kafka, awssqs, gcppubsub, mem, http/https; "
                "rabbit/nats/azuresb have no broker analog here)")
        self.topic_url = topic_url

    def send(self, key: str, event: dict) -> None:
        self._inner.send(key, event)

    def close(self):
        self._inner.close()


def publisher_from_config(cfg: dict):
    """Build the enabled publisher from a flattened notification config
    (util.config.load_config("notification")) — the reference filer's
    notification.LoadConfiguration over notification.toml: the section
    with `enabled = true` wins, its remaining keys become the
    publisher's options. Returns None when nothing is enabled; more
    than one enabled section is a config conflict and fails loudly
    (a flattened dict has no file order to break the tie with, and
    silently picking one would publish to the wrong broker).

    Env-sourced keys arrive with dots where TOML has underscores
    (WEED_NOTIFICATION_AWS_SQS_QUEUE_URL ->
    "notification.aws.sqs.queue.url"), so both spellings of the section
    name and of option keys are accepted.
    """
    def truthy(v) -> bool:
        if isinstance(v, str):
            return v.strip().lower() in ("1", "true", "yes", "on")
        return bool(v)

    enabled_names = []
    for name in sorted(PUBLISHERS):
        prefixes = {f"notification.{name}.",
                    f"notification.{name.replace('_', '.')}."}
        if any(truthy(cfg.get(p + "enabled")) for p in prefixes):
            enabled_names.append((name, prefixes))
    if not enabled_names:
        return None
    if len(enabled_names) > 1:
        raise ValueError(
            "notification config enables more than one backend: "
            + ", ".join(n for n, _ in enabled_names)
            + " — enable exactly one (check WEED_NOTIFICATION_* env "
            "vars too)")
    name, prefixes = enabled_names[0]
    options = {}
    for key, value in cfg.items():
        for p in prefixes:
            if key.startswith(p):
                opt = key[len(p):].replace(".", "_")
                if opt != "enabled":
                    options[opt] = value
                break
    return make_publisher(name, **options)
