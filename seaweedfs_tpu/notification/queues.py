"""Notification publisher implementations + registry."""

from __future__ import annotations

import sys
import threading
from typing import Callable, Dict, List, Type


class Publisher:
    name = "abstract"

    def initialize(self, **options):
        pass

    def send(self, key: str, event: dict) -> None:
        raise NotImplementedError

    def close(self):
        pass


PUBLISHERS: Dict[str, Type[Publisher]] = {}


def register(cls: Type[Publisher]) -> Type[Publisher]:
    PUBLISHERS[cls.name] = cls
    return cls


def make_publisher(name: str, **options) -> Publisher:
    cls = PUBLISHERS.get(name)
    if cls is None:
        raise ValueError(f"unknown notification backend {name!r}; "
                         f"have {sorted(PUBLISHERS)}")
    p = cls()
    p.initialize(**options)
    return p


@register
class LogPublisher(Publisher):
    """Reference notification/log/log_queue.go — print each event."""

    name = "log"

    def initialize(self, stream=None, **options):
        self._stream = stream or sys.stderr

    def send(self, key: str, event: dict) -> None:
        print(f"[notify] {key}: {event}", file=self._stream)


@register
class MemoryPublisher(Publisher):
    """In-process pub-sub used by tests and the local replicator."""

    name = "memory"

    def initialize(self, **options):
        self._subs: List[Callable[[str, dict], None]] = []
        self._lock = threading.Lock()
        self.events: List[tuple] = []

    def subscribe(self, fn: Callable[[str, dict], None]):
        with self._lock:
            self._subs.append(fn)

    def send(self, key: str, event: dict) -> None:
        with self._lock:
            self.events.append((key, event))
            subs = list(self._subs)
        for fn in subs:
            fn(key, event)


class StubPublisher(Publisher):
    """Placeholder for cloud brokers not present in this environment
    (kafka/aws_sqs/google_pub_sub/gocdk_pub_sub). Configuring one fails
    at first send with an actionable error, mirroring how the reference
    fails when the broker endpoint is unreachable."""

    def send(self, key: str, event: dict) -> None:
        raise RuntimeError(
            f"notification backend {self.name!r} requires an external "
            f"broker that is not available in this environment")


for _name in ("kafka", "aws_sqs", "google_pub_sub", "gocdk_pub_sub"):
    register(type(f"Stub_{_name}", (StubPublisher,), {"name": _name}))
