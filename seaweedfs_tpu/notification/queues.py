"""Notification publisher implementations + registry."""

from __future__ import annotations

import sys
import threading
from typing import Callable, Dict, List, Type


class Publisher:
    name = "abstract"

    def initialize(self, **options):
        pass

    def send(self, key: str, event: dict) -> None:
        raise NotImplementedError

    def close(self):
        pass


PUBLISHERS: Dict[str, Type[Publisher]] = {}


def register(cls: Type[Publisher]) -> Type[Publisher]:
    PUBLISHERS[cls.name] = cls
    return cls


def make_publisher(name: str, **options) -> Publisher:
    cls = PUBLISHERS.get(name)
    if cls is None:
        raise ValueError(f"unknown notification backend {name!r}; "
                         f"have {sorted(PUBLISHERS)}")
    p = cls()
    p.initialize(**options)
    return p


@register
class LogPublisher(Publisher):
    """Reference notification/log/log_queue.go — print each event."""

    name = "log"

    def initialize(self, stream=None, **options):
        self._stream = stream or sys.stderr

    def send(self, key: str, event: dict) -> None:
        print(f"[notify] {key}: {event}", file=self._stream)


@register
class MemoryPublisher(Publisher):
    """In-process pub-sub used by tests and the local replicator."""

    name = "memory"

    def initialize(self, **options):
        self._subs: List[Callable[[str, dict], None]] = []
        self._lock = threading.Lock()
        self.events: List[tuple] = []

    def subscribe(self, fn: Callable[[str, dict], None]):
        with self._lock:
            self._subs.append(fn)

    def send(self, key: str, event: dict) -> None:
        with self._lock:
            self.events.append((key, event))
            subs = list(self._subs)
        for fn in subs:
            fn(key, event)


def _post_with_retries(url: str, body: bytes, headers: dict,
                       timeout: float, retries: int, label: str) -> None:
    """Shared external-POST discipline for HTTP-backed publishers:
    retry with capped exponential backoff; 4xx (bar 429) short-circuits
    — it can never succeed on retry."""
    import time as _time
    from ..server.http_util import HttpError, http_call
    last = None
    for attempt in range(retries):
        try:
            return http_call("POST", url, body, headers,
                             timeout=timeout, external=True)
        except HttpError as e:
            last = e
            if 400 <= e.status < 500 and e.status != 429:
                break
        except Exception as e:  # noqa: BLE001 - network: retried
            last = e
        if attempt + 1 < retries:
            _time.sleep(min(0.2 * (2 ** attempt), 2.0))
    # chain the last HttpError so callers can classify by status
    # (google_pub_sub re-auths on 401)
    raise RuntimeError(f"{label} {url} failed after "
                       f"{attempt + 1} attempts: {last}") from last


@register
class WebhookPublisher(Publisher):
    """POST each metadata event as JSON to an HTTP endpoint — the
    broker-neutral external integration (any Kafka/SQS bridge, serverless
    consumer, or audit collector can sit behind a URL). Plays the role of
    the reference's external notification backends
    (weed/notification/) without requiring their cloud SDKs.

    Options: url (required), timeout (s), retries (attempts per event),
    hmac_key (optional — adds an X-Seaweed-Signature hex-HMAC-SHA256 of
    the body so the receiver can authenticate the sender).
    """

    name = "webhook"

    def initialize(self, url: str = "", timeout: float = 10.0,
                   retries: int = 3, hmac_key: str = "", **options):
        if not url:
            raise ValueError("webhook publisher needs a url")
        self.url = url
        self.timeout = float(timeout)
        self.retries = max(1, int(retries))
        self.hmac_key = hmac_key

    def send(self, key: str, event: dict) -> None:
        import hashlib
        import hmac
        import json
        body = json.dumps({"key": key, "event": event}).encode()
        headers = {"Content-Type": "application/json"}
        if self.hmac_key:
            headers["X-Seaweed-Signature"] = hmac.new(
                self.hmac_key.encode(), body, hashlib.sha256).hexdigest()
        _post_with_retries(self.url, body, headers, self.timeout,
                           self.retries, "webhook")


@register
class KafkaPublisher(Publisher):
    """Publish events to a Kafka topic over the classic binary protocol —
    a from-scratch produce client (notification/kafka.py), no SDK.
    Mirrors reference weed/notification/kafka/kafka_queue.go (sarama):
    event key = file path (so per-path ordering holds within a
    partition), value = JSON event."""

    name = "kafka"

    def initialize(self, hosts: str = "", topic: str = "seaweedfs_filer",
                   timeout: float = 10.0, retries: int = 3, **options):
        if not hosts:
            raise ValueError("kafka publisher needs hosts (host:port[,..])")
        from .kafka import KafkaProducer
        self.topic = topic
        self._producer = KafkaProducer(hosts, timeout=timeout,
                                       retries=retries)

    def send(self, key: str, event: dict) -> None:
        import json
        self._producer.send(self.topic, key.encode(),
                            json.dumps({"key": key, "event": event},
                                       sort_keys=True).encode())

    def close(self):
        self._producer.close()


@register
class SqsPublisher(Publisher):
    """Publish events to an AWS SQS queue via the query API with SigV4
    signing (reference weed/notification/aws_sqs/sqs_queue.go via the
    AWS SDK; same SendMessage wire call, signed by our own s3/auth
    primitives with service='sqs')."""

    name = "aws_sqs"

    def initialize(self, queue_url: str = "", access_key: str = "",
                   secret_key: str = "", region: str = "us-east-1",
                   timeout: float = 10.0, retries: int = 3, **options):
        if not queue_url:
            raise ValueError("aws_sqs publisher needs queue_url")
        self.queue_url = queue_url
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = float(timeout)
        self.retries = max(1, int(retries))

    def send(self, key: str, event: dict) -> None:
        import datetime
        import hashlib
        import json
        import urllib.parse
        from ..s3.auth import authorization_header_v4
        body = urllib.parse.urlencode({
            "Action": "SendMessage",
            "MessageBody": json.dumps({"key": key, "event": event},
                                      sort_keys=True),
            "Version": "2012-11-05",
        }).encode()
        parsed = urllib.parse.urlparse(self.queue_url)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            "content-type": "application/x-www-form-urlencoded",
            "host": parsed.netloc,
            "x-amz-content-sha256": hashlib.sha256(body).hexdigest(),
            "x-amz-date": now.strftime("%Y%m%dT%H%M%SZ"),
        }
        headers["Authorization"] = authorization_header_v4(
            "POST", parsed.path or "/", headers,
            headers["x-amz-content-sha256"], self.access_key,
            self.secret_key, self.region, "sqs")
        _post_with_retries(self.queue_url, body, headers, self.timeout,
                           self.retries, "sqs")


class StubPublisher(Publisher):
    """Placeholder for meta-backends with nothing concrete to wrap
    (gocdk_pub_sub points at whichever broker gocdk is configured
    for — kafka/SQS/pubsub all have native publishers here).
    Configuring one fails at first send with an actionable error,
    mirroring how the reference fails when the broker endpoint is
    unreachable."""

    def send(self, key: str, event: dict) -> None:
        raise RuntimeError(
            f"notification backend {self.name!r} requires an external "
            f"broker that is not available in this environment")


# google_pub_sub is REAL now (google_pub_sub.py: from-scratch OAuth2
# JWT-bearer + RS256 + REST publish); only the gocdk meta-backend stays
# a stub (it exists to wrap whichever broker gocdk points at — every
# concrete broker here already has a native publisher)
for _name in ("gocdk_pub_sub",):
    register(type(f"Stub_{_name}", (StubPublisher,), {"name": _name}))
