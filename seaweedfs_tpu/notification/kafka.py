"""Minimal Kafka wire-protocol producer (no SDK).

The reference ships a Kafka notification backend
(reference weed/notification/kafka/kafka_queue.go via the sarama client);
this is a from-scratch produce-only client speaking the classic binary
protocol over TCP — Metadata v0 (api_key 3) to discover partition
leaders, Produce v0 (api_key 0) with message-format-v0 sets to publish —
so filer metadata events can land in any broker that accepts the classic
protocol (Kafka <= 3.x, Redpanda), with zero dependencies.

Kept deliberately at protocol v0: the framing is stable, every broker
generation that predates KIP-896 accepts it, and the publisher's job is
an at-least-once event firehose, not a transactional producer.

Wire shapes (big-endian):
  frame    = int32 size | payload
  request  = int16 api_key | int16 api_version | int32 correlation_id
           | STRING client_id | body
  response = int32 correlation_id | body
  STRING   = int16 len | bytes          (-1 = null)
  BYTES    = int32 len | bytes          (-1 = null)
  message  = int64 offset | int32 size | uint32 crc | int8 magic(0)
           | int8 attrs(0) | BYTES key | BYTES value
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

API_PRODUCE = 0
API_METADATA = 3

# error codes that a metadata refresh + retry can fix
_RETRIABLE = {3, 5, 6, 7}  # unknown topic/partition, leader not
# available, not leader for partition, request timed out


class KafkaError(Exception):
    """retriable=False marks permanent broker verdicts (e.g.
    MESSAGE_TOO_LARGE) that re-sending the same payload can never fix —
    send() propagates those immediately instead of burning retries."""

    def __init__(self, msg: str, retriable: bool = True):
        super().__init__(msg)
        self.retriable = retriable


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    """Cursor over a response payload."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise KafkaError("short response")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode()


def encode_message_set(pairs: List[Tuple[Optional[bytes], bytes]]) -> bytes:
    """Message-format-v0 set: one (key, value) message per pair."""
    out = []
    for key, value in pairs:
        body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        msg = struct.pack(">I", crc) + body
        out.append(struct.pack(">qi", 0, len(msg)) + msg)
    return b"".join(out)


class KafkaProducer:
    """Produce-only client: leader discovery, per-key partitioning,
    retry with metadata refresh on retriable errors."""

    def __init__(self, bootstrap: str, client_id: str = "seaweedfs",
                 timeout: float = 10.0, acks: int = 1, retries: int = 3):
        # bootstrap: "host:port" or comma-separated list
        self.seeds = []
        for hp in bootstrap.split(","):
            hp = hp.strip()
            if not hp:
                continue
            host, _, port = hp.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"bad kafka bootstrap entry {hp!r}: want host:port")
            self.seeds.append((host, int(port)))
        if not self.seeds:
            raise ValueError("kafka producer needs bootstrap host:port")
        self.client_id = client_id
        self.timeout = float(timeout)
        self.acks = int(acks)
        self.retries = max(1, int(retries))
        self._corr = 0
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        # topic -> {partition: (host, port)} (leaderless partitions absent)
        self._leaders: Dict[str, Dict[int, Tuple[str, int]]] = {}
        # topic -> total partition count (incl. leaderless — the key->
        # partition mapping must be stable across leader elections)
        self._npartitions: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- transport --------------------------------------------------------

    def _conn(self, addr: Tuple[str, int]) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.timeout)
        sock.settimeout(self.timeout)
        self._conns[addr] = sock
        return sock

    def _drop_conn(self, addr: Tuple[str, int]):
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _call(self, addr: Tuple[str, int], api_key: int, body: bytes,
              expect_response: bool = True) -> Optional[_Reader]:
        self._corr += 1
        corr = self._corr
        header = struct.pack(">hhi", api_key, 0, corr) + _str(self.client_id)
        frame = header + body
        sock = self._conn(addr)
        try:
            sock.sendall(struct.pack(">i", len(frame)) + frame)
            if not expect_response:
                # produce with acks=0: the broker sends nothing back
                return None
            raw = self._recv_exact(sock, 4)
            (size,) = struct.unpack(">i", raw)
            if size < 4 or size > 64 << 20:
                raise KafkaError(f"bad response size {size}")
            payload = self._recv_exact(sock, size)
        except (OSError, KafkaError):
            self._drop_conn(addr)
            raise
        r = _Reader(payload)
        got = r.i32()
        if got != corr:
            self._drop_conn(addr)
            raise KafkaError(f"correlation mismatch {got} != {corr}")
        return r

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            c = sock.recv(n)
            if not c:
                raise KafkaError("connection closed")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    # -- metadata ---------------------------------------------------------

    def _refresh_metadata(self, topic: str):
        body = struct.pack(">i", 1) + _str(topic)
        last: Exception = KafkaError("no seed brokers")
        for addr in self.seeds:
            try:
                r = self._call(addr, API_METADATA, body)
            except (OSError, KafkaError) as e:
                last = e
                continue
            brokers: Dict[int, Tuple[str, int]] = {}
            for _ in range(r.i32()):
                node = r.i32()
                host = r.string() or ""
                port = r.i32()
                brokers[node] = (host, port)
            leaders: Dict[int, Tuple[str, int]] = {}
            topic_err = 0
            total = 0
            for _ in range(r.i32()):
                terr = r.i16()
                tname = r.string()
                parts = {}
                nparts = r.i32()
                for _ in range(nparts):
                    perr = r.i16()
                    pid = r.i32()
                    leader = r.i32()
                    for _ in range(r.i32()):  # replicas
                        r.i32()
                    for _ in range(r.i32()):  # isr
                        r.i32()
                    if perr in (0, 9) and leader in brokers:
                        # 9 = replica-not-available: leader still usable
                        parts[pid] = brokers[leader]
                if tname == topic:
                    topic_err = terr
                    leaders = parts
                    total = nparts
            if topic_err not in (0, 5) and not leaders:
                raise KafkaError(f"topic {topic!r}: broker error "
                                 f"{topic_err}")
            if leaders:
                self._leaders[topic] = leaders
                self._npartitions[topic] = total
                return
            last = KafkaError(f"no leaders for topic {topic!r}")
        raise last

    def _leader_for(self, topic: str, key: Optional[bytes]
                    ) -> Tuple[int, Tuple[str, int]]:
        parts = self._leaders.get(topic)
        if not parts:
            self._refresh_metadata(topic)
            parts = self._leaders.get(topic) or {}
        if not parts:
            raise KafkaError(f"no partitions for topic {topic!r}")
        total = self._npartitions.get(topic, len(parts))
        if key is None:
            # keyless: any currently-led partition will do
            pids = sorted(parts)
            pid = pids[int(time.monotonic() * 1000) % len(pids)]
        else:
            # keyed: hash over the TOTAL partition count so the key->
            # partition mapping (and per-key ordering) is stable across
            # leader elections; a leaderless target is a retriable
            # condition, not a remap (sarama's hash partitioner errors
            # the same way)
            pid = zlib.crc32(key) % total
            if pid not in parts:
                raise KafkaError(
                    f"partition {pid} of {topic!r} has no leader")
        return pid, parts[pid]

    # -- produce ----------------------------------------------------------

    def send(self, topic: str, key: Optional[bytes], value: bytes) -> int:
        """Publish one message; returns the broker-assigned base offset
        (-1 with acks=0). Retries with a metadata refresh on leadership
        errors — at-least-once, like the reference's sarama config."""
        with self._lock:
            last: Exception = KafkaError("unreachable")
            for attempt in range(self.retries):
                try:
                    return self._send_once(topic, key, value)
                except (OSError, KafkaError) as e:
                    if isinstance(e, KafkaError) and not e.retriable:
                        raise  # permanent verdict: retrying can't help
                    last = e
                    self._leaders.pop(topic, None)
                    if attempt + 1 < self.retries:
                        time.sleep(min(0.1 * (2 ** attempt), 1.0))
            raise KafkaError(
                f"produce to {topic!r} failed after {self.retries} "
                f"attempts: {last}")

    def _send_once(self, topic: str, key: Optional[bytes],
                   value: bytes) -> int:
        pid, addr = self._leader_for(topic, key)
        mset = encode_message_set([(key, value)])
        body = (struct.pack(">hi", self.acks, int(self.timeout * 1000))
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">i", pid) + struct.pack(">i", len(mset))
                + mset)
        r = self._call(addr, API_PRODUCE, body,
                       expect_response=self.acks != 0)
        if self.acks == 0:
            return -1
        for _ in range(r.i32()):
            r.string()  # topic name
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                offset = r.i64()
                if err:
                    if err in _RETRIABLE:
                        raise KafkaError(f"retriable broker error {err}")
                    raise KafkaError(
                        f"produce failed: broker error {err}",
                        retriable=False)
                return offset
        raise KafkaError("empty produce response")

    def close(self):
        with self._lock:
            for addr in list(self._conns):
                self._drop_conn(addr)
