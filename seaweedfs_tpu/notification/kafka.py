"""Minimal Kafka wire-protocol producer (no SDK).

The reference ships a Kafka notification backend
(reference weed/notification/kafka/kafka_queue.go via the sarama
client, which version-negotiates automatically); this is a from-scratch
produce-only client speaking the binary protocol over TCP, with zero
dependencies.

Version negotiation (KIP-35): on the first use of each broker
connection the client sends ApiVersions v0 and intersects the broker's
advertised [min,max] per api with what it speaks — Metadata v0 or v4,
Produce v0 (message-format-v0 sets) or v3 (record-batch v2 with
crc32c + varints). Classic brokers (<= 3.x, Redpanda) get the v0
forms; KIP-896 brokers (Kafka 4.x, which REMOVED Produce v0-v2) get
v3. No overlap fails loudly and permanently — silently "retrying" an
unsupported version can never succeed. A broker so old it resets on
ApiVersions itself is assumed v0-only, like sarama's fallback.

Wire shapes (big-endian):
  frame    = int32 size | payload
  request  = int16 api_key | int16 api_version | int32 correlation_id
           | STRING client_id | body
  response = int32 correlation_id | body
  STRING   = int16 len | bytes          (-1 = null)
  BYTES    = int32 len | bytes          (-1 = null)
  message  = int64 offset | int32 size | uint32 crc | int8 magic(0)
           | int8 attrs(0) | BYTES key | BYTES value
  batch(v2)= int64 baseOffset | int32 batchLen | int32 leaderEpoch(-1)
           | int8 magic(2) | uint32 crc32c | int16 attrs
           | int32 lastOffsetDelta | int64 baseTs | int64 maxTs
           | int64 producerId(-1) | int16 producerEpoch(-1)
           | int32 baseSeq(-1) | int32 count | records
  record   = varint len | int8 attrs | varint tsDelta | varint offDelta
           | varint keyLen | key | varint valLen | val | varint headers
"""

from __future__ import annotations

import socket
import struct
import threading
from ..util.locks import make_lock
import time
import zlib
from typing import Dict, List, Optional, Tuple
from ..util import config

API_PRODUCE = 0
API_METADATA = 3
API_VERSIONS = 18

ERR_UNSUPPORTED_VERSION = 35

# error codes that a metadata refresh + retry can fix
_RETRIABLE = {3, 5, 6, 7}  # unknown topic/partition, leader not
# available, not leader for partition, request timed out


class KafkaError(Exception):
    """retriable=False marks permanent broker verdicts (e.g.
    MESSAGE_TOO_LARGE) that re-sending the same payload can never fix —
    send() propagates those immediately instead of burning retries."""

    def __init__(self, msg: str, retriable: bool = True):
        super().__init__(msg)
        self.retriable = retriable


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    """Cursor over a response payload."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise KafkaError("short response")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode()


def encode_message_set(pairs: List[Tuple[Optional[bytes], bytes]]) -> bytes:
    """Message-format-v0 set: one (key, value) message per pair."""
    out = []
    for key, value in pairs:
        body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        msg = struct.pack(">I", crc) + body
        out.append(struct.pack(">qi", 0, len(msg)) + msg)
    return b"".join(out)


# -- record-batch v2 (Produce >= v3) -----------------------------------------

_CRC32C_TABLE = []


def _crc32c(data: bytes) -> int:
    """Castagnoli CRC (record-batch v2 checksums use it, not CRC-32)."""
    if not _CRC32C_TABLE:
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            _CRC32C_TABLE.append(c)
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _varint(n: int) -> bytes:
    """Zigzag varint (protobuf-style), as records use."""
    z = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """(value, new_pos) — exported for the test broker's decoder."""
    shift = z = 0
    while True:
        b = buf[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


def encode_record_batch(pairs: List[Tuple[Optional[bytes], bytes]],
                        ts_ms: int) -> bytes:
    """Message-format-v2 batch (the only format Produce v3+ accepts)."""
    records = []
    for i, (key, value) in enumerate(pairs):
        body = bytearray(b"\x00")                    # record attributes
        body += _varint(0)                           # timestamp delta
        body += _varint(i)                           # offset delta
        if key is None:
            body += _varint(-1)
        else:
            body += _varint(len(key)) + key
        body += _varint(len(value)) + value
        body += _varint(0)                           # no headers
        records.append(_varint(len(body)) + bytes(body))
    recs = b"".join(records)
    # attributes .. records — the crc32c covers exactly this span
    tail = (struct.pack(">hiqqqhii", 0, len(pairs) - 1, ts_ms, ts_ms,
                        -1, -1, -1, len(pairs)) + recs)
    head = struct.pack(">ib", -1, 2)  # partitionLeaderEpoch, magic
    inner = head + struct.pack(">I", _crc32c(tail)) + tail
    return struct.pack(">qi", 0, len(inner)) + inner


class KafkaProducer:
    """Produce-only client: leader discovery, per-key partitioning,
    retry with metadata refresh on retriable errors."""

    def __init__(self, bootstrap: str, client_id: str = "seaweedfs",
                 timeout: float = 10.0, acks: int = 1, retries: int = 3):
        # bootstrap: "host:port" or comma-separated list
        self.seeds = []
        for hp in bootstrap.split(","):
            hp = hp.strip()
            if not hp:
                continue
            host, _, port = hp.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"bad kafka bootstrap entry {hp!r}: want host:port")
            self.seeds.append((host, int(port)))
        if not self.seeds:
            raise ValueError("kafka producer needs bootstrap host:port")
        self.client_id = client_id
        self.timeout = float(timeout)
        self.acks = int(acks)
        self.retries = max(1, int(retries))
        self._corr = 0
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        # broker -> {api_key: (min, max)} from the ApiVersions probe
        self._api_ranges: Dict[Tuple[str, int],
                               Dict[int, Tuple[int, int]]] = {}
        # topic -> {partition: (host, port)} (leaderless partitions absent)
        self._leaders: Dict[str, Dict[int, Tuple[str, int]]] = {}
        # topic -> total partition count (incl. leaderless — the key->
        # partition mapping must be stable across leader elections)
        self._npartitions: Dict[str, int] = {}
        self._lock = make_lock("kafka._lock")

    # -- transport --------------------------------------------------------

    def _conn(self, addr: Tuple[str, int]) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.timeout)
        sock.settimeout(self.timeout)
        self._conns[addr] = sock
        if addr not in self._api_ranges:
            self._probe_versions(addr, sock)
        # the probe's legacy fallback may have replaced the socket
        return self._conns[addr]

    def _probe_versions(self, addr: Tuple[str, int],
                        sock: socket.socket):
        """ApiVersions v0 handshake (KIP-35): learn the broker's
        [min,max] per api before speaking anything else. A broker so
        ancient it drops the probe is assumed v0-only (sarama's
        fallback for pre-0.10 brokers)."""
        self._corr += 1
        corr = self._corr
        frame = struct.pack(">hhi", API_VERSIONS, 0, corr) + \
            _str(self.client_id)
        try:
            sock.sendall(struct.pack(">i", len(frame)) + frame)
            (size,) = struct.unpack(">i", self._recv_exact(sock, 4))
            payload = self._recv_exact(sock, size) if 4 <= size <= 1 << 20 \
                else None
        except OSError:
            # TRANSPORT failure only: a pre-KIP-35 broker severs on the
            # probe — reconnect and speak the classic v0 protocol. The
            # cache entry dies with the connection (_drop_conn), so a
            # transient hiccup against a modern broker re-probes on the
            # next reconnect instead of pinning it to v0. The v0 pin is
            # written only AFTER the reconnect succeeds — a failed
            # reconnect must leave no cache for the next attempt to
            # skip the probe on.
            self._drop_conn(addr)
            sock = socket.create_connection(addr, timeout=self.timeout)
            sock.settimeout(self.timeout)
            self._conns[addr] = sock
            self._api_ranges[addr] = {API_PRODUCE: (0, 0),
                                      API_METADATA: (0, 0)}
            return
        # a broker that ANSWERED but with garbage or an explicit
        # non-35 error is not a legacy broker — diagnose loudly,
        # permanently (guessing v0 would just retry-loop into severed
        # connections with a misleading error)
        if payload is None:
            self._drop_conn(addr)
            raise KafkaError(f"bad ApiVersions response size {size}",
                             retriable=False)
        r = _Reader(payload)
        if r.i32() != corr:
            self._drop_conn(addr)
            raise KafkaError("ApiVersions correlation mismatch",
                             retriable=False)
        err = r.i16()
        ranges: Dict[int, Tuple[int, int]] = {}
        for _ in range(r.i32()):
            api, lo, hi = r.i16(), r.i16(), r.i16()
            ranges[api] = (lo, hi)
        # KIP-511: err 35 still carries the supported table
        if (err and err != ERR_UNSUPPORTED_VERSION) or not ranges:
            self._drop_conn(addr)
            raise KafkaError(
                f"ApiVersions error {err}, {len(ranges)} entries",
                retriable=False)
        self._api_ranges[addr] = ranges

    # versions this client can speak, best first
    _SUPPORTED = {API_PRODUCE: (3, 0), API_METADATA: (4, 0)}

    def _pick_version(self, addr: Tuple[str, int], api_key: int) -> int:
        """Best mutually-supported version, or a LOUD permanent error —
        an unsupported version can never start working on retry."""
        lo, hi = self._api_ranges.get(addr, {}).get(api_key, (0, 0))
        for cand in self._SUPPORTED[api_key]:
            if lo <= cand <= hi:
                return cand
        raise KafkaError(
            f"no overlapping version for api {api_key}: broker "
            f"{addr[0]}:{addr[1]} supports [{lo},{hi}], client speaks "
            f"{sorted(self._SUPPORTED[api_key])}", retriable=False)

    def _drop_conn(self, addr: Tuple[str, int]):
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        # version knowledge is per-connection: a fallback cached off a
        # TRANSIENT failure must not pin a modern broker to v0 forever,
        # so the next reconnect re-probes (one extra roundtrip)
        self._api_ranges.pop(addr, None)

    def _call(self, addr: Tuple[str, int], api_key: int, body: bytes,
              expect_response: bool = True,
              version: int = 0) -> Optional[_Reader]:
        self._corr += 1
        corr = self._corr
        header = struct.pack(">hhi", api_key, version, corr) + \
            _str(self.client_id)
        frame = header + body
        sock = self._conn(addr)
        try:
            sock.sendall(struct.pack(">i", len(frame)) + frame)
            if not expect_response:
                # produce with acks=0: the broker sends nothing back
                return None
            raw = self._recv_exact(sock, 4)
            (size,) = struct.unpack(">i", raw)
            if size < 4 or size > 64 << 20:
                raise KafkaError(f"bad response size {size}")
            payload = self._recv_exact(sock, size)
        except (OSError, KafkaError):
            self._drop_conn(addr)
            raise
        r = _Reader(payload)
        got = r.i32()
        if got != corr:
            self._drop_conn(addr)
            raise KafkaError(f"correlation mismatch {got} != {corr}")
        return r

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            c = sock.recv(n)
            if not c:
                # a TRANSPORT condition, not a protocol verdict: the
                # ApiVersions probe's legacy-broker fallback and the
                # retry loop both key on OSError for torn connections
                raise ConnectionError("connection closed by broker")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    # -- metadata ---------------------------------------------------------

    def _refresh_metadata(self, topic: str):
        last: Exception = KafkaError("no seed brokers")
        for addr in self.seeds:
            try:
                self._conn(addr)  # ensures the ApiVersions probe ran
                ver = self._pick_version(addr, API_METADATA)
                body = struct.pack(">i", 1) + _str(topic)
                if ver >= 4:
                    body += struct.pack(">b", 1)  # allow auto-create
                r = self._call(addr, API_METADATA, body, version=ver)
            except (OSError, KafkaError) as e:
                if isinstance(e, KafkaError) and not e.retriable:
                    raise
                last = e
                continue
            if ver >= 3:
                r.i32()  # throttle_time_ms
            brokers: Dict[int, Tuple[str, int]] = {}
            for _ in range(r.i32()):
                node = r.i32()
                host = r.string() or ""
                port = r.i32()
                if ver >= 1:
                    r.string()  # rack
                brokers[node] = (host, port)
            if ver >= 2:
                r.string()  # cluster_id
            if ver >= 1:
                r.i32()  # controller_id
            leaders: Dict[int, Tuple[str, int]] = {}
            topic_err = 0
            total = 0
            for _ in range(r.i32()):
                terr = r.i16()
                tname = r.string()
                if ver >= 1:
                    r._take(1)  # is_internal
                parts = {}
                nparts = r.i32()
                for _ in range(nparts):
                    perr = r.i16()
                    pid = r.i32()
                    leader = r.i32()
                    for _ in range(r.i32()):  # replicas
                        r.i32()
                    for _ in range(r.i32()):  # isr
                        r.i32()
                    if ver >= 5:
                        for _ in range(r.i32()):  # offline replicas
                            r.i32()
                    if perr in (0, 9) and leader in brokers:
                        # 9 = replica-not-available: leader still usable
                        parts[pid] = brokers[leader]
                if tname == topic:
                    topic_err = terr
                    leaders = parts
                    total = nparts
            if topic_err not in (0, 5) and not leaders:
                raise KafkaError(f"topic {topic!r}: broker error "
                                 f"{topic_err}")
            if leaders:
                self._leaders[topic] = leaders
                self._npartitions[topic] = total
                return
            last = KafkaError(f"no leaders for topic {topic!r}")
        raise last

    def _leader_for(self, topic: str, key: Optional[bytes]
                    ) -> Tuple[int, Tuple[str, int]]:
        parts = self._leaders.get(topic)
        if not parts:
            self._refresh_metadata(topic)
            parts = self._leaders.get(topic) or {}
        if not parts:
            raise KafkaError(f"no partitions for topic {topic!r}")
        total = self._npartitions.get(topic, len(parts))
        if key is None:
            # keyless: any currently-led partition will do
            pids = sorted(parts)
            pid = pids[int(time.monotonic() * 1000) % len(pids)]
        else:
            # keyed: hash over the TOTAL partition count so the key->
            # partition mapping (and per-key ordering) is stable across
            # leader elections; a leaderless target is a retriable
            # condition, not a remap (sarama's hash partitioner errors
            # the same way)
            pid = zlib.crc32(key) % total
            if pid not in parts:
                raise KafkaError(
                    f"partition {pid} of {topic!r} has no leader")
        return pid, parts[pid]

    # -- produce ----------------------------------------------------------

    def send(self, topic: str, key: Optional[bytes], value: bytes) -> int:
        """Publish one message; returns the broker-assigned base offset
        (-1 with acks=0). Retries with a metadata refresh on leadership
        errors — at-least-once, like the reference's sarama config."""
        last: Exception = KafkaError("unreachable")
        for attempt in range(self.retries):
            try:
                # the lock covers one wire attempt (socket + leader
                # cache); the backoff sleep happens OUTSIDE it so a
                # flapping leader can't stall every other producer
                # thread for the whole retry schedule
                with self._lock:
                    return self._send_once(topic, key, value)
            except (OSError, KafkaError) as e:
                if isinstance(e, KafkaError) and not e.retriable:
                    raise  # permanent verdict: retrying can't help
                last = e
                with self._lock:
                    self._leaders.pop(topic, None)
                if attempt + 1 < self.retries:
                    time.sleep(config.retry_backoff_s(
                        min(0.1 * (2 ** attempt), 1.0)))
        raise KafkaError(
            f"produce to {topic!r} failed after {self.retries} "
            f"attempts: {last}")

    def _send_once(self, topic: str, key: Optional[bytes],
                   value: bytes) -> int:
        pid, addr = self._leader_for(topic, key)
        self._conn(addr)  # ensures the ApiVersions probe ran
        ver = self._pick_version(addr, API_PRODUCE)
        if ver >= 3:
            recs = encode_record_batch([(key, value)],
                                       int(time.time() * 1000))
            body = _str(None)  # transactional_id
        else:
            recs = encode_message_set([(key, value)])
            body = b""
        body += (struct.pack(">hi", self.acks, int(self.timeout * 1000))
                 + struct.pack(">i", 1) + _str(topic)
                 + struct.pack(">i", 1)
                 + struct.pack(">i", pid) + struct.pack(">i", len(recs))
                 + recs)
        r = self._call(addr, API_PRODUCE, body,
                       expect_response=self.acks != 0, version=ver)
        if self.acks == 0:
            return -1
        for _ in range(r.i32()):
            r.string()  # topic name
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                offset = r.i64()
                if err:
                    if err in _RETRIABLE:
                        raise KafkaError(f"retriable broker error {err}")
                    raise KafkaError(
                        f"produce failed: broker error {err}",
                        retriable=False)
                return offset
        raise KafkaError("empty produce response")

    def close(self):
        with self._lock:
            for addr in list(self._conns):
                self._drop_conn(addr)
