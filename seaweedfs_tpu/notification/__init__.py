"""Pluggable metadata-event publishers.

Reference weed/notification/: a MessageQueue interface with
implementations selected by notification.toml (kafka, aws_sqs,
google_pub_sub, gocdk_pub_sub, log). Here: `log` (stderr/file),
`memory` (in-process, for tests and the replicator), `webhook`
(JSON POST), `kafka` (version-negotiated wire producer,
notification/kafka.py), `aws_sqs` (SigV4-signed SendMessage) and
`google_pub_sub` (from-scratch OAuth2 JWT-bearer + RS256 + REST
publish, google_pub_sub.py) are real; `gocdk_pub_sub` is the
URL-dispatching meta-publisher (one topic_url whose scheme picks the
broker, like the Go CDK's pubsub.OpenTopic) routing to the native
publishers above.
"""

from .google_pub_sub import GooglePubSubPublisher  # noqa: F401
from .queues import (  # noqa: F401
    PUBLISHERS,
    GocdkPubSubPublisher,
    KafkaPublisher,
    LogPublisher,
    MemoryPublisher,
    Publisher,
    SqsPublisher,
    WebhookPublisher,
    make_publisher,
)
