"""Pluggable metadata-event publishers.

Reference weed/notification/: a MessageQueue interface with
implementations selected by notification.toml (kafka, aws_sqs,
google_pub_sub, gocdk_pub_sub, log). Here: `log` (stderr/file) and
`memory` (in-process, for tests and the replicator) are real; the
cloud publishers are registered stubs that raise on use so config
errors surface the same way the reference's missing-broker errors do.
"""

from .queues import (  # noqa: F401
    PUBLISHERS,
    LogPublisher,
    MemoryPublisher,
    Publisher,
    StubPublisher,
    make_publisher,
)
