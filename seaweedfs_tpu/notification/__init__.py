"""Pluggable metadata-event publishers.

Reference weed/notification/: a MessageQueue interface with
implementations selected by notification.toml (kafka, aws_sqs,
google_pub_sub, gocdk_pub_sub, log). Here: `log` (stderr/file),
`memory` (in-process, for tests and the replicator), `webhook`
(JSON POST), `kafka` (from-scratch classic-protocol producer,
notification/kafka.py) and `aws_sqs` (SigV4-signed SendMessage) are
real; the OAuth2-gated pubsub publishers are registered stubs that
raise on use so config errors surface the same way the reference's
missing-broker errors do.
"""

from .queues import (  # noqa: F401
    PUBLISHERS,
    KafkaPublisher,
    LogPublisher,
    MemoryPublisher,
    Publisher,
    SqsPublisher,
    StubPublisher,
    WebhookPublisher,
    make_publisher,
)
