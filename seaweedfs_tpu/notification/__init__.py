"""Pluggable metadata-event publishers.

Reference weed/notification/: a MessageQueue interface with
implementations selected by notification.toml (kafka, aws_sqs,
google_pub_sub, gocdk_pub_sub, log). Here: `log` (stderr/file),
`memory` (in-process, for tests and the replicator), `webhook`
(JSON POST), `kafka` (version-negotiated wire producer,
notification/kafka.py), `aws_sqs` (SigV4-signed SendMessage) and
`google_pub_sub` (from-scratch OAuth2 JWT-bearer + RS256 + REST
publish, google_pub_sub.py) are real; the gocdk meta-backend stays a
registered stub that raises on use so config errors surface the same
way the reference's missing-broker errors do.
"""

from .google_pub_sub import GooglePubSubPublisher  # noqa: F401
from .queues import (  # noqa: F401
    PUBLISHERS,
    KafkaPublisher,
    LogPublisher,
    MemoryPublisher,
    Publisher,
    SqsPublisher,
    StubPublisher,
    WebhookPublisher,
    make_publisher,
)
