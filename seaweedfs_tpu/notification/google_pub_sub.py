"""Google Pub/Sub publisher over the REST API (no SDK).

Reference weed/notification/google_pub_sub/google_pub_sub.go (the
official cloud client): publish each filer metadata event to a topic
with the path in the `key` attribute. This build talks to the same
surface from scratch:

  * service-account auth: the OAuth2 JWT-bearer grant
    (RFC 7523) — a JWT over the SA's client_email/scope, signed
    RS256 with the SA's private key, exchanged at token_uri for a
    bearer token (cached until ~expiry);
  * RS256 itself is implemented here: minimal DER/ASN.1 parsing of
    the PKCS#8/PKCS#1 private key and EMSA-PKCS1-v1_5 + SHA-256 with
    plain modular exponentiation (python ints are fine at this rate:
    one signature per ~55-minute token refresh);
  * publish: POST v1/projects/{p}/topics/{t}:publish with base64
    message data + attributes {"key": <path>}, like the reference.

`endpoint`/`token_uri` overrides exist so the in-process fake in
tests/test_notification.py (which VERIFIES the RSA signature with the
key's public half) can stand in for the real service — the same
treatment every external protocol gets here (kafka/SQS/mysql/redis).
"""

from __future__ import annotations

import base64
import hashlib
import json
import time
from typing import List, Optional, Tuple

from .queues import Publisher, _post_with_retries, register

# -- minimal DER (ASN.1) reader ---------------------------------------------


def _der_read(buf: bytes, pos: int) -> Tuple[int, bytes, int]:
    """One TLV: returns (tag, value, next_pos)."""
    tag = buf[pos]
    pos += 1
    first = buf[pos]
    pos += 1
    if first & 0x80:
        nlen = first & 0x7F
        length = int.from_bytes(buf[pos:pos + nlen], "big")
        pos += nlen
    else:
        length = first
    return tag, buf[pos:pos + length], pos + length


def _der_ints(seq: bytes, count: int) -> List[int]:
    out, pos = [], 0
    while len(out) < count and pos < len(seq):
        tag, val, pos = _der_read(seq, pos)
        if tag != 0x02:
            raise ValueError(f"expected DER INTEGER, got tag {tag:#x}")
        out.append(int.from_bytes(val, "big"))
    if len(out) < count:
        raise ValueError("truncated RSA key")
    return out


def _pem_body(pem: str, kinds) -> Tuple[str, bytes]:
    for kind in kinds:
        begin, end = f"-----BEGIN {kind}-----", f"-----END {kind}-----"
        if begin in pem:
            body = pem.split(begin, 1)[1].split(end, 1)[0]
            return kind, base64.b64decode("".join(body.split()))
    raise ValueError(f"no {'/'.join(kinds)} block in PEM")


class RsaPrivateKey:
    """n, e, d from a PKCS#8 ("PRIVATE KEY", what Google issues) or
    PKCS#1 ("RSA PRIVATE KEY") PEM."""

    def __init__(self, n: int, e: int, d: int):
        self.n, self.e, self.d = n, e, d
        self.size = (n.bit_length() + 7) // 8

    @classmethod
    def from_pem(cls, pem: str) -> "RsaPrivateKey":
        kind, der = _pem_body(pem, ("PRIVATE KEY", "RSA PRIVATE KEY"))
        tag, seq, _ = _der_read(der, 0)
        if tag != 0x30:
            raise ValueError("bad DER: outer SEQUENCE missing")
        if kind == "PRIVATE KEY":
            # PKCS#8: version, AlgorithmIdentifier, OCTET STRING(PKCS#1)
            pos = 0
            _, _version, pos = _der_read(seq, pos)
            _, _alg, pos = _der_read(seq, pos)
            tag, inner, pos = _der_read(seq, pos)
            if tag != 0x04:
                raise ValueError("bad PKCS#8: key OCTET STRING missing")
            tag, seq, _ = _der_read(inner, 0)
            if tag != 0x30:
                raise ValueError("bad PKCS#1 inside PKCS#8")
        # PKCS#1 RSAPrivateKey: version, n, e, d, ...
        version, n, e, d = _der_ints(seq, 4)
        return cls(n, e, d)


# SHA-256 DigestInfo prefix (RFC 8017 §9.2 note 1)
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def rs256_sign(key: RsaPrivateKey, data: bytes) -> bytes:
    """RSASSA-PKCS1-v1_5 with SHA-256."""
    digest = hashlib.sha256(data).digest()
    t = _SHA256_PREFIX + digest
    ps = b"\xff" * (key.size - len(t) - 3)
    em = int.from_bytes(b"\x00\x01" + ps + b"\x00" + t, "big")
    return pow(em, key.d, key.n).to_bytes(key.size, "big")


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


@register
class GooglePubSubPublisher(Publisher):
    """`notification.toml [notification.google_pub_sub]` analog:
    google_application_credentials (SA json path), project_id, topic;
    endpoint/token_uri overrides for tests/self-hosted emulators."""

    name = "google_pub_sub"

    SCOPE = "https://www.googleapis.com/auth/pubsub"

    def initialize(self, google_application_credentials: str = "",
                   project_id: str = "", topic: str = "seaweedfs_filer",
                   endpoint: str = "https://pubsub.googleapis.com",
                   token_uri: str = "", timeout: float = 10.0,
                   retries: int = 3, **options):
        if not google_application_credentials:
            raise ValueError(
                "google_pub_sub needs google_application_credentials "
                "(service-account json path)")
        with open(google_application_credentials) as f:
            sa = json.load(f)
        self._email = sa["client_email"]
        self._key = RsaPrivateKey.from_pem(sa["private_key"])
        self._token_uri = token_uri or sa.get(
            "token_uri", "https://oauth2.googleapis.com/token")
        self.project_id = project_id or sa.get("project_id", "")
        if not self.project_id:
            raise ValueError("google_pub_sub needs a project_id")
        self.topic = topic
        self.endpoint = endpoint.rstrip("/")
        self.timeout = float(timeout)
        self.retries = max(1, int(retries))
        self._token: Optional[str] = None
        self._token_exp = 0.0

    # -- oauth2 jwt-bearer grant (RFC 7523) --------------------------------

    def _jwt_assertion(self, now: float) -> str:
        header = _b64url(json.dumps(
            {"alg": "RS256", "typ": "JWT"}).encode())
        claims = _b64url(json.dumps({
            "iss": self._email, "scope": self.SCOPE,
            "aud": self._token_uri,
            "iat": int(now), "exp": int(now) + 3600}).encode())
        signing_input = f"{header}.{claims}".encode()
        sig = _b64url(rs256_sign(self._key, signing_input))
        return f"{header}.{claims}.{sig}"

    def _bearer(self) -> str:
        now = time.time()
        if self._token and now < self._token_exp - 300:
            return self._token
        from urllib.parse import urlencode
        body = urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": self._jwt_assertion(now)}).encode()
        # the token endpoint gets the same centralized retry
        # discipline as every publisher POST: a blip at the
        # ~55-minute refresh boundary must not drop the event
        raw = _post_with_retries(
            self._token_uri, body,
            {"Content-Type": "application/x-www-form-urlencoded"},
            self.timeout, self.retries, "google_pub_sub token grant")
        tok = json.loads(raw)
        self._token = tok["access_token"]
        self._token_exp = now + float(tok.get("expires_in", 3600))
        return self._token

    # -- publish ------------------------------------------------------------

    def send(self, key: str, event: dict) -> None:
        body = json.dumps({"messages": [{
            "data": base64.b64encode(
                json.dumps(event).encode()).decode(),
            "attributes": {"key": key},
        }]}).encode()
        url = (f"{self.endpoint}/v1/projects/{self.project_id}"
               f"/topics/{self.topic}:publish")
        try:
            _post_with_retries(
                url, body,
                {"Content-Type": "application/json",
                 "Authorization": f"Bearer {self._bearer()}"},
                self.timeout, self.retries, "google_pub_sub")
        except RuntimeError as e:
            # a 401 with ~55 minutes left on the cached token means the
            # server revoked it (key rotation, emulator restart):
            # re-auth once instead of dropping every event until local
            # expiry (the reference's google-auth client refreshes on
            # 401 the same way)
            from ..server.http_util import HttpError
            cause = e.__cause__
            if not (isinstance(cause, HttpError)
                    and cause.status == 401):
                raise
            self._token = None
            _post_with_retries(
                url, body,
                {"Content-Type": "application/json",
                 "Authorization": f"Bearer {self._bearer()}"},
                self.timeout, self.retries, "google_pub_sub")
