"""Offline volume tools — backup, export, fix, compact.

Reference weed/command/{backup,export,fix,compact}.go: `backup` keeps an
incremental local copy of a live volume (full pull on first run or after
a remote compaction, raw record tail afterwards); `export` dumps live
needles to a tar; `fix` rebuilds the .idx from a .dat scan; `compact`
force-vacuums a local volume.
"""

from __future__ import annotations

import io
import os
import tarfile
from typing import Optional

from ..client import operation as op
from ..server.http_util import get_json, http_call, http_download
from ..storage import volume_backup
from ..storage.needle_map import walk_index_file
from ..storage.types import TOMBSTONE_FILE_SIZE
from ..storage.volume import Volume, VolumeError, volume_file_prefix

TAIL_PAGE_BYTES = volume_backup.DEFAULT_TAIL_PAGE_BYTES


def backup_volume(master_url: str, vid: int, dirname: str,
                  collection: str = "") -> dict:
    """Pull/refresh a local copy of volume vid from its live server."""
    locations = op.lookup(master_url, vid)
    if not locations:
        raise VolumeError(f"volume {vid} has no locations")
    src = locations[0]
    status = get_json(f"http://{src}/admin/volume/sync_status?volume={vid}")
    prefix = volume_file_prefix(dirname, collection, vid)
    dat_path, idx_path = prefix + ".dat", prefix + ".idx"
    os.makedirs(dirname, exist_ok=True)
    basename = os.path.basename(dat_path)

    mode = "incremental"
    if os.path.exists(dat_path) and os.path.exists(idx_path):
        local = Volume(dirname, collection, vid)
        try:
            revision = local.super_block.compaction_revision
            if revision != status["compact_revision"] or \
                    local.size() > status["tail_offset"]:
                mode = "full"          # remote was compacted: resync
            else:
                applied = 0
                since = volume_backup.last_append_at_ns(local)
                while True:            # record-aligned pages until dry
                    blob = http_call(
                        "GET",
                        f"http://{src}/admin/volume/tail?volume={vid}"
                        f"&since_ns={since}"
                        f"&max_bytes={TAIL_PAGE_BYTES}")
                    got, new_since = volume_backup.append_raw_records(
                        local, blob, since)
                    applied += got
                    # done only when the cursor stops moving — pages are
                    # record-aligned so they are almost never exactly
                    # TAIL_PAGE_BYTES long and a length test would stop
                    # after one page
                    if not blob or new_since == since:
                        break
                    since = new_since
                return {"volume": vid, "mode": mode, "applied": applied,
                        "size": local.size()}
        finally:
            local.close()
    else:
        mode = "full"

    if mode == "full":
        http_download(f"http://{src}/admin/file?name={basename}",
                      dat_path)
        volume_backup.rebuild_index(dat_path, idx_path)
    local = Volume(dirname, collection, vid)
    try:
        return {"volume": vid, "mode": mode,
                "applied": local.file_count(), "size": local.size()}
    finally:
        local.close()


def export_volume(dirname: str, vid: int, collection: str = "",
                  tar_path: Optional[str] = None) -> list:
    """Dump live needles; returns [(fid, name, size)] and optionally
    writes a tar whose members carry needle names (fid fallback)."""
    v = Volume(dirname, collection, vid)
    listed = []
    tar = tarfile.open(tar_path, "w") if tar_path else None
    snapshot = None
    try:
        from ..storage.compact_map import snapshot_live_items
        snapshot = snapshot_live_items(v.nm, by_offset=True)
        for nid, nv in snapshot:
            if nv.size == TOMBSTONE_FILE_SIZE or nv.offset == 0:
                continue
            from ..storage.needle import Needle
            blob = v._read_blob(nv.offset, nv.size)
            n = Needle.from_bytes(blob, v.version, expected_size=nv.size)
            fid = f"{vid},{n.fid_suffix()}"
            name = n.name.decode("utf-8", "replace") if n.has_name() \
                else fid.replace(",", "_")
            listed.append((fid, name, len(n.data)))
            if tar is not None:
                info = tarfile.TarInfo(name=name)
                info.size = len(n.data)
                if n.has_last_modified():
                    info.mtime = n.last_modified
                tar.addfile(info, io.BytesIO(n.data))
    finally:
        if tar is not None:
            tar.close()
        if snapshot is not None:
            snapshot.close()
        v.close()
    return listed


def fix_volume(dirname: str, vid: int, collection: str = "") -> int:
    """Rebuild the .idx from the .dat (reference weed/command/fix.go)."""
    prefix = volume_file_prefix(dirname, collection, vid)
    return volume_backup.rebuild_index(prefix + ".dat", prefix + ".idx")


def compact_volume(dirname: str, vid: int, collection: str = "",
                   method: int = 1) -> dict:
    """Force-vacuum a local volume in place. method 0 scans the .dat
    (reference Compact / `weed compact -method 0`), method 1 copies by
    the index (reference Compact2 / -method 1, the default the live
    vacuum uses)."""
    v = Volume(dirname, collection, vid)
    try:
        before = v.size()
        if method == 0:
            v.compact_scan()
        else:
            v.compact()
        v.commit_compact()
        return {"volume": vid, "before": before, "after": v.size(),
                "method": method}
    finally:
        v.close()


def see_idx(idx_path: str, offset_width: int = 4, out=None,
            limit: int = 0) -> int:
    """Print every .idx record as `key offset size` (reference
    unmaintained/see_idx/see_idx.go). Returns the record count."""
    import sys as _sys
    out = out or _sys.stdout
    count = 0
    for nid, offset, size in walk_index_file(idx_path, offset_width):
        print(f"key {nid} offset {offset} size {size}"
              + (" (tombstone)" if size == TOMBSTONE_FILE_SIZE else ""),
              file=out)
        count += 1
        if limit and count >= limit:
            break
    return count


def see_dat(dat_path: str, out=None, limit: int = 0) -> int:
    """Scan a .dat and print each needle record (reference
    unmaintained/see_dat/see_dat.go): offset, id, cookie, sizes, name,
    mime. A size-0 record is a delete marker — that is how
    delete_needle appends tombstones to the .dat (the 0xFFFFFFFF
    TOMBSTONE_FILE_SIZE value exists only in .idx records). Returns
    the needle count."""
    import sys as _sys

    from ..storage.needle import Needle
    from ..storage.super_block import SUPER_BLOCK_SIZE, SuperBlock

    out = out or _sys.stdout
    count = 0
    with open(dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
        print(f"superblock: version {sb.version} replication "
              f"{sb.replica_placement} ttl {sb.ttl} "
              f"compact_revision {sb.compaction_revision}", file=out)
        f.seek(0, 2)
        end = f.tell()

        def pread(offset, size):
            f.seek(offset)
            return f.read(size)

        # the record framing lives in volume_backup.walk_records (one
        # place), which also guards against a corrupt 0xFFFFFFFF size
        # that would otherwise leap the cursor past the file end
        tail = SUPER_BLOCK_SIZE  # where the walk stopped
        for n, pos, total in volume_backup.walk_records(
                pread, sb.version, SUPER_BLOCK_SIZE, end):
            tail = pos + total
            try:
                full = Needle.from_bytes(pread(pos, total), sb.version,
                                         expected_size=n.size)
                name = full.name.decode("utf-8", "replace") \
                    if full.has_name() else ""
                mime = full.mime.decode("utf-8", "replace") \
                    if full.has_mime() else ""
            except Exception:  # torn tail / corrupt record
                name = mime = ""
            print(f"offset {pos} id {n.id} cookie {n.cookie:08x} "
                  f"size {n.size}"
                  + (f" name {name!r}" if name else "")
                  + (f" mime {mime}" if mime else "")
                  + (" DELETED" if n.size == 0 else ""), file=out)
            count += 1
            if limit and count >= limit:
                break
        else:
            # a complete header with a truncated body at the tail is a
            # torn append — exactly what a forensic dump must surface
            if end - tail >= 16:
                t = Needle.parse_header(pread(tail, 16))
                print(f"offset {tail} id {t.id} cookie "
                      f"{t.cookie:08x} size {t.size} TORN "
                      f"({end - tail} bytes of record present)",
                      file=out)
    return count
