"""Offline volume tools — backup, export, fix, compact.

Reference weed/command/{backup,export,fix,compact}.go: `backup` keeps an
incremental local copy of a live volume (full pull on first run or after
a remote compaction, raw record tail afterwards); `export` dumps live
needles to a tar; `fix` rebuilds the .idx from a .dat scan; `compact`
force-vacuums a local volume.
"""

from __future__ import annotations

import io
import os
import tarfile
from typing import Optional

from ..client import operation as op
from ..server.http_util import get_json, http_call, http_download
from ..storage import volume_backup
from ..storage.types import TOMBSTONE_FILE_SIZE
from ..storage.volume import Volume, VolumeError, volume_file_prefix

TAIL_PAGE_BYTES = volume_backup.DEFAULT_TAIL_PAGE_BYTES


def backup_volume(master_url: str, vid: int, dirname: str,
                  collection: str = "") -> dict:
    """Pull/refresh a local copy of volume vid from its live server."""
    locations = op.lookup(master_url, vid)
    if not locations:
        raise VolumeError(f"volume {vid} has no locations")
    src = locations[0]
    status = get_json(f"http://{src}/admin/volume/sync_status?volume={vid}")
    prefix = volume_file_prefix(dirname, collection, vid)
    dat_path, idx_path = prefix + ".dat", prefix + ".idx"
    os.makedirs(dirname, exist_ok=True)
    basename = os.path.basename(dat_path)

    mode = "incremental"
    if os.path.exists(dat_path) and os.path.exists(idx_path):
        local = Volume(dirname, collection, vid)
        try:
            revision = local.super_block.compaction_revision
            if revision != status["compact_revision"] or \
                    local.size() > status["tail_offset"]:
                mode = "full"          # remote was compacted: resync
            else:
                applied = 0
                since = volume_backup.last_append_at_ns(local)
                while True:            # record-aligned pages until dry
                    blob = http_call(
                        "GET",
                        f"http://{src}/admin/volume/tail?volume={vid}"
                        f"&since_ns={since}"
                        f"&max_bytes={TAIL_PAGE_BYTES}")
                    got, new_since = volume_backup.append_raw_records(
                        local, blob, since)
                    applied += got
                    # done only when the cursor stops moving — pages are
                    # record-aligned so they are almost never exactly
                    # TAIL_PAGE_BYTES long and a length test would stop
                    # after one page
                    if not blob or new_since == since:
                        break
                    since = new_since
                return {"volume": vid, "mode": mode, "applied": applied,
                        "size": local.size()}
        finally:
            local.close()
    else:
        mode = "full"

    if mode == "full":
        http_download(f"http://{src}/admin/file?name={basename}",
                      dat_path)
        volume_backup.rebuild_index(dat_path, idx_path)
    local = Volume(dirname, collection, vid)
    try:
        return {"volume": vid, "mode": mode,
                "applied": local.file_count(), "size": local.size()}
    finally:
        local.close()


def export_volume(dirname: str, vid: int, collection: str = "",
                  tar_path: Optional[str] = None) -> list:
    """Dump live needles; returns [(fid, name, size)] and optionally
    writes a tar whose members carry needle names (fid fallback)."""
    v = Volume(dirname, collection, vid)
    listed = []
    tar = tarfile.open(tar_path, "w") if tar_path else None
    try:
        for nid, nv in sorted(v.nm.items(), key=lambda kv: kv[1].offset):
            if nv.size == TOMBSTONE_FILE_SIZE or nv.offset == 0:
                continue
            from ..storage.needle import Needle
            blob = v._read_blob(nv.offset, nv.size)
            n = Needle.from_bytes(blob, v.version, expected_size=nv.size)
            fid = f"{vid},{n.fid_suffix()}"
            name = n.name.decode("utf-8", "replace") if n.has_name() \
                else fid.replace(",", "_")
            listed.append((fid, name, len(n.data)))
            if tar is not None:
                info = tarfile.TarInfo(name=name)
                info.size = len(n.data)
                if n.has_last_modified():
                    info.mtime = n.last_modified
                tar.addfile(info, io.BytesIO(n.data))
    finally:
        if tar is not None:
            tar.close()
        v.close()
    return listed


def fix_volume(dirname: str, vid: int, collection: str = "") -> int:
    """Rebuild the .idx from the .dat (reference weed/command/fix.go)."""
    prefix = volume_file_prefix(dirname, collection, vid)
    return volume_backup.rebuild_index(prefix + ".dat", prefix + ".idx")


def compact_volume(dirname: str, vid: int, collection: str = "") -> dict:
    """Force-vacuum a local volume in place."""
    v = Volume(dirname, collection, vid)
    try:
        before = v.size()
        v.compact()
        v.commit_compact()
        return {"volume": vid, "before": before, "after": v.size()}
    finally:
        v.close()
