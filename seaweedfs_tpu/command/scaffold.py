"""`weed-tpu scaffold` — print commented example configs.

Reference weed/command/scaffold.go prints the five TOML templates
(security/master/filer/notification/replication); this build's configs
are JSON files passed via flags, so the scaffold prints annotated JSON
examples for each.
"""

SCAFFOLDS = {
    "tier": """\
// volume/server -tierConfig: remote backends for volume.tier.upload
// (reference master.toml [storage.backend.<kind>.<id>])
{
  "s3": {
    "default": {
      "endpoint": "http://s3.example.com:8333",
      "bucket": "volume-tier",
      "access_key": "ACCESSKEY",
      "secret_key": "SECRETKEY",
      "region": "us-east-1"
    }
  },
  "dir": {
    "cold": {"path": "/mnt/cold-disk/tier"}
  }
}
""",
    "s3": """\
// s3 / filer -s3Config: IAM identities and per-identity actions
// (reference s3 config shape, weed/s3api/auth_credentials.go)
{
  "identities": [
    {
      "name": "admin",
      "credentials": [
        {"accessKey": "ACCESSKEY", "secretKey": "SECRETKEY"}
      ],
      "actions": ["Admin", "Read", "Write", "List", "Tagging"]
    },
    {
      "name": "readonly",
      "credentials": [
        {"accessKey": "ROKEY", "secretKey": "ROSECRET"}
      ],
      "actions": ["Read", "List"]
    }
  ]
}
""",
    "replication": """\
// filer.replicate -config: follow one filer's events into a sink
// (reference replication.toml [source.filer] + [sink.*])
{
  "source": {
    "filer": "127.0.0.1:8888",
    "master": "127.0.0.1:9333",
    "path": "/buckets"
  },
// sink alternatives: "type": "filer" (below); "type": "s3" with
// endpoint/bucket/access_key/secret_key/directory; "gcs"/"b2" (same
// keys over their S3-interop APIs); "azure" with
// account/account_key/container/directory (SharedKey Blob REST)
  "sink": {
    "type": "filer",
    "filer_url": "remote-filer:8888",
    "target_dir": "/backup"
  }
}
""",
    "security": """\
// security.toml — searched in ., ~/.seaweedfs_tpu, /etc/seaweedfs_tpu
// (reference util/config.go tiers); every key also overridable via
// WEED_* env vars, e.g. WEED_JWT_SIGNING_KEY=secret.
// Equivalent flags: -jwtKey, -tlsCert/-tlsKey/-tlsCa, -whiteList.
//
//   [jwt.signing]
//   key = "write-token-secret"      # JWT-protected writes
//
//   [https]                         # TLS on every surface
//   cert = "/etc/seaweedfs_tpu/cluster.crt"
//   key  = "/etc/seaweedfs_tpu/cluster.key"
//   ca   = "/etc/seaweedfs_tpu/ca.crt"
{}
""",
    "notification": """\
// filer notification publisher (reference notification.toml):
// configured programmatically via
// seaweedfs_tpu.notification.make_publisher(name, **options);
// built-ins:
//   "log"      print events
//   "memory"   in-process pub-sub (tests/replicator)
//   "webhook"  POST JSON to any HTTP endpoint, options:
//              url, timeout, retries, hmac_key (X-Seaweed-Signature)
//   "kafka"    classic-protocol producer (no SDK), options:
//              hosts ("h1:9092,h2:9092"), topic, timeout, retries
//   "aws_sqs"  SendMessage via the SQS query API (SigV4), options:
//              queue_url, access_key, secret_key, region
//   "google_pub_sub"  REST publish with OAuth2 JWT-bearer auth
//              (no SDK), options: google_application_credentials
//              (service-account json), project_id, topic,
//              endpoint/token_uri overrides for emulators
//   "gocdk_pub_sub"  URL-dispatching meta-publisher: one topic_url
//              whose scheme picks the broker (kafka://topic,
//              awssqs://sqs.<region>.amazonaws.com/<acct>/<queue>,
//              gcppubsub://projects/<p>/topics/<t>, mem://,
//              http(s):// webhook); remaining options pass through
{}
""",
    "filer": """\
// filer store selection (reference filer.toml):
//   -store memory                     volatile, tests
//   -store sqlite  -db ./filer.db     single-file embedded store
//   -store sharded -db ./filer_meta \\
//          -storeShards 8             leveldb2-style sharded store:
//                                     md5(dir) routes to one of N
//                                     sqlite shards; count is sticky
//   -store redis   -redisAddr host:6379 [-redisPassword ..]
//          [-redisDb N]               external store over a built-in
//                                     RESP client (Redis/KeyDB/Valkey)
//   -store mysql   -mysqlAddr host:3306 -mysqlUser .. -mysqlPassword ..
//          [-mysqlDatabase seaweedfs]  built-in MySQL wire client
//                                      (MySQL/MariaDB/Percona/Vitess)
//   -store postgres -postgresAddr host:5432 -postgresUser ..
//          -postgresPassword .. [-postgresDatabase seaweedfs]
//                                      built-in protocol-3.0 client
//                                      with SCRAM-SHA-256 auth
//   -store cassandra -cassandraAddr host:9042 [-cassandraUser ..
//          -cassandraPassword ..] [-cassandraKeyspace seaweedfs]
//                                      built-in CQL v4 client
//                                      (directory-partitioned table)
//   -store etcd -etcdAddr host:2379 [-etcdUser .. -etcdPassword ..]
//                                      built-in etcd v3 JSON-gateway
//                                      client (bearer auth, prefix
//                                      ranges over <dir>\\0<name> keys)
{}
""",
    "master": """\
# master.toml — searched in ., ~/.seaweedfs_tpu, /etc/seaweedfs_tpu
# (reference scaffold.go MASTER_TOML_EXAMPLE); keys also overridable
# via WEED_MASTER_* env vars. Flags win over config when both are set.

[master.maintenance]
# shell command lines cron'd on the leader, one per line
# (equivalent flag: -maintenanceScripts, ';'-separated)
scripts = \"\"\"
  ec.rebuild
  volume.balance
  volume.vacuum -garbageThreshold 0.3
\"\"\"
sleep_minutes = 17            # -maintenanceIntervalSeconds / 60

[master.filer]
# filer the maintenance shell's fs.* commands talk to
default_filer_url = "http://localhost:8888/"

[master.sequencer]
type = "memory"               # memory | etcd  (-sequencer)
# first URL is used; plain host:port works too  (-sequencerEtcd)
sequencer_etcd_urls = "http://127.0.0.1:2379"

# tier destinations for volume.tier.upload (same shape as the
# reference master.toml [storage.backend]; also via -tierConfig JSON)
[storage.backend.s3.default]
enabled = false
aws_access_key_id = ""
aws_secret_access_key = ""
region = "us-east-1"
bucket = "volume-tier"
endpoint = "http://s3.example.com:8333"

# volumes grown per growth event, by replica copy count
# (reference master.toml [master.volume_growth])
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1
""",
}


def print_scaffold(name: str) -> str:
    if name not in SCAFFOLDS:
        raise SystemExit(
            f"unknown config {name!r}; have {sorted(SCAFFOLDS)}")
    return SCAFFOLDS[name]
