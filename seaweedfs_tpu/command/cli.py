"""The `weed`-style CLI (reference weed/weed.go + weed/command/).

Usage: python -m seaweedfs_tpu.command.cli <command> [flags]
Commands: master, volume, server, shell, benchmark, upload, download,
          version
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


def _security_cfg(args):
    """security.toml/json + WEED_* env, loaded once per process and
    memoized on args (reference three-tier config, util/config.go +
    scaffold.go)."""
    if not hasattr(args, "_security_cfg_cache"):
        from ..util.config import load_config
        args._security_cfg_cache = load_config("security")
    return args._security_cfg_cache


def _apply_security_config(args):
    """Flag -> config -> env fallback for the JWT key."""
    from ..util.config import config_get
    if not getattr(args, "jwtKey", ""):
        args.jwtKey = config_get(_security_cfg(args),
                                 "jwt.signing.key", "") or ""


def _apply_tls_config(args):
    """TLS material (reference security/tls.go) applies to EVERY
    command: servers present cert/key, and pure clients (upload,
    download, shell, benchmark) still need the client context to reach
    a TLS cluster."""
    from ..util.config import config_get
    cfg = _security_cfg(args)
    cert = getattr(args, "tlsCert", "") or \
        config_get(cfg, "https.cert", "") or ""
    key = getattr(args, "tlsKey", "") or \
        config_get(cfg, "https.key", "") or ""
    ca = getattr(args, "tlsCa", "") or \
        config_get(cfg, "https.ca", "") or ""
    mutual = getattr(args, "tlsMutual", False) or \
        str(config_get(cfg, "https.mutual", "")).lower() in ("true", "1")
    if cert or ca:
        from ..server.http_util import configure_tls
        configure_tls(cert, key, ca, mutual=mutual)


def _apply_master_config(args) -> dict:
    """master.toml / WEED_MASTER_* (reference scaffold.go
    MASTER_TOML_EXAMPLE + master_server.go:187-232): config fills
    whatever the flags left at their defaults — an explicit flag
    always wins. Returns extra MasterServer kwargs that have no flag
    spelling (growth counts, the maintenance shell's filer)."""
    from ..util.config import config_get, load_config
    cfg = load_config("master")
    scripts = str(config_get(cfg, "master.maintenance.scripts", "")
                  or "")
    if scripts.strip() and not getattr(args, "maintenanceScripts", ""):
        # reference scripts are newline-separated; the flag is ';'
        args.maintenanceScripts = ";".join(
            ln.strip() for ln in scripts.splitlines() if ln.strip())
    sleep_m = config_get(cfg, "master.maintenance.sleep_minutes", None)
    if sleep_m is not None and \
            getattr(args, "maintenanceIntervalSeconds", 17 * 60) \
            == 17 * 60:
        args.maintenanceIntervalSeconds = float(sleep_m) * 60
    if str(config_get(cfg, "master.sequencer.type", "")) == "etcd" \
            and getattr(args, "sequencer", "auto") == "auto":
        args.sequencer = "etcd"
        urls = str(config_get(
            cfg, "master.sequencer.sequencer_etcd_urls", "") or "")
        if urls and getattr(args, "sequencerEtcd", "") \
                in ("", "127.0.0.1:2379"):
            from urllib.parse import urlparse
            first = urls.split(",")[0].strip()
            p = urlparse(first if "//" in first else "//" + first)
            if p.hostname:
                args.sequencerEtcd = f"{p.hostname}:{p.port or 2379}"
    growth = {}
    for copies, key in ((1, "copy_1"), (2, "copy_2"), (3, "copy_3"),
                        ("other", "copy_other")):
        val = config_get(cfg, f"master.volume_growth.{key}", None)
        if val is not None:
            growth[copies] = int(val)
    # [storage.backend.<kind>.<id>] tier destinations (flattened keys
    # back to the nested configure_backends shape; reference TOML
    # credential names mapped to the client's)
    nested = {}
    for key, val in cfg.items():
        parts = key.split(".")
        if parts[:2] == ["storage", "backend"] and len(parts) >= 5:
            # >5 parts happen via WEED_* env overrides, whose underscores
            # all became dots (aws_access_key_id -> aws.access.key.id):
            # everything past the 4th segment is one underscore-joined
            # param name
            _, _, kind, bid = parts[:4]
            param = "_".join(parts[4:])
            nested.setdefault(kind, {}).setdefault(bid, {})[param] = val
    backends = {}
    rename = {"aws_access_key_id": "access_key",
              "aws_secret_access_key": "secret_key"}
    for kind, ids in nested.items():
        for bid, params in ids.items():
            enabled = params.pop("enabled", False)
            if str(enabled).lower() not in ("true", "1"):
                continue
            backends.setdefault(kind, {})[bid] = {
                rename.get(k, k): v for k, v in params.items()}
    if backends:
        from ..storage.backend import configure_backends
        configure_backends(backends)
    filer_url = str(config_get(cfg, "master.filer.default_filer_url",
                               "") or "")
    maintenance_filer = ""
    if filer_url:
        from urllib.parse import urlparse
        p = urlparse(filer_url if "//" in filer_url
                     else "//" + filer_url)
        if p.hostname:
            maintenance_filer = f"{p.hostname}:{p.port or 8888}"
    return {"growth_counts": growth or None,
            "maintenance_filer_url": maintenance_filer}


def _build_sequencer(args):
    """-sequencer etcd -> an EtcdSequencer, else None (in-memory/raft).
    Shared by `weed master` and `weed server` so [master.sequencer]
    config is honored in both modes."""
    if getattr(args, "sequencer", "auto") != "etcd":
        return None
    # reference -master.sequencer etcd (weed/sequence/
    # etcd_sequencer.go): file keys granted by CAS blocks on an
    # external etcd shared by every master
    from ..topology.topology import EtcdSequencer
    meta_dir = getattr(args, "mdir", "")
    if not meta_dir:
        # sequencer.dat must never silently vanish (same hazard as
        # raft persistence, master.py raft_dir fallback): without
        # it a wiped etcd + restart re-mints live file ids. In
        # `weed server` mode (no -mdir flag) anchor it to this
        # cluster's own data dir — a fixed shared /tmp path would be
        # overwritten by any other cluster on the host
        data_dirs = getattr(args, "dir", "")
        if data_dirs:
            meta_dir = os.path.join(data_dirs.split(",")[0].strip(),
                                    "master-meta")
        else:
            import tempfile
            meta_dir = os.path.join(tempfile.gettempdir(),
                                    "weed-tpu-raft")
        os.makedirs(meta_dir, exist_ok=True)
    endpoint = getattr(args, "sequencerEtcd", "") or "127.0.0.1:2379"
    sequencer = EtcdSequencer(
        endpoint,
        user=getattr(args, "sequencerEtcdUser", ""),
        password=getattr(args, "sequencerEtcdPassword", ""),
        meta_dir=meta_dir)
    print(f"sequencer: etcd at {endpoint} (ceiling file in {meta_dir})")
    return sequencer


def cmd_master(args):
    _apply_security_config(args)
    master_cfg = _apply_master_config(args)
    from ..server.master import MasterServer
    sequencer = _build_sequencer(args)
    m = MasterServer(port=args.port, host=args.ip,
                     sequencer=sequencer,
                     volume_size_limit_mb=args.volumeSizeLimitMB,
                     default_replication=args.defaultReplication,
                     pulse_seconds=args.pulseSeconds,
                     jwt_signing_key=args.jwtKey,
                     peers=args.peers, raft_dir=args.mdir,
                     maintenance_scripts=args.maintenanceScripts,
                     maintenance_interval=args.maintenanceIntervalSeconds,
                     vacuum_interval=args.vacuumIntervalSeconds,
                     garbage_threshold=args.garbageThreshold,
                     whitelist=[w for w in args.whiteList.split(",")
                                if w],
                     metrics_address=args.metricsAddress,
                     metrics_interval=args.metricsInterval,
                     **master_cfg).start()
    print(f"master listening on {m.url}")
    _wait(m)


def _load_tier_config(path: str):
    if not path:
        return
    import json
    from ..storage.backend import configure_backends
    with open(path) as f:
        configure_backends(json.load(f))


def cmd_volume(args):
    _apply_security_config(args)
    if getattr(args, "meshCoordinator", ""):
        # join the multi-host device mesh BEFORE any jax work: the
        # -ec.backend mesh/tpu codecs then compile over the global
        # device list, collectives riding ICI intra-host and DCN
        # across hosts (SURVEY §5.8; parallel/multihost.py)
        from ..parallel import init_distributed
        init_distributed(args.meshCoordinator, args.meshProcesses,
                         args.meshProcessId)
    from ..server.volume_server import VolumeServer
    _load_tier_config(args.tierConfig)
    dirs = args.dir.split(",")
    maxes = [int(x) for x in args.max.split(",")] if args.max else None
    if maxes and len(maxes) == 1:
        maxes = maxes * len(dirs)
    vs = VolumeServer(port=args.port, host=args.ip, directories=dirs,
                      master_url=args.mserver, data_center=args.dataCenter,
                      rack=args.rack, max_volume_counts=maxes,
                      pulse_seconds=args.pulseSeconds,
                      ec_backend=args.ec_backend,
                      jwt_signing_key=args.jwtKey,
                      index_kind=args.index,
                      fast_port=args.fastPort,
                      public_url=args.publicUrl,
                      read_redirect=args.readRedirect == "true",
                      file_size_limit_mb=args.fileSizeLimitMB,
                      compaction_mbps=args.compactionMBps,
                      whitelist=[w for w in args.whiteList.split(",")
                                 if w]).start()
    print(f"volume server listening on {vs.url}, "
          f"heartbeating to {args.mserver}")
    if vs.fast_plane is not None:
        print(f"native read plane on {vs.fast_url}")
    prof = _maybe_profiler(args)
    _wait(vs)
    if prof:
        prof.stop()
        print(f"cpu profile (collapsed stacks) -> {args.cpuprofile}")


def cmd_server(args):
    """Combined master + volume (+ filer) in one process
    (reference `weed server`)."""
    _apply_security_config(args)
    master_cfg = _apply_master_config(args)
    from ..server.master import MasterServer
    from ..server.volume_server import VolumeServer
    _load_tier_config(getattr(args, "tierConfig", ""))
    m = MasterServer(port=args.masterPort, host=args.ip,
                     default_replication=args.defaultReplication,
                     jwt_signing_key=args.jwtKey,
                     sequencer=_build_sequencer(args),
                     maintenance_scripts=getattr(
                         args, "maintenanceScripts", ""),
                     maintenance_interval=getattr(
                         args, "maintenanceIntervalSeconds", 17 * 60),
                     **master_cfg).start()
    dirs = args.dir.split(",")
    maxes = [int(args.max)] * len(dirs)
    vs = VolumeServer(port=args.port, host=args.ip, directories=dirs,
                      master_url=m.url, data_center=args.dataCenter,
                      rack=args.rack, pulse_seconds=args.pulseSeconds,
                      max_volume_counts=maxes,
                      ec_backend=args.ec_backend,
                      fast_port=args.fastPort,
                      jwt_signing_key=args.jwtKey).start()
    print(f"master on {m.url}, volume server on {vs.url}")
    if vs.fast_plane is not None:
        print(f"native read plane on {vs.fast_url}")
    stoppables = [vs]
    if args.filer or args.s3 or args.webdav:
        from ..server.filer_server import FilerServer
        f = FilerServer(port=args.filerPort, host=args.ip,
                        master_url=m.url,
                        jwt_signing_key=args.jwtKey,
                        notify_publisher=_notification_publisher()).start()
        print(f"filer on {f.url}")
        if args.s3:
            s3 = _start_s3(f, args.s3Port, args.ip, args.s3Config)
            print(f"s3 gateway on {s3.url}")
            stoppables.append(s3)
        if args.webdav:
            from ..server.webdav_server import WebDavServer
            w = WebDavServer(f.filer, m.url, port=args.webdavPort,
                             host=args.ip).start()
            print(f"webdav on {w.url}")
            stoppables.append(w)
        stoppables.append(f)
    stoppables.append(m)
    prof = _maybe_profiler(args)
    _wait(*stoppables)
    if prof:
        prof.stop()
        print(f"cpu profile (collapsed stacks) -> {args.cpuprofile}")


def _start_s3(filer_server, port: int, host: str, config_path: str):
    import json as _json
    from ..s3 import Iam, S3ApiServer
    iam = Iam()
    if config_path:
        with open(config_path) as fh:
            iam = Iam.from_config(_json.load(fh))
    return S3ApiServer(filer_server.filer, filer_server.master_url,
                       port=port, host=host, iam=iam).start()


def _notification_publisher():
    """notification.toml/json from the config search path (plus WEED_*
    env) — the reference filer's notification.LoadConfiguration: the
    first `[notification.<backend>]` section with enabled=true becomes
    the filer's metadata-event publisher."""
    from ..notification.queues import publisher_from_config
    from ..util.config import load_config
    pub = publisher_from_config(load_config("notification"))
    if pub is not None:
        print(f"notification -> {pub.name}")
    return pub


def cmd_filer(args):
    _apply_security_config(args)
    from ..server.filer_server import FilerServer
    db = args.db
    if args.store == "sharded":
        # the sharded store wants a DIRECTORY of shard dbs; don't reuse
        # the sqlite single-file default as a directory name
        if db == "./filer.db":
            db = "./filer_meta"
        store_options = {"path": db, "shards": args.storeShards}
    elif args.store == "sqlite":
        store_options = {"path": db}
    elif args.store == "redis":
        store_options = {"addr": args.redisAddr,
                         "password": args.redisPassword,
                         "db": args.redisDb}
    elif args.store == "mysql":
        store_options = {"addr": args.mysqlAddr,
                         "user": args.mysqlUser,
                         "password": args.mysqlPassword,
                         "database": args.mysqlDatabase}
    elif args.store == "postgres":
        store_options = {"addr": args.postgresAddr,
                         "user": args.postgresUser,
                         "password": args.postgresPassword,
                         "database": args.postgresDatabase}
    elif args.store == "cassandra":
        store_options = {"addr": args.cassandraAddr,
                         "user": args.cassandraUser,
                         "password": args.cassandraPassword,
                         "keyspace": args.cassandraKeyspace}
    elif args.store == "etcd":
        store_options = {"addr": args.etcdAddr,
                         "user": args.etcdUser,
                         "password": args.etcdPassword}
    else:
        store_options = {}
    f = FilerServer(port=args.port, host=args.ip, master_url=args.master,
                    store=args.store, store_options=store_options,
                    collection=args.collection,
                    replication=args.defaultReplicaPlacement,
                    chunk_size=args.maxMB << 20,
                    jwt_signing_key=args.jwtKey,
                    cipher=args.encryptVolumeData,
                    compress=args.compress,
                    notify_publisher=_notification_publisher()).start()
    print(f"filer listening on {f.url}, master {args.master}")
    if args.s3:
        s3 = _start_s3(f, args.s3Port, args.ip, args.s3Config)
        print(f"s3 gateway on {s3.url}")
    _wait(f)


def cmd_s3(args):
    """Standalone S3 gateway against a remote filer
    (reference weed/command/s3.go)."""
    import json as _json
    from ..filer.filer_client import FilerClient
    from ..s3 import Iam, S3ApiServer
    iam = Iam()
    if args.config:
        with open(args.config) as fh:
            iam = Iam.from_config(_json.load(fh))
    client = FilerClient(args.filer)
    master = args.master or _filer_master(args.filer)
    s3 = S3ApiServer(client, master, port=args.port, host=args.ip,
                     iam=iam).start()
    print(f"s3 gateway on {s3.url}, filer {args.filer}")
    _wait(s3)


def cmd_webdav(args):
    """WebDAV gateway (reference weed/command/webdav.go)."""
    from ..filer.filer_client import FilerClient
    from ..server.webdav_server import WebDavServer
    client = FilerClient(args.filer)
    master = args.master or _filer_master(args.filer)
    w = WebDavServer(client, master, port=args.port, host=args.ip,
                     collection=args.collection,
                     chunk_size=args.maxMB << 20).start()
    print(f"webdav on {w.url}, filer {args.filer}")
    _wait(w)


def _filer_master(filer_url: str) -> str:
    """Discover the master from the filer's status endpoint."""
    from ..server.http_util import get_json
    url = filer_url if filer_url.startswith("http") \
        else "http://" + filer_url
    return get_json(f"{url}/filer/status").get("master", "")


def cmd_shell(args):
    import seaweedfs_tpu.shell  # noqa: F401  (registers all commands)
    from ..shell.command_env import CommandEnv, run_command
    from ..shell.command_env import split_script
    env = CommandEnv(args.master, filer_url=args.filer)
    if args.c:
        # ';'-separated command lines (quote-aware), same convention as
        # the master's -maintenanceScripts cron; 'exit' stops the script
        for line in split_script(args.c):
            if not run_command(env, line):
                break
        return
    print("seaweedfs_tpu shell; 'help' lists commands, 'exit' quits")
    while True:
        try:
            line = input("> ")
        except (EOFError, KeyboardInterrupt):
            break
        if not run_command(env, line):
            break


def _maybe_profiler(args):
    """Start the all-thread stack sampler when -cpuprofile is set
    (reference -cpuprofile, weed/command/volume.go:71)."""
    path = getattr(args, "cpuprofile", "")
    if not path:
        return None
    from ..util.profiling import SamplingProfiler
    return SamplingProfiler(path).start()


def cmd_benchmark(args):
    from .benchmark import run_benchmark, run_native_benchmark
    prof = _maybe_profiler(args)
    try:
        if args.native:
            run_native_benchmark(args.master, file_size=args.size,
                                 concurrency=args.c,
                                 collection=args.collection,
                                 seconds=args.seconds, pool=args.pool,
                                 assign_batch=args.assignBatch)
        else:
            run_benchmark(args.master, num_files=args.n,
                          file_size=args.size,
                          concurrency=args.c, collection=args.collection,
                          assign_batch=args.assignBatch)
    finally:
        if prof:
            prof.stop()
            print(f"cpu profile (collapsed stacks) -> {args.cpuprofile}")


def cmd_upload(args):
    from ..client import operation as op
    max_bytes = args.maxMB << 20
    for path in args.files:
        with open(path, "rb") as f:
            data = f.read()
        if max_bytes and len(data) > max_bytes:
            from ..client.chunked import submit_chunked
            fid = submit_chunked(args.master, data, filename=path,
                                 collection=args.collection,
                                 replication=args.replication,
                                 ttl=args.ttl, chunk_size=max_bytes)
        else:
            fid = op.upload_data(args.master, data, filename=path,
                                 collection=args.collection,
                                 replication=args.replication,
                                 ttl=args.ttl)
        print(f"{path} -> {fid}")


def cmd_download(args):
    import os

    from ..client import operation as op
    os.makedirs(args.dir, exist_ok=True)
    for fid in args.fids:
        data, name = op.read_file_named(args.master, fid)
        # basename only: the stored name is uploader-controlled and
        # must never traverse outside -dir (or crash on subdirs)
        name = os.path.basename(name.replace("\\", "/"))
        out = os.path.join(args.dir, name or fid.replace(",", "_"))
        with open(out, "wb") as f:
            f.write(data)
        print(f"{fid} -> {out} ({len(data)} bytes)")


def cmd_backup(args):
    from .volume_tools import backup_volume
    out = backup_volume(args.server, args.volumeId, args.dir,
                        collection=args.collection)
    print(f"volume {out['volume']}: {out['mode']} sync, "
          f"{out['applied']} records, {out['size']} bytes")


def cmd_see(args):
    from . import volume_tools
    if args.file.endswith(".idx") or args.file.endswith(".ecx"):
        n = volume_tools.see_idx(args.file,
                                 offset_width=args.offsetWidth,
                                 limit=args.limit)
        print(f"{n} index records")
    else:
        n = volume_tools.see_dat(args.file, limit=args.limit)
        print(f"{n} needles")


def cmd_export(args):
    from .volume_tools import export_volume
    listed = export_volume(args.dir, args.volumeId,
                           collection=args.collection,
                           tar_path=args.o or None)
    for fid, name, size in listed:
        print(f"{fid}\t{name}\t{size}")
    print(f"exported {len(listed)} files")


def cmd_fix(args):
    from .volume_tools import fix_volume
    n = fix_volume(args.dir, args.volumeId, collection=args.collection)
    print(f"walked {n} records")


def cmd_compact(args):
    from .volume_tools import compact_volume
    out = compact_volume(args.dir, args.volumeId,
                         collection=args.collection,
                         method=args.method)
    print(f"volume {out['volume']}: {out['before']} -> "
          f"{out['after']} bytes")


def cmd_watch(args):
    from ..replication.sub import EventSubscriber, format_event
    sub = EventSubscriber(args.filer, since=args.since,
                          path_prefix=args.pathPrefix)
    try:
        for ts, event in sub.follow():
            print(format_event(ts, event), flush=True)
    except KeyboardInterrupt:
        pass


def cmd_filer_copy(args):
    """Copy local files/directories into the filer (reference
    `weed filer.copy`, weed/command/filer_copy.go): the last argument
    is the filer URL destination folder, everything before it is a
    local source; directories recurse, -include filters by glob, -c
    uploads files concurrently."""
    import fnmatch
    import mimetypes
    import os
    import posixpath as pp
    import urllib.parse
    from concurrent.futures import ThreadPoolExecutor

    from ..server.http_util import http_call

    if len(args.paths) < 2:
        raise SystemExit("usage: filer.copy <src>... http://filer/dir/")
    dest = args.paths[-1]
    sources = args.paths[:-1]
    parsed = urllib.parse.urlparse(
        dest if "://" in dest else "http://" + dest)
    filer = parsed.netloc
    # decode before joining: put() re-quotes the final path, so keeping
    # the URL encoding here would double-escape ("%20" -> "%2520")
    dest_dir = urllib.parse.unquote(parsed.path).rstrip("/") or "/"

    work = []  # (local_path, remote_path)
    for src in sources:
        if os.path.isdir(src):
            base = os.path.basename(os.path.abspath(src))
            for root, _dirs, files in os.walk(src):
                rel_root = os.path.relpath(root, src)
                for name in files:
                    if args.include and not fnmatch.fnmatch(
                            name, args.include):
                        continue
                    rel = name if rel_root == "." else \
                        os.path.join(rel_root, name)
                    work.append((os.path.join(root, name),
                                 pp.join(dest_dir, base,
                                         rel.replace(os.sep, "/"))))
        elif os.path.isfile(src):
            if args.include and not fnmatch.fnmatch(
                    os.path.basename(src), args.include):
                continue
            work.append((src, pp.join(dest_dir, os.path.basename(src))))
        else:
            raise SystemExit(f"no such file or directory: {src}")

    q = []
    if args.collection:
        q.append(f"collection={urllib.parse.quote(args.collection)}")
    if args.replication:
        q.append(f"replication={urllib.parse.quote(args.replication)}")
    if args.ttl:
        q.append(f"ttl={urllib.parse.quote(args.ttl)}")
    suffix = ("?" + "&".join(q)) if q else ""

    def put(item):
        local, remote = item
        size = os.path.getsize(local)
        mime = mimetypes.guess_type(local)[0] or \
            "application/octet-stream"
        # stream the file object: -c workers each holding a whole
        # file in RAM would OOM on volume-sized inputs
        with open(local, "rb") as f:
            http_call("PUT",
                      f"http://{filer}"
                      f"{urllib.parse.quote(remote)}{suffix}",
                      f, {"Content-Type": mime,
                          "Content-Length": str(size)}, timeout=600)
        return remote, size

    copied = errors = 0
    with ThreadPoolExecutor(max_workers=args.c) as pool:
        for fut in [pool.submit(put, item) for item in work]:
            try:
                remote, n = fut.result()
                copied += 1
                print(f"{remote} ({n} bytes)")
            except Exception as e:  # noqa: BLE001 - per-file report
                errors += 1
                print(f"ERROR: {e}", file=sys.stderr)
    print(f"copied {copied} files to {filer}{dest_dir}"
          + (f", {errors} failed" if errors else ""))
    if errors:
        raise SystemExit(1)


def cmd_filer_replicate(args):
    import json
    from ..replication import (EventSubscriber, FilerSource, Replicator,
                               make_sink)
    with open(args.config) as f:
        cfg = json.load(f)
    src_cfg = cfg["source"]
    source = FilerSource(src_cfg["filer"], src_cfg["master"],
                         path_prefix=src_cfg.get("path", "/"))
    sink = make_sink(cfg["sink"])
    rep = Replicator(source, sink)
    # the replicator still routes by source.path_prefix; the server-side
    # prefix just keeps foreign-path event batches off the wire
    sub = EventSubscriber(src_cfg["filer"], since=args.since,
                          path_prefix=(source.path_prefix
                                       if source.path_prefix != "/"
                                       else ""))
    print(f"replicating {src_cfg['filer']}{source.path_prefix} "
          f"-> {sink.kind} sink", flush=True)
    import time as _time
    from ..server.http_util import HttpError
    try:
        while True:
            try:
                # cursor advances only after the batch fully applies —
                # a down sink must stall replication, not skip events
                batch = sub.poll_once(advance=False)
            except HttpError:
                _time.sleep(1.0)
                continue
            for e in batch:
                delay = 1.0
                while True:
                    try:
                        action = rep.replicate(e["event"])
                        break
                    except Exception as err:
                        print(f"RETRY in {delay:.0f}s: {err}",
                              flush=True)
                        _time.sleep(delay)
                        delay = min(delay * 2, 30.0)
                if action != "skip":
                    path = (e["event"].get("newEntry")
                            or e["event"].get("oldEntry")
                            or {}).get("FullPath", "?")
                    print(f"{action} {path}", flush=True)
            sub.commit(batch)
    except KeyboardInterrupt:
        pass


def cmd_mount(args):
    from ..mount.fuse_ll import FuseError, FuseMount
    from ..mount.wfs import WeedFS
    try:
        fs = WeedFS(args.filer, master_url=args.master,
                    chunk_size=args.chunkSizeLimitMB << 20,
                    collection=args.collection,
                    replication=args.replication,
                    root_path=args.filerPath)
        mount = FuseMount(fs, args.dir, allow_other=args.allowOthers)
    except FuseError as e:
        raise SystemExit(str(e))
    print(f"mounting {args.filer} at {args.dir}", flush=True)
    _spawn_unmount_watchdog(args.dir)
    raise SystemExit(mount.run())


def _spawn_unmount_watchdog(mountpoint):
    """Exit the process once the mountpoint is externally unmounted.

    Normally libfuse's event loop returns ENODEV after `fusermount -u`
    and `mount.run()` exits on its own; on some kernels (observed on the
    4.4-era sandbox this ships in) the read on /dev/fuse blocks forever
    instead. Detection must happen OUTSIDE this process: from inside the
    FUSE server, both /proc/self/mounts (mount-namespace lock) and
    stat-based os.path.ismount (GETATTR racing mount setup) were observed
    to block indefinitely. So spawn a tiny watcher subprocess that polls
    /proc/mounts and TERM-then-KILLs us once the mountpoint entry has
    appeared and then disappeared. The watcher exits on its own if we die
    first, and stands down if the mount never appears (startup failure is
    mount.run()'s to report).
    """
    # /proc/mounts records the symlink-resolved path, octal-escaping
    # space, tab, newline and backslash.
    esc = (os.path.realpath(mountpoint)
           .replace("\\", "\\134").replace(" ", "\\040")
           .replace("\t", "\\011").replace("\n", "\\012"))

    def count_entries():
        try:
            with open("/proc/mounts") as f:
                return sum(1 for line in f
                           if len(p := line.split()) > 1 and p[1] == esc)
        except OSError:
            return -1

    # Baseline BEFORE any FUSE activity (a pre-existing bind/tmpfs mount
    # at the same target must not satisfy "our mount appeared", nor keep
    # "our mount is gone" false after fusermount -u removes only ours).
    # Taken in the parent so the watcher can't race mount.run().
    baseline = count_entries()
    if baseline < 0:
        return   # no usable /proc/mounts; watchdog can't help here
    watcher_src = r"""
import os, signal, sys, time
esc, pid, baseline = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

def alive():
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False

def count():
    try:
        with open("/proc/mounts") as f:
            return sum(1 for line in f
                       if len(p := line.split()) > 1 and p[1] == esc)
    except OSError:
        return baseline + 1   # can't tell; don't kill a healthy mount

deadline = time.monotonic() + 30
while time.monotonic() < deadline and count() <= baseline:
    if not alive():
        sys.exit(0)
    time.sleep(0.2)
if count() <= baseline:
    sys.exit(0)       # never mounted; not ours to clean up
while count() > baseline:
    if not alive():
        sys.exit(0)
    time.sleep(0.5)
time.sleep(2.0)       # grace: let fuse_main return on its own
for sig in (signal.SIGTERM, signal.SIGKILL):
    if not alive():
        sys.exit(0)
    try:
        os.kill(pid, sig)
    except OSError:
        sys.exit(0)
    time.sleep(2.0)
"""
    import subprocess
    try:
        # -S: the watcher is stdlib-only; skip site/sitecustomize (which
        # can pull heavyweight deps or touch accelerator runtimes).
        subprocess.Popen(
            [sys.executable, "-S", "-c", watcher_src, esc,
             str(os.getpid()), str(baseline)],
            stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
    except OSError:
        pass   # watchdog is best-effort; never block the mount itself


def cmd_msg_broker(args):
    from ..server.msg_broker import MsgBrokerServer
    b = MsgBrokerServer(port=args.port, host=args.ip).start()
    print(f"message broker on {b.url}")
    _wait(b)


def cmd_scaffold(args):
    from .scaffold import print_scaffold
    print(print_scaffold(args.config), end="")


def cmd_version(args):
    from .. import VERSION
    print(f"seaweedfs_tpu {VERSION}")


def _wait(*stoppables):
    """Park until SIGTERM/SIGINT, then stop servers gracefully
    (reference util/signal_handling.go OnInterrupt) — a clean volume
    server shutdown sends /cluster/goodbye so watch subscribers reroute
    immediately instead of waiting out heartbeat expiry."""
    done = threading.Event()

    def on_signal(signum, frame):
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, on_signal)
        except (ValueError, OSError):
            pass
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    for s_ in stoppables:
        try:
            s_.stop()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="weed-tpu")
    p.add_argument("-v", type=int, default=0,
                   help="glog verbosity level")
    p.add_argument("-vmodule", default="",
                   help="per-module verbosity, e.g. volume_server=3")
    sub = p.add_subparsers(dest="command", required=True)

    m = sub.add_parser("master", help="start a master server")
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-ip", default="127.0.0.1")
    m.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    m.add_argument("-defaultReplication", default="000")
    m.add_argument("-pulseSeconds", type=int, default=5)
    m.add_argument("-jwtKey", default="",
                   help="HS256 key for per-fid write tokens")
    m.add_argument("-tlsCert", default="")
    m.add_argument("-tlsKey", default="")
    m.add_argument("-tlsCa", default="")
    m.add_argument("-tlsMutual", action="store_true",
                   help="require CA-verified client certs "
                        "on cluster-internal routes")
    m.add_argument("-peers", default="",
                   help="comma-separated master peers for raft HA, "
                        "e.g. host1:9333,host2:9333,host3:9333")
    m.add_argument("-mdir", default="",
                   help="directory for raft state persistence")
    m.add_argument("-maintenanceScripts", default="",
                   help="';'-separated shell command lines cron'd on "
                        "the leader (reference master.maintenance), "
                        'e.g. "volume.vacuum; ec.rebuild"')
    m.add_argument("-maintenanceIntervalSeconds", type=float,
                   default=17 * 60)
    m.add_argument("-whiteList", default="",
                   help="comma-separated IPs/CIDRs allowed on the "
                        "user-facing API (reference -whiteList). "
                        "Include your volume servers/filers/gateways: "
                        "only heartbeat/goodbye/raft stay open")
    m.add_argument("-metrics.address", dest="metricsAddress", default="",
                   help="Prometheus push-gateway address broadcast to "
                        "volume servers (reference -metrics.address)")
    m.add_argument("-metrics.intervalSeconds", dest="metricsInterval",
                   type=int, default=15)
    m.add_argument("-vacuumIntervalSeconds", type=float, default=15 * 60,
                   help="automatic vacuum + TTL-expiry sweep on the "
                        "leader (0 disables; reference "
                        "StartRefreshWritableVolumes)")
    m.add_argument("-garbageThreshold", type=float, default=0.3)
    m.add_argument("-sequencer", default="auto",
                   choices=["auto", "etcd"],
                   help="file-key sequencer: auto = in-memory "
                        "(raft-granted when -peers is set); etcd = "
                        "CAS blocks on an external etcd "
                        "(reference etcd_sequencer.go)")
    m.add_argument("-sequencerEtcd", default="127.0.0.1:2379",
                   help="etcd endpoint for -sequencer etcd")
    m.add_argument("-sequencerEtcdUser", default="")
    m.add_argument("-sequencerEtcdPassword", default="")
    m.set_defaults(fn=cmd_master)

    v = sub.add_parser("volume", help="start a volume server")
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-ip", default="127.0.0.1")
    v.add_argument("-dir", default="./data")
    v.add_argument("-max", default="7")
    v.add_argument("-mserver", default="127.0.0.1:9333")
    v.add_argument("-dataCenter", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-pulseSeconds", type=int, default=5)
    v.add_argument("-ec.backend", dest="ec_backend", default="auto",
                   choices=["auto", "numpy", "native", "tpu", "mesh"])
    v.add_argument("-mesh.coordinator", dest="meshCoordinator",
                   default="",
                   help="host:port of process 0 — joins a multi-host "
                        "device mesh via jax.distributed before the "
                        "EC codec compiles (DCN tier)")
    v.add_argument("-mesh.processes", dest="meshProcesses", type=int,
                   default=1)
    v.add_argument("-mesh.processId", dest="meshProcessId", type=int,
                   default=0)
    v.add_argument("-fastPort", type=int, default=0,
                   help="native C++ read plane port (0 = auto-pick, "
                        "-1 = disabled); plain needle GETs are served "
                        "there without the Python GIL")
    v.add_argument("-publicUrl", default="",
                   help="publicly accessible address advertised to "
                        "clients (reference -publicUrl)")
    v.add_argument("-read.redirect", dest="readRedirect",
                   default="true", choices=["true", "false"],
                   help="redirect reads for non-local volumes to a "
                        "replica (reference -read.redirect)")
    v.add_argument("-fileSizeLimitMB", type=int, default=256,
                   help="reject uploads above this size, 0 = no limit "
                        "(reference -fileSizeLimitMB)")
    v.add_argument("-compactionMBps", type=int, default=0,
                   help="throttle vacuum/compaction writes (MB/s, "
                        "0 = unthrottled; reference compactionMBps)")
    v.add_argument("-index", default="memory",
                   choices=["memory", "compact", "sortedfile", "disk"],
                   help="needle map variant (reference -index flag): "
                        "memory dict, 16B/needle compact arrays, "
                        "mmap'd sorted file, or a disk-backed writable "
                        "map for indexes larger than RAM (reference "
                        "-index leveldb)")
    v.add_argument("-cpuprofile", default="",
                   help="write an all-thread collapsed-stack CPU "
                        "profile here on shutdown (flamegraph.pl/"
                        "speedscope format; reference -cpuprofile)")
    v.add_argument("-jwtKey", default="")
    v.add_argument("-tlsCert", default="")
    v.add_argument("-tlsKey", default="")
    v.add_argument("-tlsCa", default="")
    v.add_argument("-tlsMutual", action="store_true",
                   help="require CA-verified client certs "
                        "on cluster-internal routes")
    v.add_argument("-whiteList", default="",
                   help="comma-separated IPs/CIDRs allowed to call")
    v.add_argument("-tierConfig", default="",
                   help="JSON file of remote tier backends, e.g. "
                        '{"s3": {"default": {"endpoint": ..., '
                        '"bucket": ...}}}')
    v.set_defaults(fn=cmd_volume)

    s = sub.add_parser("server", help="master + volume (+filer) combined")
    s.add_argument("-cpuprofile", default="",
                   help="write an all-thread collapsed-stack CPU "
                        "profile here on shutdown")
    s.add_argument("-ip", default="127.0.0.1")
    s.add_argument("-masterPort", type=int, default=9333)
    s.add_argument("-port", type=int, default=8080)
    s.add_argument("-dir", default="./data")
    s.add_argument("-max", default="50",
                   help="volume slots per directory")
    s.add_argument("-defaultReplication", default="000")
    s.add_argument("-dataCenter", default="")
    s.add_argument("-rack", default="")
    s.add_argument("-pulseSeconds", type=int, default=5)
    s.add_argument("-filer", action="store_true")
    s.add_argument("-filerPort", type=int, default=8888)
    s.add_argument("-s3", action="store_true")
    s.add_argument("-s3Port", type=int, default=8333)
    s.add_argument("-s3Config", default="",
                   help="IAM identities JSON (reference s3 config shape)")
    s.add_argument("-webdav", action="store_true")
    s.add_argument("-webdavPort", type=int, default=7333)
    s.add_argument("-ec.backend", dest="ec_backend", default="auto",
                   choices=["auto", "numpy", "native", "tpu", "mesh"])
    s.add_argument("-fastPort", type=int, default=0,
                   help="native C++ read plane port (0 = auto-pick, "
                        "-1 = disabled)")
    s.add_argument("-jwtKey", default="")
    s.add_argument("-tlsCert", default="")
    s.add_argument("-tlsKey", default="")
    s.add_argument("-tlsCa", default="")
    s.add_argument("-tlsMutual", action="store_true",
                   help="require CA-verified client certs "
                        "on cluster-internal routes")
    s.add_argument("-tierConfig", default="")
    s.set_defaults(fn=cmd_server)

    f = sub.add_parser("filer", help="start a filer server")
    f.add_argument("-port", type=int, default=8888)
    f.add_argument("-ip", default="127.0.0.1")
    f.add_argument("-master", default="127.0.0.1:9333")
    f.add_argument("-store", default="sqlite",
                   choices=["memory", "sqlite", "sharded", "redis",
                            "mysql", "postgres", "cassandra", "etcd"])
    f.add_argument("-db", default="./filer.db",
                   help="metadata path: a sqlite file, or a directory "
                        "of shard dbs for -store sharded (default "
                        "./filer_meta there)")
    f.add_argument("-storeShards", type=int, default=8,
                   help="shard count for -store sharded (sticky per "
                        "store directory)")
    f.add_argument("-redisAddr", default="127.0.0.1:6379",
                   help="redis endpoint for -store redis")
    f.add_argument("-redisPassword", default="")
    f.add_argument("-redisDb", type=int, default=0)
    f.add_argument("-mysqlAddr", default="127.0.0.1:3306",
                   help="mysql endpoint for -store mysql")
    f.add_argument("-mysqlUser", default="root")
    f.add_argument("-mysqlPassword", default="")
    f.add_argument("-mysqlDatabase", default="seaweedfs")
    f.add_argument("-postgresAddr", default="127.0.0.1:5432",
                   help="postgres endpoint for -store postgres")
    f.add_argument("-postgresUser", default="postgres")
    f.add_argument("-postgresPassword", default="")
    f.add_argument("-postgresDatabase", default="seaweedfs")
    f.add_argument("-cassandraAddr", default="127.0.0.1:9042",
                   help="cassandra endpoint for -store cassandra")
    f.add_argument("-cassandraUser", default="")
    f.add_argument("-cassandraPassword", default="")
    f.add_argument("-cassandraKeyspace", default="seaweedfs")
    f.add_argument("-etcdAddr", default="127.0.0.1:2379",
                   help="etcd endpoint for -store etcd (v3 JSON "
                        "gateway)")
    f.add_argument("-etcdUser", default="")
    f.add_argument("-etcdPassword", default="")
    f.add_argument("-collection", default="")
    f.add_argument("-defaultReplicaPlacement", default="")
    f.add_argument("-maxMB", type=int, default=32,
                   help="autochunk split size")
    f.add_argument("-s3", action="store_true")
    f.add_argument("-s3Port", type=int, default=8333)
    f.add_argument("-s3Config", default="")
    f.add_argument("-jwtKey", default="")
    f.add_argument("-tlsCert", default="")
    f.add_argument("-tlsKey", default="")
    f.add_argument("-tlsCa", default="")
    f.add_argument("-tlsMutual", action="store_true",
                   help="require CA-verified client certs "
                        "on cluster-internal routes")
    f.add_argument("-encryptVolumeData", action="store_true",
                   help="AES-256-GCM encrypt chunk data; volume servers "
                        "only see ciphertext (reference filer.toml "
                        "cipher)")
    f.add_argument("-compress", action="store_true",
                   help="gzip compressible chunks before storing")
    f.set_defaults(fn=cmd_filer)

    s3 = sub.add_parser("s3", help="standalone S3 gateway over a filer")
    s3.add_argument("-port", type=int, default=8333)
    s3.add_argument("-ip", default="127.0.0.1")
    s3.add_argument("-filer", default="127.0.0.1:8888")
    s3.add_argument("-master", default="",
                    help="master url (default: ask the filer)")
    s3.add_argument("-config", default="",
                    help="IAM identities JSON")
    s3.set_defaults(fn=cmd_s3)

    w = sub.add_parser("webdav", help="WebDAV gateway over a filer")
    w.add_argument("-port", type=int, default=7333)
    w.add_argument("-ip", default="127.0.0.1")
    w.add_argument("-filer", default="127.0.0.1:8888")
    w.add_argument("-master", default="")
    w.add_argument("-collection", default="")
    w.add_argument("-maxMB", type=int, default=8)
    w.set_defaults(fn=cmd_webdav)

    sh = sub.add_parser("shell", help="admin shell")
    sh.add_argument("-master", default="127.0.0.1:9333")
    sh.add_argument("-filer", default="",
                    help="filer host:port for fs.* commands")
    sh.add_argument("-c", default="", help="run one command and exit")
    sh.set_defaults(fn=cmd_shell)

    b = sub.add_parser("benchmark", help="cluster load test")
    b.add_argument("-master", default="127.0.0.1:9333")
    b.add_argument("-n", type=int, default=1024)
    b.add_argument("-size", type=int, default=1024)
    b.add_argument("-c", type=int, default=16)
    b.add_argument("-collection", default="benchmark")
    b.add_argument("-assignBatch", type=int, default=1,
                   help="files per master assign (?count= + fid_N "
                        "suffixes): >1 amortizes assign round trips "
                        "so the tool measures the data plane, not "
                        "its own per-file assign chatter")
    b.add_argument("-cpuprofile", default="",
                   help="write an all-thread collapsed-stack CPU "
                        "profile of the run (reference benchmark "
                        "-cpuprofile)")
    b.add_argument("-native", action="store_true",
                   help="drive the cluster with the C++ keep-alive "
                        "load engine (duration-based): measures server "
                        "capacity instead of this client's own ceiling")
    b.add_argument("-seconds", type=float, default=10.0,
                   help="per-phase duration for -native")
    b.add_argument("-pool", type=int, default=4096,
                   help="assigned-fid pool size for -native")
    b.set_defaults(fn=cmd_benchmark)

    u = sub.add_parser("upload", help="upload files")
    u.add_argument("-master", default="127.0.0.1:9333")
    u.add_argument("-collection", default="")
    u.add_argument("-replication", default="")
    u.add_argument("-ttl", default="")
    u.add_argument("-maxMB", type=int, default=32,
                   help="files above this split into chunk needles "
                        "behind a manifest fid (reference submit.go)")
    u.add_argument("files", nargs="+")
    u.set_defaults(fn=cmd_upload)

    d = sub.add_parser("download", help="download files by fid")
    d.add_argument("-master", default="127.0.0.1:9333")
    d.add_argument("-dir", default=".",
                   help="output directory (reference download -dir); "
                        "files keep their stored names when present")
    d.add_argument("fids", nargs="+")
    d.set_defaults(fn=cmd_download)

    wt = sub.add_parser("watch", help="follow a filer's metadata events")
    wt.add_argument("-filer", default="127.0.0.1:8888")
    wt.add_argument("-since", type=float, default=0.0,
                    help="resume from this event timestamp")
    wt.add_argument("-pathPrefix", default="",
                    help="only events under this path prefix "
                         "(reference watch -pathPrefix; filtered "
                         "server-side)")
    wt.set_defaults(fn=cmd_watch)

    fc = sub.add_parser("filer.copy",
                        help="copy local files/dirs into the filer")
    fc.add_argument("paths", nargs="+",
                    help="src... then http://filer:8888/dest/dir/")
    fc.add_argument("-include", default="",
                    help="glob of file names to copy, e.g. *.pdf")
    fc.add_argument("-collection", default="")
    fc.add_argument("-replication", default="")
    fc.add_argument("-ttl", default="")
    fc.add_argument("-c", type=int, default=8,
                    help="concurrent file uploads")
    fc.set_defaults(fn=cmd_filer_copy)

    fr = sub.add_parser("filer.replicate",
                        help="continuously replicate filer changes to a "
                             "sink (another filer or an S3 bucket)")
    fr.add_argument("-config", required=True,
                    help='JSON: {"source": {"filer":..., "master":..., '
                         '"path":...}, "sink": {"type": "filer"|"s3", '
                         '...}}')
    fr.add_argument("-since", type=float, default=0.0)
    fr.set_defaults(fn=cmd_filer_replicate)

    bk = sub.add_parser("backup",
                        help="incremental local copy of a live volume")
    bk.add_argument("-server", default="127.0.0.1:9333",
                    help="master url")
    bk.add_argument("-dir", default=".")
    bk.add_argument("-volumeId", type=int, required=True)
    bk.add_argument("-collection", default="")
    bk.set_defaults(fn=cmd_backup)

    se = sub.add_parser("see",
                        help="dump .dat/.idx records as text (reference "
                             "see_dat/see_idx debug tools)")
    se.add_argument("file", help="path to a .dat or .idx file")
    se.add_argument("-offsetWidth", type=int, default=4,
                    choices=[4, 5], help="idx entry offset width")
    se.add_argument("-limit", type=int, default=0,
                    help="stop after N records (0 = all)")
    se.set_defaults(fn=cmd_see)

    ex = sub.add_parser("export", help="export volume needles to tar")
    ex.add_argument("-dir", default=".")
    ex.add_argument("-volumeId", type=int, required=True)
    ex.add_argument("-collection", default="")
    ex.add_argument("-o", default="", help="tar output path")
    ex.set_defaults(fn=cmd_export)

    fx = sub.add_parser("fix", help="rebuild .idx from .dat")
    fx.add_argument("-dir", default=".")
    fx.add_argument("-volumeId", type=int, required=True)
    fx.add_argument("-collection", default="")
    fx.set_defaults(fn=cmd_fix)

    cp = sub.add_parser("compact", help="force-vacuum a local volume")
    cp.add_argument("-dir", default=".")
    cp.add_argument("-volumeId", type=int, required=True)
    cp.add_argument("-collection", default="")
    cp.add_argument("-method", type=int, default=1, choices=[0, 1],
                    help="0 = scan the .dat (reference Compact), "
                         "1 = copy by the index (reference Compact2)")
    cp.set_defaults(fn=cmd_compact)

    mt = sub.add_parser("mount", help="FUSE-mount the filer namespace")
    mt.add_argument("-filer", default="127.0.0.1:8888")
    mt.add_argument("-master", default="",
                    help="master url (default: ask the filer)")
    mt.add_argument("-dir", required=True, help="mount point")
    mt.add_argument("-collection", default="")
    mt.add_argument("-replication", default="")
    mt.add_argument("-chunkSizeLimitMB", type=int, default=8)
    mt.add_argument("-allowOthers", action="store_true")
    mt.add_argument("-filer.path", dest="filerPath", default="/",
                    help="mount this remote subtree of the filer "
                         "namespace (reference mount -filer.path)")
    mt.set_defaults(fn=cmd_mount)

    mb = sub.add_parser("msgBroker", help="message queue broker")
    mb.add_argument("-port", type=int, default=17777)
    mb.add_argument("-ip", default="127.0.0.1")
    mb.set_defaults(fn=cmd_msg_broker)

    sc = sub.add_parser("scaffold", help="print example config files")
    sc.add_argument("-config", default="replication",
                    choices=["tier", "s3", "replication", "security",
                             "notification", "filer", "master"])
    sc.set_defaults(fn=cmd_scaffold)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=cmd_version)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    from ..util import glog
    glog.set_verbosity(args.v)
    if args.vmodule:
        glog.set_vmodule(args.vmodule)
    # sitecustomize pre-imports jax with its own platform choice; re-apply
    # the JAX_PLATFORMS env request before any device touch so
    # `JAX_PLATFORMS=cpu weed volume -ec.backend mesh` really runs on CPU
    try:
        from ..util.jax_platform import honor_platform_request
        honor_platform_request()
    except Exception:  # noqa: BLE001 - jax may be absent entirely
        pass
    _apply_tls_config(args)
    args.fn(args)


if __name__ == "__main__":
    main()
