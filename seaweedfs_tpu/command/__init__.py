"""command — the `weed`-style CLI entry points."""
