"""Built-in cluster load generator.

Reference weed/command/benchmark.go (defaults: 16 concurrent, 1KB files,
1M files, collection "benchmark"): concurrent assign+upload, then random
reads, reporting req/s, throughput, and latency percentiles — the
reference's README numbers (README.md:477-522) come from exactly this
tool.
"""

from __future__ import annotations

import threading
import time
from typing import List

import numpy as np

from ..client import operation as op
from ..server.http_util import HttpError, http_call


class Stats:
    def __init__(self):
        self.latencies: List[float] = []
        self.failed = 0
        self.bytes = 0
        self.lock = threading.Lock()

    def add(self, dt: float, nbytes: int):
        with self.lock:
            self.latencies.append(dt)
            self.bytes += nbytes

    def fail(self):
        with self.lock:
            self.failed += 1

    def report(self, title: str, wall: float, out):
        lat = sorted(self.latencies)
        n = len(lat)
        print(f"\n--- {title} ---", file=out)
        print(f"requests: {n} ok, {self.failed} failed in {wall:.3f}s",
              file=out)
        if not n:
            return
        print(f"throughput: {n / wall:.2f} req/s, "
              f"{self.bytes / wall / 1024:.2f} KB/s", file=out)
        for p in (50, 75, 90, 95, 99):
            print(f"  p{p}: {lat[min(n - 1, n * p // 100)] * 1000:.1f} ms",
                  file=out)
        print(f"  max: {lat[-1] * 1000:.1f} ms", file=out)


def run_benchmark(master_url: str, num_files: int = 1024,
                  file_size: int = 1024, concurrency: int = 16,
                  collection: str = "benchmark", write: bool = True,
                  read: bool = True, assign_batch: int = 1, out=None):
    import sys
    out = out or sys.stdout
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, file_size).astype(np.uint8).tobytes()
    fids: List[str] = []
    fid_lock = threading.Lock()

    if write:
        stats = Stats()

        def worker_count(wid: int) -> int:
            # distribute the remainder so exactly num_files are written
            return num_files // concurrency + \
                (1 if wid < num_files % concurrency else 0)

        def writer(wid: int):
            # assign_batch > 1 amortizes the master round trip over a
            # batch of sequential keys (?count= assign + the fid_N
            # suffix convention), so the tool measures the DATA plane
            # rather than its own per-file assign chatter. The assign
            # round trip is charged to the batch's FIRST file, so at
            # the default batch of 1 every request's latency includes
            # it — identical to the tool's historical numbers.
            remaining = worker_count(wid)
            batch = max(1, assign_batch)
            seq = 0
            while remaining > 0:
                t_assign = time.perf_counter()
                try:
                    a = op.assign(master_url,
                                  count=min(batch, remaining),
                                  collection=collection)
                except HttpError:
                    stats.fail()
                    remaining -= 1
                    continue
                granted = max(1, min(int(a.get("count", 1)),
                                     remaining))
                if a.get("auth"):
                    # write JWTs are bound to the exact fid: suffixed
                    # batch fids would 401 — drop to per-file assigns
                    # (and stop over-reserving sequencer keys)
                    granted = 1
                    batch = 1
                target = a.get("fastUrl") or a["url"]
                for i in range(granted):
                    fid = a["fid"] if i == 0 else f"{a['fid']}_{i}"
                    t = t_assign if i == 0 else time.perf_counter()
                    try:
                        # plain uploads ride the holder's native write
                        # plane when it advertises one (reference
                        # clients hit the Go data plane directly);
                        # anything the plane won't serve 307s back to
                        # the Python server
                        op.upload(target, fid, payload,
                                  filename=f"b{wid}_{seq}",
                                  jwt=a.get("auth", ""))
                        stats.add(time.perf_counter() - t, file_size)
                        with fid_lock:
                            fids.append(fid)
                    except HttpError:
                        stats.fail()
                    seq += 1
                remaining -= granted

        t0 = time.perf_counter()
        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(concurrency)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats.report("write", time.perf_counter() - t0, out)

    if read and fids:
        stats = Stats()
        cache = op.VidCache(master_url)
        reads = len(fids)
        idx_seq = rng.integers(0, len(fids), reads)
        chunks = np.array_split(idx_seq, concurrency)

        def reader(idxs):
            for i in idxs:
                fid = fids[int(i)]
                t = time.perf_counter()
                try:
                    data = op.read_file(master_url, fid, cache)
                    stats.add(time.perf_counter() - t, len(data))
                except HttpError:
                    stats.fail()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=reader, args=(c,))
                   for c in chunks]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats.report("random read", time.perf_counter() - t0, out)
    return fids
