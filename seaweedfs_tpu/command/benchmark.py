"""Built-in cluster load generator.

Reference weed/command/benchmark.go (defaults: 16 concurrent, 1KB files,
1M files, collection "benchmark"): concurrent assign+upload, then random
reads, reporting req/s, throughput, and latency percentiles — the
reference's README numbers (README.md:477-522) come from exactly this
tool.
"""

from __future__ import annotations

import threading
from ..util.locks import make_lock
import time
from typing import List

import numpy as np

from ..client import operation as op
from ..server.http_util import HttpError, http_call


class Stats:
    def __init__(self):
        self.latencies: List[float] = []
        self.failed = 0
        self.bytes = 0
        self.lock = make_lock("benchmark.lock")

    def add(self, dt: float, nbytes: int):
        with self.lock:
            self.latencies.append(dt)
            self.bytes += nbytes

    def fail(self):
        with self.lock:
            self.failed += 1

    def report(self, title: str, wall: float, out):
        lat = sorted(self.latencies)
        n = len(lat)
        print(f"\n--- {title} ---", file=out)
        print(f"requests: {n} ok, {self.failed} failed in {wall:.3f}s",
              file=out)
        if not n:
            return
        print(f"throughput: {n / wall:.2f} req/s, "
              f"{self.bytes / wall / 1024:.2f} KB/s", file=out)
        for p in (50, 75, 90, 95, 99):
            print(f"  p{p}: {lat[min(n - 1, n * p // 100)] * 1000:.1f} ms",
                  file=out)
        print(f"  max: {lat[-1] * 1000:.1f} ms", file=out)


def run_benchmark(master_url: str, num_files: int = 1024,
                  file_size: int = 1024, concurrency: int = 16,
                  collection: str = "benchmark", write: bool = True,
                  read: bool = True, assign_batch: int = 1, out=None):
    import sys
    out = out or sys.stdout
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, file_size).astype(np.uint8).tobytes()
    fids: List[str] = []
    fid_lock = make_lock("benchmark.fid_lock")

    if write:
        stats = Stats()

        def worker_count(wid: int) -> int:
            # distribute the remainder so exactly num_files are written
            return num_files // concurrency + \
                (1 if wid < num_files % concurrency else 0)

        def writer(wid: int):
            # assign_batch > 1 amortizes the master round trip over a
            # batch of sequential keys (?count= assign + the fid_N
            # suffix convention), so the tool measures the DATA plane
            # rather than its own per-file assign chatter. The assign
            # round trip is charged to the batch's FIRST file, so at
            # the default batch of 1 every request's latency includes
            # it — identical to the tool's historical numbers.
            remaining = worker_count(wid)
            batch = max(1, assign_batch)
            seq = 0
            while remaining > 0:
                t_assign = time.perf_counter()
                try:
                    a = op.assign(master_url,
                                  count=min(batch, remaining),
                                  collection=collection)
                except HttpError:
                    stats.fail()
                    remaining -= 1
                    continue
                granted = max(1, min(int(a.get("count", 1)),
                                     remaining))
                if a.get("auth"):
                    # write JWTs are bound to the exact fid: suffixed
                    # batch fids would 401 — drop to per-file assigns
                    # (and stop over-reserving sequencer keys)
                    granted = 1
                    batch = 1
                target = a.get("fastUrl") or a["url"]
                for i, fid in enumerate(
                        op.expand_batch_fids(a["fid"], granted)):
                    t = t_assign if i == 0 else time.perf_counter()
                    try:
                        # plain uploads ride the holder's native write
                        # plane when it advertises one (reference
                        # clients hit the Go data plane directly);
                        # anything the plane won't serve 307s back to
                        # the Python server
                        op.upload(target, fid, payload,
                                  filename=f"b{wid}_{seq}",
                                  jwt=a.get("auth", ""))
                        stats.add(time.perf_counter() - t, file_size)
                        with fid_lock:
                            fids.append(fid)
                    except HttpError:
                        stats.fail()
                    seq += 1
                remaining -= granted

        t0 = time.perf_counter()
        threads = [threading.Thread(target=writer, args=(w,),
                                    name=f"bench-writer-{w}")
                   for w in range(concurrency)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats.report("write", time.perf_counter() - t0, out)

    if read and fids:
        stats = Stats()
        cache = op.VidCache(master_url)
        reads = len(fids)
        idx_seq = rng.integers(0, len(fids), reads)
        chunks = np.array_split(idx_seq, concurrency)

        def reader(idxs):
            for i in idxs:
                fid = fids[int(i)]
                t = time.perf_counter()
                try:
                    data = op.read_file(master_url, fid, cache)
                    stats.add(time.perf_counter() - t, len(data))
                except HttpError:
                    stats.fail()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=reader, args=(c,),
                                    name=f"bench-reader-{c[0]}")
                   for c in chunks]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats.report("random read", time.perf_counter() - t0, out)
    return fids


def _loadgen_binary() -> str:
    """Locate (or build) the native keep-alive load generator that the
    -native mode uses as its engine. A Python client process tops out
    near ~350 req/s on this class of kernel, so measuring a native data
    plane needs a native instrument."""
    import os
    import subprocess
    d = os.path.join(os.path.dirname(__file__), "..", "server", "native")
    d = os.path.abspath(d)
    binary = os.path.join(d, "loadgen")
    src = os.path.join(d, "loadgen.cc")
    have_src = os.path.exists(src)
    if os.path.exists(binary) and (
            not have_src
            or os.path.getmtime(binary) >= os.path.getmtime(src)):
        return binary
    if not have_src:
        raise RuntimeError(f"no loadgen binary at {binary} and no "
                           f"source at {src} to build it from")
    r = subprocess.run(["g++", "-O2", "-std=c++17", "-pthread",
                        "-o", binary, src],
                       capture_output=True, timeout=120, text=True)
    if r.returncode != 0:
        raise RuntimeError(f"building loadgen failed:\n{r.stderr}")
    return binary


def run_native_benchmark(master_url: str, file_size: int = 1024,
                         concurrency: int = 16,
                         collection: str = "benchmark",
                         seconds: float = 10.0, pool: int = 4096,
                         assign_batch: int = 256, out=None):
    """`weed benchmark -native`: drive the cluster with the C++
    keep-alive load generator instead of Python worker threads.

    The classic mode measures what one Python client process can push
    (the reference's Go benchmark has no such client-side ceiling); this
    mode measures what the SERVERS can take: batch-assign a pool of
    fids, then run the native engine in multipart-POST mode and again in
    GET mode against each volume server's advertised fast port,
    duration-based. Reports per-phase req/s aggregated across targets
    plus one JSON line per phase.
    """
    import json
    import os
    import socket
    import subprocess
    import sys
    import tempfile
    out = out or sys.stdout
    binary = _loadgen_binary()

    # -- assign a fid pool, grouped by target host:port -------------------
    targets = {}  # (host, port) -> [paths]
    assigned = 0
    assign_failures = 0
    while assigned < pool:
        try:
            a = op.assign(master_url,
                          count=min(assign_batch, pool - assigned),
                          collection=collection)
        except HttpError as e:
            # same per-batch resilience as the classic writer: one
            # transient master hiccup must not abort the run
            assign_failures += 1
            if assign_failures > 5:
                raise RuntimeError(
                    f"assign pool: {assign_failures} consecutive "
                    f"failures, giving up: {e}") from e
            time.sleep(0.2 * assign_failures)
            continue
        assign_failures = 0
        if a.get("auth"):
            raise SystemExit(
                "benchmark -native does not speak per-fid write JWTs; "
                "run it against a cluster without -jwtKey")
        granted = max(1, min(int(a.get("count", 1)), pool - assigned))
        url = a.get("fastUrl") or a["url"]
        host, _, port = url.rpartition(":")
        host = host.strip("[]") or "127.0.0.1"
        # the C++ engine dials IPv4 (inet_addr); prefer an A record and
        # fail with the reason rather than a bare gaierror when the
        # host is AAAA-only
        try:
            infos = socket.getaddrinfo(host, int(port),
                                       socket.AF_INET,
                                       socket.SOCK_STREAM)
            host = infos[0][4][0]
        except socket.gaierror as e:
            raise RuntimeError(
                f"benchmark -native needs an IPv4 route to {host!r} "
                f"(the native engine dials IPv4): {e}") from e
        bucket = targets.setdefault((host, int(port)), [])
        for fid in op.expand_batch_fids(a["fid"], granted):
            bucket.append("/" + fid)
        assigned += granted

    def thread_split() -> dict:
        """Exactly `concurrency` connections, split proportionally by
        pooled paths (largest remainder), every target getting >=1."""
        items = list(targets.items())
        total_paths = sum(len(p) for _, p in items)
        want = max(len(items), concurrency)
        extra = want - len(items)  # every target starts with 1
        shares = [(key, len(paths) * extra / total_paths)
                  for key, paths in items]
        alloc = {key: 1 + int(s) for key, s in shares}
        left = want - sum(alloc.values())
        for key, s in sorted(shares, key=lambda kv: kv[1] - int(kv[1]),
                             reverse=True):
            if left <= 0:
                break
            alloc[key] += 1
            left -= 1
        return alloc

    def drive(phase_args, label):
        """One loadgen per target, concurrency split proportionally."""
        import shutil
        procs = []
        alloc = thread_split()
        tmpdir = tempfile.mkdtemp(prefix="weedbench")
        requests = errors = 0
        wall = 0.0
        try:
            for n, ((host, port), paths) in enumerate(targets.items()):
                threads = alloc[(host, port)]
                pf = os.path.join(tmpdir, f"paths{n}")
                with open(pf, "w") as f:
                    f.write("\n".join(paths))
                procs.append(subprocess.Popen(
                    [binary, host, str(port), str(seconds),
                     str(threads), pf] + phase_args,
                    stdout=subprocess.PIPE, text=True))
            for p in procs:
                stdout, _ = p.communicate(timeout=seconds + 60)
                if p.returncode != 0:
                    raise RuntimeError(f"loadgen exited {p.returncode}")
                r = json.loads(stdout)
                requests += r["requests"]
                errors += r["errors"]
                wall = max(wall, r["seconds"])
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            shutil.rmtree(tmpdir, ignore_errors=True)
        rps = requests / wall if wall else 0.0
        conns = sum(alloc.values())
        print(f"\n--- native {label}: {len(targets)} target(s), "
              f"{conns} connections ---", file=out)
        print(f"requests: {requests}  errors: {errors}", file=out)
        print(f"time taken: {wall:.2f}s  req/s: {rps:.1f}  "
              f"KB/s: {rps * file_size / 1024:.1f}", file=out)
        print(json.dumps({"phase": label, "requests": requests,
                          "errors": errors, "seconds": round(wall, 3),
                          "rps": round(rps, 1), "connections": conns,
                          "targets": len(targets)}), file=out)
        return requests, errors

    drive(["post", str(file_size)], "write")
    # the write phase cycled the pool for `seconds`, so every pooled
    # path now exists (loadgen wrote each at least once unless the run
    # was too short for one full cycle — reads of unwritten fids would
    # count as errors, which is the honest outcome)
    _, read_errors = drive([], "random read")
    return read_errors
