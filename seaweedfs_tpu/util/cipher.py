"""AES-256-GCM chunk encryption.

Reference weed/util/cipher.go (Encrypt/Decrypt: AES-GCM with a random
per-chunk 256-bit key, random nonce prepended to the ciphertext) —
used by the filer write path so volume servers only ever see
ciphertext; the per-chunk key lives in filer metadata
(FileChunk.cipher_key, reference filer.proto FileChunk.cipher_key).
"""

from __future__ import annotations

import os

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:
    # runtime image without the cryptography wheel: same AES-GCM via
    # ctypes + libcrypto (which every Python with `ssl` already links)
    from .aesgcm_openssl import AESGCM, InvalidTag

KEY_SIZE = 32
NONCE_SIZE = 12


class CipherError(Exception):
    pass


def gen_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(plain: bytes, key: bytes = None) -> tuple:
    """Returns (nonce || ciphertext || tag, key). A fresh random key is
    generated when none is given (one key per chunk, like the
    reference)."""
    if key is None:
        key = gen_key()
    if len(key) != KEY_SIZE:
        raise CipherError(f"key must be {KEY_SIZE} bytes, got {len(key)}")
    nonce = os.urandom(NONCE_SIZE)
    sealed = AESGCM(key).encrypt(nonce, plain, None)
    return nonce + sealed, key


def decrypt(blob: bytes, key: bytes) -> bytes:
    if len(key) != KEY_SIZE:
        raise CipherError(f"key must be {KEY_SIZE} bytes, got {len(key)}")
    if len(blob) < NONCE_SIZE + 16:
        raise CipherError("ciphertext too short")
    nonce, sealed = blob[:NONCE_SIZE], blob[NONCE_SIZE:]
    try:
        return AESGCM(key).decrypt(nonce, sealed, None)
    except InvalidTag:
        raise CipherError("decryption failed (wrong key or corrupt "
                          "ciphertext)") from None
