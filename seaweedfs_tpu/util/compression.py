"""Gzip compression + compressibility heuristics.

Reference weed/util/compression.go: IsGzippable decides by extension
and mime type; already-compressed media/archive formats are left
alone, text-ish content is gzipped when that actually shrinks it.
"""

from __future__ import annotations

import gzip
import io

_COMPRESSIBLE_EXT = {
    ".txt", ".text", ".htm", ".html", ".css", ".js", ".json", ".xml",
    ".csv", ".tsv", ".md", ".yaml", ".yml", ".toml", ".ini", ".conf",
    ".log", ".svg", ".sql", ".go", ".py", ".c", ".cc", ".cpp", ".h",
    ".java", ".rs", ".ts", ".sh", ".bat", ".pdf",
}
_INCOMPRESSIBLE_EXT = {
    ".zip", ".gz", ".tgz", ".bz2", ".xz", ".zst", ".7z", ".rar",
    ".jpg", ".jpeg", ".png", ".gif", ".webp", ".heic",
    ".mp3", ".mp4", ".mkv", ".avi", ".mov", ".ogg", ".flac",
    ".woff", ".woff2",
}
_COMPRESSIBLE_MIME_PREFIXES = ("text/",)
_COMPRESSIBLE_MIMES = {
    "application/json", "application/xml", "application/javascript",
    "application/x-javascript", "application/xhtml+xml",
    "image/svg+xml",
}


def is_compressible(filename: str = "", mime: str = "") -> bool:
    name = filename.lower()
    for ext in _INCOMPRESSIBLE_EXT:
        if name.endswith(ext):
            return False
    for ext in _COMPRESSIBLE_EXT:
        if name.endswith(ext):
            return True
    mime = mime.split(";")[0].strip().lower()
    if mime.startswith(_COMPRESSIBLE_MIME_PREFIXES):
        return True
    return mime in _COMPRESSIBLE_MIMES


def gzip_data(data: bytes, level: int = 3) -> bytes:
    buf = io.BytesIO()
    # mtime=0 keeps output deterministic for etag/dedup purposes
    with gzip.GzipFile(fileobj=buf, mode="wb", compresslevel=level,
                       mtime=0) as f:
        f.write(data)
    return buf.getvalue()


def gunzip_data(data: bytes) -> bytes:
    return gzip.decompress(data)
