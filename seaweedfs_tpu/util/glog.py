"""Leveled logging (reference weed/glog, a vendored google/glog fork).

Same conventions: `V(n)` gates verbose logs behind a -v level, vmodule
overrides per-module, severities I/W/E with glog's line format
`I0729 14:30:05.123456 file.py:42] message`. Backed by a plain stream
(stderr default) rather than rotating files — containerized deployments
collect stdout/stderr.
"""

from __future__ import annotations

import inspect
import os
import sys
import threading
from .locks import make_lock
import time
from typing import Dict, TextIO

_verbosity = 0
_vmodule: Dict[str, int] = {}
_stream: TextIO = sys.stderr
_lock = make_lock("glog._lock")


def set_verbosity(v: int):
    global _verbosity
    _verbosity = int(v)


def set_vmodule(spec: str):
    """'volume_server=3,store=1' — per-module verbosity overrides
    (reference glog -vmodule)."""
    _vmodule.clear()
    for part in spec.split(","):
        if "=" in part:
            mod, lvl = part.split("=", 1)
            _vmodule[mod.strip()] = int(lvl)


def set_stream(stream: TextIO):
    global _stream
    _stream = stream


def _caller(depth: int = 3):
    frame = inspect.currentframe()
    for _ in range(depth):
        if frame.f_back is None:
            break
        frame = frame.f_back
    fname = os.path.basename(frame.f_code.co_filename)
    return fname, frame.f_lineno


def _emit(severity: str, msg: str, args):
    if args:
        msg = msg % args
    fname, lineno = _caller()
    now = time.time()
    stamp = time.strftime("%m%d %H:%M:%S", time.localtime(now))
    micros = int((now % 1) * 1e6)
    line = f"{severity}{stamp}.{micros:06d} {fname}:{lineno}] {msg}\n"
    with _lock:
        _stream.write(line)
        _stream.flush()


def infof(msg: str, *args):
    _emit("I", msg, args)


def warningf(msg: str, *args):
    _emit("W", msg, args)


def errorf(msg: str, *args):
    _emit("E", msg, args)


class _Verbose:
    def __init__(self, enabled: bool):
        self.enabled = enabled

    def infof(self, msg: str, *args):
        if self.enabled:
            _emit("I", msg, args)

    def __bool__(self):
        return self.enabled


def V(level: int) -> _Verbose:
    """glog.V(n).infof(...) — logs only when -v >= n (or the calling
    module's vmodule override allows it)."""
    if _vmodule:
        fname, _ = _caller(depth=2)
        mod = fname[:-3] if fname.endswith(".py") else fname
        if mod in _vmodule:
            return _Verbose(level <= _vmodule[mod])
    return _Verbose(level <= _verbosity)
