"""Profiling hooks (reference §5.1 analog: weed/util/pprof.go).

The reference wires Go pprof behind -cpuprofile/-memprofile flags
(reference weed/command/volume.go:71-72, weed/util/pprof.go). The TPU
build's equivalents:

  * ``maybe_trace(label)`` — a context manager that captures a JAX/XLA
    profiler trace (viewable in TensorBoard / Perfetto) when
    ``SW_PROFILE_DIR`` is set, and is free when it is not. Wrap device
    call sites (the EC pipeline does this around its stream loop).
  * ``cpu_profile(path)`` — cProfile for single-threaded host code
    (offline tools, kernels).
  * ``SamplingProfiler`` — an all-thread stack sampler for the servers
    (cProfile only sees the calling thread, useless for a threaded
    server): samples ``sys._current_frames()`` on an interval and dumps
    a collapsed-stack report (flamegraph.pl / speedscope compatible).
    Wired behind ``-cpuprofile`` on the server/benchmark CLIs.

All are no-ops unless explicitly enabled, so they can stay in the
serving path.
"""

from __future__ import annotations

import contextlib
import cProfile
import os
import threading
from . import config
from .locks import make_lock
import time
from typing import Dict, List, Optional, Tuple


@contextlib.contextmanager
def maybe_trace(label: str = "trace", profile_dir: Optional[str] = None):
    """Capture a jax.profiler trace into ``$SW_PROFILE_DIR/<label>`` (or
    ``profile_dir``) when configured; otherwise do nothing."""
    out = profile_dir or config.env_str("SW_PROFILE_DIR")
    if not out:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(out, label)):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named region in a captured device trace (no-op outside tracing)."""
    try:
        import jax.profiler as jp
        with jp.TraceAnnotation(name):
            yield
    except Exception:  # noqa: BLE001 - tracing must never break the op
        yield


@contextlib.contextmanager
def cpu_profile(path: Optional[str]):
    """cProfile the enclosed block into ``path`` (pstats format)."""
    if not path:
        yield
        return
    prof = cProfile.Profile()
    prof.enable()
    try:
        yield
    finally:
        prof.disable()
        prof.dump_stats(path)


class SamplingProfiler:
    """All-thread wall-clock stack sampler.

    A daemon thread snapshots every thread's Python stack
    (``sys._current_frames()``) every ``interval`` seconds and counts
    collapsed stacks. ``stop()`` writes one ``frame;frame;... count``
    line per distinct stack — the folded format flamegraph.pl and
    speedscope ingest directly. Overhead is one GIL-held walk per
    sample (~10-50us), fine at the default 10ms period.
    """

    def __init__(self, path: Optional[str], interval: float = 0.01):
        self.path = path
        self.interval = float(interval)
        self.counts: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sampling-profiler")

    def start(self) -> "SamplingProfiler":
        self._thread.start()
        return self

    def _run(self):
        import sys
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            for tid, top in sys._current_frames().items():
                if tid == me:
                    continue
                frames = []
                f = top
                while f is not None and len(frames) < 64:
                    code = f.f_code
                    frames.append(
                        f"{code.co_name} "
                        f"({code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno})")
                    f = f.f_back
                key = ";".join(reversed(frames))
                self.counts[key] = self.counts.get(key, 0) + 1

    def report(self) -> str:
        """Collapsed-stack text (``frame;frame;... count`` per line,
        hottest first) from the samples gathered so far."""
        return "".join(
            f"{stack} {n}\n"
            for stack, n in sorted(self.counts.items(),
                                   key=lambda kv: -kv[1]))

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        if self.path:
            with open(self.path, "w") as out:
                out.write(self.report())

    @classmethod
    def run_for(cls, seconds: float,
                interval: float = 0.01) -> str:
        """Sample every thread for ``seconds`` and return the collapsed
        stacks — the `POST /admin/profile` path, no file involved."""
        prof = cls(None, interval=interval).start()
        try:
            time.sleep(max(0.0, float(seconds)))
        finally:
            prof.stop()
        return prof.report()


class StageTimer:
    """Accumulates wall time per named stage plus timestamped intervals
    for stages whose concurrency matters (d2h drains overlap each other;
    the interesting figure is the union of their busy windows, which is
    the link's effective busy time)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.bytes: Dict[str, int] = {}
        self.intervals: Dict[str, List[Tuple[float, float]]] = {}
        self._t0 = time.perf_counter()
        self._lock = make_lock("profiling._lock")  # stages report from worker threads

    def add(self, stage: str, dt: float, nbytes: int = 0,
            interval: Optional[Tuple[float, float]] = None):
        with self._lock:
            self.totals[stage] = self.totals.get(stage, 0.0) + dt
            if nbytes:
                self.bytes[stage] = self.bytes.get(stage, 0) + nbytes
            if interval is not None:
                self.intervals.setdefault(stage, []).append(interval)

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int = 0):
        t = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            self.add(name, end - t, nbytes, interval=(t, end))

    def busy_time(self, stage: str) -> float:
        """Union length of the stage's intervals (overlaps collapsed)."""
        ivs = sorted(self.intervals.get(stage, []))
        total, cur_start, cur_end = 0.0, None, None
        for s, e in ivs:
            if cur_end is None or s > cur_end:
                if cur_end is not None:
                    total += cur_end - cur_start
                cur_start, cur_end = s, e
            else:
                cur_end = max(cur_end, e)
        if cur_end is not None:
            total += cur_end - cur_start
        return total

    def rate_mbps(self, stage: str, use_busy: bool = False) -> float:
        t = self.busy_time(stage) if use_busy else self.totals.get(stage, 0.0)
        if t <= 0:
            return 0.0
        return self.bytes.get(stage, 0) / t / 1e6

    def summary(self) -> str:
        wall = time.perf_counter() - self._t0
        parts = [f"wall {wall:.1f}s"]
        for name in sorted(self.totals):
            line = f"{name} {self.totals[name]:.1f}s"
            if name in self.intervals:
                busy = self.busy_time(name)
                if abs(busy - self.totals[name]) > 0.05:
                    line += f" (busy {busy:.1f}s)"
            if self.bytes.get(name):
                line += f" @{self.rate_mbps(name, name in self.intervals):.0f}MB/s"
            parts.append(line)
        return ", ".join(parts)
