"""Cross-cutting utilities (reference weed/util/)."""

from .cipher import CipherError, decrypt, encrypt, gen_key  # noqa: F401
from .compression import (gunzip_data, gzip_data,  # noqa: F401
                          is_compressible)
