"""Cross-cutting utilities (reference weed/util/)."""

import hashlib as _hashlib

from .cipher import CipherError, decrypt, encrypt, gen_key  # noqa: F401
from .compression import (gunzip_data, gzip_data,  # noqa: F401
                          is_compressible)


def file_sha256(fileobj) -> str:
    """hashlib.file_digest(f, "sha256").hexdigest() for Python < 3.11."""
    if hasattr(_hashlib, "file_digest"):
        return _hashlib.file_digest(fileobj, "sha256").hexdigest()
    h = _hashlib.sha256()
    for block in iter(lambda: fileobj.read(1 << 20), b""):
        h.update(block)
    return h.hexdigest()
