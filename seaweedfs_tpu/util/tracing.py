"""Lightweight distributed tracing for the EC data path.

Spans are cheap structs (two os.urandom calls, a dict of tags) linked
by W3C-style ``traceparent`` ids: the HTTP client injects the header on
every cluster-internal call and every server router continues it, so a
shell-initiated ``ec.rebuild`` yields one trace spanning the shell,
master, rebuilder volume server, and the peer fetches it triggers.

The current span rides a contextvar, which means it follows ordinary
call chains within a thread but does NOT cross the pipeline's reader /
drain worker threads — phase work that interleaves across threads is
accumulated as plain seconds and materialized with ``record_span``
instead.

Finished spans fan out three ways (see ``_export``):

* a bounded in-memory ring of recent traces (``RING``), served as JSON
  at ``/admin/traces`` and rendered in the status UI;
* per-phase Prometheus histograms/counters (lazy import of
  ``stats.metrics`` to avoid an import cycle — this module is imported
  by ``server.http_util`` which ``stats.metrics`` uses for pushes);
* caller-registered hooks (``add_finish_hook``) for tests and tuners.

This module must stay dependency-free: stdlib only, no jax, no other
seaweedfs_tpu imports at module level.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from .locks import make_lock
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

# EC phase names instrumented across the encode/rebuild hot paths.
PHASES = ("gather", "plan", "dispatch", "drain", "write")

TRACEPARENT_HEADER = "traceparent"

_current: contextvars.ContextVar = contextvars.ContextVar(
    "sw_current_span", default=None)


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One timed operation. ``finish()`` is idempotent; a span created
    by ``start_span`` is the thread's current span until finished."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "tags",
                 "start_wall", "start_mono", "duration_s", "_token")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 tags: Optional[Dict] = None):
        self.name = name
        self.trace_id = trace_id or _hex_id(16)     # 32 hex chars
        self.span_id = _hex_id(8)                   # 16 hex chars
        self.parent_id = parent_id
        self.tags = dict(tags or {})
        self.start_wall = time.time()
        self.start_mono = time.perf_counter()
        self.duration_s: Optional[float] = None
        self._token = None

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> Dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start_wall,
            "duration_s": self.duration_s,
            "tags": dict(self.tags),
        }


_LOWER_HEX = frozenset("0123456789abcdef")


def parse_traceparent(header) -> Optional[Tuple[str, str]]:
    """``00-<trace>-<span>-<flags>`` -> (trace_id, parent_span_id).

    Strictly W3C (trace-context §3.2): ids must be lowercase hex —
    uppercase is invalid on the wire, and ``int(x, 16)`` would happily
    continue a bogus trace under a casing no other participant can
    match — and all-zero trace/span ids mean "not sampled / invalid"
    and must start a fresh root instead of threading onto id 0."""
    if not header:
        return None
    parts = str(header).strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    if not (_LOWER_HEX.issuperset(version)
            and _LOWER_HEX.issuperset(trace_id)
            and _LOWER_HEX.issuperset(span_id)
            and _LOWER_HEX.issuperset(flags)):
        return None
    if version == "ff":          # forbidden version value
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def current_span() -> Optional[Span]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    s = _current.get()
    return s.trace_id if s is not None else None


def outbound_traceparent() -> str:
    """Header value for an outbound call: the current span's ids, or a
    fresh root so downstream spans still group into one trace."""
    s = _current.get()
    if s is not None:
        return s.traceparent()
    return f"00-{_hex_id(16)}-{_hex_id(8)}-01"


def start_span(name: str, parent: Optional[Span] = None,
               traceparent: Optional[str] = None, **tags) -> Span:
    """Start a span and make it the current one for this context.

    Parent resolution order: explicit ``parent`` span, then a remote
    ``traceparent`` header, then the context's current span, else a
    new root trace.
    """
    if parent is not None:
        s = Span(name, trace_id=parent.trace_id,
                 parent_id=parent.span_id, tags=tags)
    else:
        remote = parse_traceparent(traceparent)
        if remote is not None:
            s = Span(name, trace_id=remote[0], parent_id=remote[1],
                     tags=tags)
        else:
            cur = _current.get()
            if cur is not None:
                s = Span(name, trace_id=cur.trace_id,
                         parent_id=cur.span_id, tags=tags)
            else:
                s = Span(name, tags=tags)
    s._token = _current.set(s)
    return s


def finish_span(span: Optional[Span]):
    """Close the span, restore the previous current span, export."""
    if span is None or span.duration_s is not None:
        return
    span.duration_s = time.perf_counter() - span.start_mono
    if span._token is not None:
        try:
            _current.reset(span._token)
        except ValueError:       # finished from a different context
            pass
        span._token = None
    _export(span.to_dict())


@contextlib.contextmanager
def span(name: str, parent: Optional[Span] = None,
         traceparent: Optional[str] = None, **tags):
    s = start_span(name, parent=parent, traceparent=traceparent, **tags)
    try:
        yield s
    except BaseException as e:
        s.tags.setdefault("error", type(e).__name__)
        raise
    finally:
        finish_span(s)


def record_span(name: str, duration_s: float,
                parent: Optional[Span] = None,
                start_wall: Optional[float] = None, **tags):
    """Materialize an already-measured duration as a finished span.

    Used for phase durations accumulated across worker threads (the
    pipeline's reader and drain threads don't inherit the contextvar),
    where start/stop bracketing a single code region is impossible.
    """
    parent = parent if parent is not None else _current.get()
    d = {
        "trace_id": parent.trace_id if parent else _hex_id(16),
        "span_id": _hex_id(8),
        "parent_id": parent.span_id if parent else None,
        "name": name,
        "start": (start_wall if start_wall is not None
                  else time.time() - duration_s),
        "duration_s": float(duration_s),
        "tags": dict(tags),
    }
    _export(d)
    return d


class TraceRing:
    """Bounded map of trace_id -> span list; oldest trace evicted."""

    def __init__(self, max_traces: int = 64, max_spans: int = 512):
        self.max_traces = max_traces
        self.max_spans = max_spans
        self._lock = make_lock("tracing._lock")
        self._traces: "OrderedDict[str, List[Dict]]" = OrderedDict()

    def add(self, span_dict: Dict):
        tid = span_dict.get("trace_id")
        if not tid:
            return
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                while len(self._traces) >= self.max_traces:
                    self._traces.popitem(last=False)
                spans = self._traces[tid] = []
            if len(spans) < self.max_spans:
                spans.append(span_dict)
            self._traces.move_to_end(tid)

    def get(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def recent(self, n: int = 20) -> List[Dict]:
        """Newest-first list of {trace_id, spans: [...]} dicts."""
        with self._lock:
            items = list(self._traces.items())[-n:]
        out = []
        for tid, spans in reversed(items):
            total = max((s.get("duration_s") or 0.0) for s in spans)
            root = next((s for s in spans if not s.get("parent_id")),
                        spans[0])
            out.append({"trace_id": tid, "root": root.get("name"),
                        "spans": list(spans), "span_count": len(spans),
                        "max_span_s": total})
        return out

    def clear(self):
        with self._lock:
            self._traces.clear()


# Big enough that steady-state heartbeat/poll traces (one span each)
# don't evict a rebuild trace before an operator can look at it.
RING = TraceRing(max_traces=256)

_FINISH_HOOKS: List[Callable[[Dict], None]] = []
_metrics_export = None      # resolved lazily; False = unavailable


def add_finish_hook(fn: Callable[[Dict], None]):
    _FINISH_HOOKS.append(fn)


def remove_finish_hook(fn: Callable[[Dict], None]):
    try:
        _FINISH_HOOKS.remove(fn)
    except ValueError:
        pass


def _export(span_dict: Dict):
    RING.add(span_dict)
    global _metrics_export
    if _metrics_export is None:
        try:
            from ..stats import metrics as _m
            _metrics_export = _m.observe_span
        except Exception:
            _metrics_export = False
    if _metrics_export:
        try:
            _metrics_export(span_dict)
        except Exception:
            pass
    for fn in list(_FINISH_HOOKS):
        try:
            fn(span_dict)
        except Exception:
            pass
