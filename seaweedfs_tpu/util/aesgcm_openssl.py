"""AES-GCM via ctypes + libcrypto — fallback when the `cryptography`
wheel is absent from the runtime image.

Exposes the same two names util/cipher.py needs (`AESGCM`, `InvalidTag`)
with the same call shapes, backed by OpenSSL's EVP interface, which
every Python build with an `ssl` module already links. Only what the
cipher path uses is implemented: 16/24/32-byte keys, no AAD streaming
beyond a single optional buffer, 16-byte tag appended to the
ciphertext.
"""

from __future__ import annotations

import ctypes
import ctypes.util


class InvalidTag(Exception):
    pass


_EVP_CTRL_GCM_SET_IVLEN = 0x9
_EVP_CTRL_GCM_GET_TAG = 0x10
_EVP_CTRL_GCM_SET_TAG = 0x11
_TAG_SIZE = 16

_lib = None


def _crypto():
    global _lib
    if _lib is None:
        name = ctypes.util.find_library("crypto") or "libcrypto.so"
        lib = ctypes.CDLL(name)
        lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
        for f in ("EVP_aes_128_gcm", "EVP_aes_192_gcm", "EVP_aes_256_gcm"):
            getattr(lib, f).restype = ctypes.c_void_p
        _lib = lib
    return _lib


def _cipher_for(key: bytes):
    lib = _crypto()
    by_len = {16: lib.EVP_aes_128_gcm, 24: lib.EVP_aes_192_gcm,
              32: lib.EVP_aes_256_gcm}
    if len(key) not in by_len:
        raise ValueError(f"AESGCM key must be 16/24/32 bytes, "
                         f"got {len(key)}")
    return by_len[len(key)]()


class AESGCM:
    def __init__(self, key: bytes):
        self._key = bytes(key)
        _cipher_for(self._key)  # validate key size eagerly

    def _init_ctx(self, nonce: bytes, encrypt: bool):
        lib = _crypto()
        ctx = lib.EVP_CIPHER_CTX_new()
        if not ctx:
            raise MemoryError("EVP_CIPHER_CTX_new failed")
        init = lib.EVP_EncryptInit_ex if encrypt else lib.EVP_DecryptInit_ex
        if init(ctypes.c_void_p(ctx), ctypes.c_void_p(_cipher_for(self._key)),
                None, None, None) != 1:
            lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))
            raise RuntimeError("EVP init (cipher) failed")
        if lib.EVP_CIPHER_CTX_ctrl(ctypes.c_void_p(ctx),
                                   _EVP_CTRL_GCM_SET_IVLEN,
                                   len(nonce), None) != 1 or \
                init(ctypes.c_void_p(ctx), None, None, self._key,
                     bytes(nonce)) != 1:
            lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))
            raise RuntimeError("EVP init (key/iv) failed")
        return lib, ctx

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        lib, ctx = self._init_ctx(nonce, encrypt=True)
        try:
            outl = ctypes.c_int(0)
            if aad:
                if lib.EVP_EncryptUpdate(ctypes.c_void_p(ctx), None,
                                         ctypes.byref(outl), bytes(aad),
                                         len(aad)) != 1:
                    raise RuntimeError("EVP aad update failed")
            out = ctypes.create_string_buffer(len(data) + _TAG_SIZE)
            if lib.EVP_EncryptUpdate(ctypes.c_void_p(ctx), out,
                                     ctypes.byref(outl), bytes(data),
                                     len(data)) != 1:
                raise RuntimeError("EVP encrypt update failed")
            total = outl.value
            if lib.EVP_EncryptFinal_ex(
                    ctypes.c_void_p(ctx),
                    ctypes.byref(out, total), ctypes.byref(outl)) != 1:
                raise RuntimeError("EVP encrypt final failed")
            total += outl.value
            tag = ctypes.create_string_buffer(_TAG_SIZE)
            if lib.EVP_CIPHER_CTX_ctrl(ctypes.c_void_p(ctx),
                                       _EVP_CTRL_GCM_GET_TAG,
                                       _TAG_SIZE, tag) != 1:
                raise RuntimeError("EVP get tag failed")
            return out.raw[:total] + tag.raw
        finally:
            lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(data) < _TAG_SIZE:
            raise InvalidTag("ciphertext shorter than the GCM tag")
        ct, tag = bytes(data[:-_TAG_SIZE]), bytes(data[-_TAG_SIZE:])
        lib, ctx = self._init_ctx(nonce, encrypt=False)
        try:
            outl = ctypes.c_int(0)
            if aad:
                if lib.EVP_DecryptUpdate(ctypes.c_void_p(ctx), None,
                                         ctypes.byref(outl), bytes(aad),
                                         len(aad)) != 1:
                    raise RuntimeError("EVP aad update failed")
            out = ctypes.create_string_buffer(max(len(ct), 1))
            if lib.EVP_DecryptUpdate(ctypes.c_void_p(ctx), out,
                                     ctypes.byref(outl), ct, len(ct)) != 1:
                raise InvalidTag("GCM decrypt update failed")
            total = outl.value
            if lib.EVP_CIPHER_CTX_ctrl(ctypes.c_void_p(ctx),
                                       _EVP_CTRL_GCM_SET_TAG,
                                       _TAG_SIZE, tag) != 1:
                raise RuntimeError("EVP set tag failed")
            if lib.EVP_DecryptFinal_ex(ctypes.c_void_p(ctx),
                                       ctypes.byref(out, total),
                                       ctypes.byref(outl)) != 1:
                raise InvalidTag("GCM tag mismatch")
            total += outl.value
            return out.raw[:total]
        finally:
            lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))
