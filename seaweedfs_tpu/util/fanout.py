"""Parallel fan-out over cluster peers.

The reference fans maintenance and replication traffic out with a
goroutine per target and an all-must-succeed barrier
(reference weed/topology/store_replicate.go:137-152 distributedOperation,
weed/shell/command_ec_encode.go:200-235 parallelCopyEcShardsFromSource,
weed/storage/store_ec.go:329-362 parallel sibling-interval fetches). The
Python analog is a bounded thread pool: every target runs concurrently
and the caller gets (result | exception) per target, in input order.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Sequence, Tuple, TypeVar

T = TypeVar("T")
R = TypeVar("R")

_MAX_WORKERS = 32
_pool = None
_pool_lock = __import__("threading").Lock()


def _shared_pool() -> ThreadPoolExecutor:
    """One long-lived pool — fan_out sits on the per-request write/delete
    hot path, so per-call executor spawn/teardown would tax every
    replicated PUT. Callables must not recursively fan_out."""
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(max_workers=_MAX_WORKERS,
                                       thread_name_prefix="fanout")
        return _pool


def fan_out(fn: Callable[[T], R], items: Sequence[T],
            dedicated: bool = False) -> List[Tuple[T, R, Exception]]:
    """Run ``fn(item)`` for every item concurrently.

    Returns [(item, result, None) | (item, None, exc)] in input order.
    With zero or one item there is no pool overhead.

    ``dedicated=True`` spins a private pool for this call — use it for
    rare long-timeout fan-outs (degraded-read shard fetches, shell
    maintenance copies) so they cannot head-of-line block the shared
    pool serving the per-request replication hot path.
    """
    items = list(items)
    if not items:
        return []
    if len(items) == 1:
        try:
            return [(items[0], fn(items[0]), None)]
        except Exception as e:  # noqa: BLE001 - relayed to caller
            return [(items[0], None, e)]
    out: List[Tuple[T, R, Exception]] = [None] * len(items)  # type: ignore

    def run(i: int):
        try:
            out[i] = (items[i], fn(items[i]), None)
        except Exception as e:  # noqa: BLE001 - relayed to caller
            out[i] = (items[i], None, e)

    if dedicated:
        with ThreadPoolExecutor(max_workers=min(_MAX_WORKERS,
                                                len(items))) as ex:
            list(ex.map(run, range(len(items))))
    else:
        list(_shared_pool().map(run, range(len(items))))
    return out


def fan_out_must_succeed(fn: Callable[[T], R], items: Sequence[T],
                         what: str = "operation",
                         ok: Callable[[Exception], bool] = None,
                         dedicated: bool = False) -> List[R]:
    """All-must-succeed barrier (reference distributedOperation): raises
    RuntimeError naming every failed target; ``ok(exc)`` may whitelist
    benign failures (e.g. 404 on a replica delete — already gone)."""
    failed = []
    results = []
    for item, result, exc in fan_out(fn, items, dedicated=dedicated):
        if exc is not None and not (ok is not None and ok(exc)):
            failed.append(f"{item}: {exc}")
        else:
            results.append(result)
    if failed:
        raise RuntimeError(f"{what} failed on " + "; ".join(failed))
    return results
