"""Lock factory with an optional runtime lock-order recorder.

The Go reference leans on ``go vet`` and the ``-race`` detector; this
codebase's equivalent is split between the static lint
(``tools/analyze.py``) and this module's dynamic half: when
``SW_LOCK_DEBUG=1`` (tests/conftest.py sets it for the whole tier-1 run,
server subprocesses included), ``make_lock``/``make_rlock`` hand out
instrumented wrappers that record the cross-thread lock-acquisition
graph — an edge ``A -> B`` means some thread acquired ``B`` while
holding ``A``.  A cycle in that graph is a potential ABBA deadlock even
if the run never actually deadlocked: two threads interleaving the two
orders can stall forever in production.  The conftest session hook (and
``tools/analyze.py --lock-report``) fail on any cycle.

Nodes are lock *names* (lockdep-style classes), not instances: every
per-volume ``volume.lock`` is one node, so an ABBA between two different
volumes is still caught.  Deliberately ordered same-class nesting must
be allowlisted in ``tools/analyze.py`` with a justification.

When recording is off the factories return plain ``threading`` locks —
zero overhead on the production path.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from . import config


def debug_enabled() -> bool:
    return config.env_bool("SW_LOCK_DEBUG")


class LockGraphRecorder:
    """Cross-thread lock-acquisition graph for one process.

    Thread-local held stacks, a global edge map keyed
    ``(holder_name, acquired_name)`` with an example location so a
    reported cycle points somewhere actionable."""

    def __init__(self):
        self._mu = threading.Lock()  # guards edges only
        self._tls = threading.local()
        # (holder, acquired) -> {"count": n, "thread": name}
        self.edges: Dict[Tuple[str, str], dict] = {}

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _seen(self) -> set:
        seen = getattr(self._tls, "seen", None)
        if seen is None:
            seen = self._tls.seen = set()
        return seen

    def on_acquire(self, lock: "_DebugLockBase"):
        held = self._held()
        if held and not lock.reentrant_held():
            top = held[-1]
            if top is not lock:
                edge = (top.name, lock.name)
                # skip the global lock for edges this thread already saw
                seen = self._seen()
                if edge not in seen:
                    seen.add(edge)
                    with self._mu:
                        e = self.edges.setdefault(
                            edge, {"count": 0,
                                   "thread": threading.current_thread().name})
                        e["count"] += 1
                else:
                    with self._mu:
                        self.edges[edge]["count"] += 1
        held.append(lock)

    def on_release(self, lock: "_DebugLockBase"):
        held = self._held()
        # remove the most recent occurrence; out-of-order releases are
        # legal (if rare), so scan instead of assuming LIFO
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def edge_list(self) -> List[dict]:
        with self._mu:
            return [{"from": a, "to": b, **info}
                    for (a, b), info in sorted(self.edges.items())]

    def clear(self):
        with self._mu:
            self.edges.clear()

    def cycles(self, extra_edges: Optional[List[dict]] = None,
               allowed: Optional[set] = None) -> List[List[str]]:
        """Elementary cycles in the (merged) name graph, each rotated to
        its lexicographically smallest node and deduplicated.  ``allowed``
        drops individual edges (the analyze.py allowlist) before the
        search, so a justified ordered nesting can't mask a real cycle
        elsewhere."""
        graph: Dict[str, set] = {}
        merged = self.edge_list() + list(extra_edges or [])
        for e in merged:
            a, b = e["from"], e["to"]
            if allowed and (a, b) in allowed:
                continue
            graph.setdefault(a, set()).add(b)
        out, seen = [], set()
        # DFS from every node; the graphs here are tiny (tens of names)
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(graph.get(node, ())):
                    if nxt == start:
                        cyc = path[:]
                        low = cyc.index(min(cyc))
                        key = tuple(cyc[low:] + cyc[:low])
                        if key not in seen:
                            seen.add(key)
                            out.append(list(key))
                    elif nxt not in path and len(path) < 16:
                        stack.append((nxt, path + [nxt]))
        return out

    def dump(self, path: str):
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"pid": os.getpid(), "edges": self.edge_list()}, f)


RECORDER = LockGraphRecorder()


class _DebugLockBase:
    """Common wrapper: acquire/release bookkeeping + the Condition
    protocol (_release_save/_acquire_restore/_is_owned) so a factory
    lock can back a threading.Condition without desyncing the held
    stack during wait()."""

    def __init__(self, name: str, inner, recorder: LockGraphRecorder):
        self.name = name
        self._inner = inner
        self._recorder = recorder

    def reentrant_held(self) -> bool:
        return False

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._recorder.on_acquire(self)
        return ok

    def release(self):
        self._recorder.on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock) protocol
    def _release_save(self):
        self._recorder.on_release(self)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._recorder.on_acquire(self)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain-Lock heuristic, same as threading.Condition's fallback
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class DebugLock(_DebugLockBase):
    pass


class DebugRLock(_DebugLockBase):
    """Re-entrant variant: nested re-acquires by the owning thread are
    not new graph edges (a lock can't deadlock against itself in one
    thread), and only the outermost release pops the held stack."""

    def __init__(self, name: str, recorder: LockGraphRecorder):
        super().__init__(name, threading.RLock(), recorder)
        self._owner: Optional[int] = None
        self._depth = 0

    def reentrant_held(self) -> bool:
        return self._owner == threading.get_ident() and self._depth > 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._depth == 0 or \
                    self._owner != threading.get_ident():
                self._recorder.on_acquire(self)
            else:
                # re-entrant: keep stack balance without a new edge
                self._recorder._held().append(self)
            self._owner = threading.get_ident()
            self._depth += 1
        return ok

    def release(self):
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
        self._recorder.on_release(self)
        self._inner.release()

    def _release_save(self):
        # Condition.wait on an RLock releases ALL recursion levels
        self._recorder.on_release(self)
        depth, self._depth = self._depth, 0
        self._owner = None
        state = self._inner._release_save()
        return (state, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._owner = threading.get_ident()
        self._depth = depth
        self._recorder.on_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()


def make_lock(name: str, recorder: Optional[LockGraphRecorder] = None):
    """A ``threading.Lock`` — instrumented with ``name`` as its
    lock-class when recording is on.  ``recorder`` is for tests; the
    process-global RECORDER is the default."""
    if recorder is None and not debug_enabled():
        return threading.Lock()
    return DebugLock(name, threading.Lock(), recorder or RECORDER)


def make_rlock(name: str, recorder: Optional[LockGraphRecorder] = None):
    if recorder is None and not debug_enabled():
        return threading.RLock()
    return DebugRLock(name, recorder or RECORDER)


def load_graph_dir(path: str) -> List[dict]:
    """Merged edge list from every per-process dump in ``path``."""
    edges: List[dict] = []
    if not path or not os.path.isdir(path):
        return edges
    for name in sorted(os.listdir(path)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(path, name), encoding="utf-8") as f:
                edges.extend(json.load(f).get("edges", []))
        except (OSError, ValueError):
            continue
    return edges


def _dump_at_exit():
    out_dir = config.env_str("SW_LOCK_GRAPH_DIR")
    if not out_dir or not RECORDER.edges:
        return
    try:
        os.makedirs(out_dir, exist_ok=True)
        RECORDER.dump(os.path.join(out_dir, f"lockgraph-{os.getpid()}.json"))
    except OSError:
        pass  # diagnostics must never break process exit


# registered unconditionally: _dump_at_exit no-ops unless recording ran
# and SW_LOCK_GRAPH_DIR is set, and import order must not decide whether
# a late-enabled process dumps its graph
atexit.register(_dump_at_exit)
