"""Config-file loading with search path + env override tiers.

Reference weed/util/config.go: viper loads <name>.toml from ".",
"$HOME/.seaweedfs", "/etc/seaweedfs", and every key is overridable via
WEED_<SECTION>_<KEY> environment variables
(reference command/scaffold.go:15-25). Here: <name>.toml (stdlib
tomllib) or <name>.json from the same three-tier search path, flattened
to dotted keys, then WEED_* env vars override — e.g.

    WEED_JWT_SIGNING_KEY=secret    ->  cfg["jwt.signing.key"]

(env words map to dotted segments, lowercase, like viper's replacer).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

SEARCH_DIRS = [".", os.path.expanduser("~/.seaweedfs_tpu"),
               "/etc/seaweedfs_tpu"]
ENV_PREFIX = "WEED_"


def _flatten(d: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in d.items():
        key = f"{prefix}{k}".lower()
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def find_config_file(name: str,
                     dirs: Optional[List[str]] = None) -> Optional[str]:
    for d in dirs or SEARCH_DIRS:
        for ext in (".toml", ".json"):
            p = os.path.join(d, name + ext)
            if os.path.isfile(p):
                return p
    return None


def _toml_module():
    """stdlib tomllib is 3.11+; on 3.10 fall back to a tomli copy —
    standalone if installed, else the one pip/setuptools vendor (same
    package tomllib was adopted from, identical load() API)."""
    try:
        import tomllib
        return tomllib
    except ImportError:
        pass
    try:
        import tomli
        return tomli
    except ImportError:
        from pip._vendor import tomli
        return tomli


def load_config(name: str, dirs: Optional[List[str]] = None,
                env: Optional[dict] = None) -> Dict[str, object]:
    """Flattened dotted-key config for <name>, {} when no file exists;
    WEED_* env vars always apply on top (a config can be pure env)."""
    cfg: Dict[str, object] = {}
    path = find_config_file(name, dirs)
    if path is not None:
        if path.endswith(".toml"):
            tomllib = _toml_module()
            with open(path, "rb") as f:
                cfg = _flatten(tomllib.load(f))
        else:
            with open(path) as f:
                cfg = _flatten(json.load(f))
    environ = os.environ if env is None else env
    for k, v in environ.items():
        if k.startswith(ENV_PREFIX):
            dotted = k[len(ENV_PREFIX):].lower().replace("_", ".")
            cfg[dotted] = v
    return cfg


def config_get(cfg: Dict[str, object], key: str, default=None):
    """Dotted lookup with underscore tolerance (env vars can't carry
    dots, so WEED_SECURITY_JWT_KEY and [security] jwt_key in TOML must
    land on the same value)."""
    key = key.lower()
    if key in cfg:
        return cfg[key]
    alt = key.replace("_", ".")
    return cfg.get(alt, default)
