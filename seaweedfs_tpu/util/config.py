"""Config-file loading with search path + env override tiers.

Reference weed/util/config.go: viper loads <name>.toml from ".",
"$HOME/.seaweedfs", "/etc/seaweedfs", and every key is overridable via
WEED_<SECTION>_<KEY> environment variables
(reference command/scaffold.go:15-25). Here: <name>.toml (stdlib
tomllib) or <name>.json from the same three-tier search path, flattened
to dotted keys, then WEED_* env vars override — e.g.

    WEED_JWT_SIGNING_KEY=secret    ->  cfg["jwt.signing.key"]

(env words map to dotted segments, lowercase, like viper's replacer).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

SEARCH_DIRS = [".", os.path.expanduser("~/.seaweedfs_tpu"),
               "/etc/seaweedfs_tpu"]
ENV_PREFIX = "WEED_"

# -- SW_* env-knob registry --------------------------------------------------
#
# Every SW_* tunable the codebase reads is declared here ONCE — name,
# type, default, one-line doc — and read through the typed accessors
# below (env_str/env_int/env_float/env_bool/env_is_set). tools/analyze.py
# enforces the contract as a tier-1 lint: a raw os.environ/os.getenv read
# of an SW_* name anywhere else is a violation, a registered knob nobody
# reads is a violation, and the README env table is generated from this
# registry (a stale committed table is a violation too).

KNOB_KINDS = ("str", "int", "float", "bool")


class EnvKnob:
    __slots__ = ("name", "kind", "default", "doc")

    def __init__(self, name: str, kind: str, default, doc: str):
        self.name = name
        self.kind = kind
        self.default = default
        self.doc = doc

    def default_repr(self) -> str:
        if self.default is None:
            return "(unset)"
        if self.kind == "bool":
            return "1" if self.default else "0"
        return str(self.default)


KNOBS: Dict[str, EnvKnob] = {}


def _knob(name: str, kind: str, default, doc: str) -> str:
    if not name.startswith("SW_"):
        raise ValueError(f"env knob {name!r} must start with SW_")
    if kind not in KNOB_KINDS:
        raise ValueError(f"env knob {name}: bad kind {kind!r}")
    if not doc or "\n" in doc:
        raise ValueError(f"env knob {name}: doc must be one line")
    if name in KNOBS:
        raise ValueError(f"env knob {name} registered twice")
    KNOBS[name] = EnvKnob(name, kind, default, doc)
    return name


# server / transport
_knob("SW_PULSE_S", "float", 5.0,
      "Default heartbeat/prune pulse seconds for servers constructed "
      "without an explicit pulse_seconds.")
_knob("SW_HTTP_POLL_S", "float", 0.5,
      "HTTP accept-loop poll interval; server shutdown latency is "
      "bounded by it.")
_knob("SW_FILER_TICK_S", "float", 1.0,
      "Filer background deletion/notification loop tick seconds.")
_knob("SW_HTTP_POOL_MAX_IDLE_S", "float", 60.0,
      "Idle age after which pooled keep-alive connections are evicted.")
_knob("SW_HTTP_PLANE_LIB", "str", None,
      "Override path to the native HTTP plane shared library (e.g. an "
      "ASAN build); must exist when set.")
_knob("SW_RETRY_BACKOFF_SCALE", "float", 1.0,
      "Multiplier on internal retry-backoff sleeps (uploads, streams, "
      "vid-map refresh, notification queues); 0 retries immediately.")
_knob("SW_CLUSTER_SCRAPE_S", "float", 15.0,
      "Master metrics-scrape sweep interval for /cluster/metrics.")
_knob("SW_REPAIR_INTERVAL_S", "float", 5.0,
      "Master repair-queue drain tick seconds; <= 0 disables the loop.")
_knob("SW_REPAIR_AT_RISK_SCORE", "float", 0.4,
      "Holder health score below which an advisory at_risk_holder "
      "incident is queued.")

# EC data path
_knob("SW_EC_SMALL_DISPATCH_BYTES", "int", 256 << 10,
      "Width below which device codecs answer reconstruct() on the "
      "host instead of dispatching.")
_knob("SW_EC_SMALL_DISPATCH_AUTO", "bool", False,
      "Let the tuner's fitted host/device crossover supersede "
      "SW_EC_SMALL_DISPATCH_BYTES live.")
_knob("SW_EC_MESH_SHARD_MIN_BYTES", "int", 1 << 20,
      "Slab payload bytes (k * width) below which the mesh backend "
      "dispatches on one device instead of sharding the width axis.")
_knob("SW_EC_MESH_WIDTH_DEVICES", "int", 0,
      "Cap on devices the mesh codec puts on its width axis; 0 uses "
      "every visible device.")
_knob("SW_EC_GATHER_WINDOW", "int", 4,
      "Bounded in-flight stripe prefetch window for streaming gathers.")
_knob("SW_EC_GATHER_MODE", "str", "stream",
      "ec.rebuild default transfer mode: stream or copy.")
_knob("SW_EC_HEDGE_MS", "float", 0.0,
      "Hedge a duplicate survivor range read after this many ms; 0 "
      "disables hedging.")
_knob("SW_EC_SPREAD_WINDOW", "int", 4,
      "Bounded per-target send-queue window for streaming encode "
      "spread.")
_knob("SW_EC_SPREAD_MODE", "str", "stream",
      "ec.encode default transfer mode: stream or copy.")
_knob("SW_EC_REPAIR_MODE", "str", "auto",
      "Single-shard rebuild mode: auto (layout-routed: piggyback on "
      "coupled layouts, else trace, with fallback), trace, piggyback, "
      "or full.")
_knob("SW_EC_LAYOUT", "str", "flat",
      "On-disk EC layout for NEW volumes: flat (plain RS) or piggyback "
      "(coupled sub-chunk parities; single-data-shard repair downloads "
      "(k+1)/2k of k*shard). Existing volumes keep their layout.")
_knob("SW_EC_PLAN_CACHE_SIZE", "int", 128,
      "LRU bound on each derived-plan cache (repair/piggyback plans); "
      "read live, so operators can resize without a restart.")
_knob("SW_EC_PIGGYBACK_PAIRS", "int", 5,
      "Cap on coupled data-shard pairs (alpha = 2^pairs sub-chunks); "
      "shards beyond the paired prefix repair via the flat paths.")
_knob("SW_EC_DEGRADED_CACHE_BYTES", "int", 64 << 20,
      "Byte budget of the reconstructed-slab LRU; 0 disables caching.")
_knob("SW_EC_DEGRADED_SLAB_BYTES", "int", 128 << 10,
      "Reconstructed-slab granularity of the degraded-read engine.")
_knob("SW_EC_DEGRADED_BATCH_MS", "float", 2.0,
      "Degraded-read leader coalescing window in milliseconds.")
_knob("SW_EC_DEGRADED_READ_TIMEOUT_S", "float", 10.0,
      "Per-holder budget for degraded-read survivor fetches.")
_knob("SW_EC_DEGRADED_READAHEAD_SLABS", "int", 1,
      "Neighbor slabs reconstructed per degraded batch beyond the "
      "requested range; 0 disables.")
_knob("SW_EC_DEGRADED_MODE", "str", "batch",
      "Degraded-read serving mode: batch (engine) or naive (per-read "
      "exactly-k fallback).")
_knob("SW_EC_SCRUB_RATE_MBPS", "float", 8.0,
      "Gather-bandwidth ceiling for a scrub pass; 0 disables pacing.")
_knob("SW_EC_SCRUB_IDLE_S", "float", 300.0,
      "Sleep between background scrub passes; <= 0 disables the loop "
      "(manual POST /admin/ec/scrub still works).")
_knob("SW_EC_SCRUB_SLAB_BYTES", "int", 1 << 20,
      "Scrub verification slab size in bytes.")
_knob("SW_TIER_ENABLE", "bool", False,
      "Master-leased background tierer: demote sealed replicated "
      "volumes to erasure-coded warm storage while they keep serving "
      "reads.")
_knob("SW_TIER_INTERVAL_S", "float", 60.0,
      "Sleep between tierer scans for demotion candidates; <= 0 "
      "disables the loop even with SW_TIER_ENABLE on.")
_knob("SW_TIER_AGE_S", "float", 3600.0,
      "Seconds a sealed volume must go unmodified before it is a "
      "demotion candidate (the f4 age threshold).")
_knob("SW_TIER_CONCURRENCY", "int", 1,
      "Volume demotions the tierer runs at once.")
_knob("SW_TIER_RATE_MBPS", "float", 8.0,
      "Encode+spread bandwidth ceiling per demotion so foreground "
      "traffic keeps its tail; 0 disables pacing.")
_knob("SW_TIER_FULL_FRAC", "float", 0.95,
      "Fraction of the volume size limit at which a still-writable "
      "volume counts as sealed for demotion purposes.")
_knob("SW_EC_HEALTH_REF_MS", "float", 50.0,
      "Holder fetch latency that scores 0.5 on the health board.")
_knob("SW_EC_HEALTH_ROUTING", "bool", False,
      "Consult holder health scores when routing gathers and choosing "
      "rebuild survivors.")
_knob("SW_EC_DEVICE_TIMING", "bool", False,
      "Sampled device-time attribution: every Nth EC dispatch is timed "
      "through block_until_ready; off adds zero clock reads.")
_knob("SW_EC_DEVICE_TIMING_SAMPLE", "int", 16,
      "Sample period for SW_EC_DEVICE_TIMING: one timed dispatch per N "
      "per entry point (1 times every dispatch).")
_knob("SW_EC_JIT_CACHE_SIZE", "int", 64,
      "lru_cache maxsize for the jitted EC kernel factories; an evicted "
      "entry recompiles on next use (visible in ec_xla_jit_cache_total).")

# debug / tooling
_knob("SW_PROFILE_DIR", "str", None,
      "Directory for jax.profiler traces; profiling is off when unset.")
_knob("SW_PROFILE_MAX_S", "float", 30.0,
      "Ceiling on POST /admin/profile?seconds=N sampling windows.")
_knob("SW_PLANE_STATS", "bool", True,
      "Native-plane telemetry (counters, latency histogram, slow ring); "
      "0 removes even the clock reads from the fast path.")
_knob("SW_PLANE_SLOW_US", "int", 10000,
      "Native-plane requests at or above this many microseconds enter "
      "the slow-request ring (GET /admin/plane/slow).")
_knob("SW_PLANE_CACHE_BYTES", "int", 32 << 20,
      "Byte budget of the native plane's reconstructed-slab cache; 0 "
      "disables the in-plane degraded fast path (lost-shard reads "
      "redirect to Python as before).")
_knob("SW_PLANE_FSYNC_MODE", "str", "off",
      "Write-durability mode for appends (plane AND Python fallback): "
      "off acks from the page cache, group amortizes one fdatasync per "
      "commit window over every rider before acking the batch, always "
      "fdatasyncs per append (the baseline group is measured against).")
_knob("SW_PLANE_FSYNC_BATCH_US", "int", 2000,
      "Group-commit window in microseconds: riders accumulate this "
      "long (or until SW_PLANE_FSYNC_MAX_PENDING) before the one "
      "covering fdatasync; p99 write latency absorbs at most one "
      "window.")
_knob("SW_PLANE_FSYNC_MAX_PENDING", "int", 512,
      "Riders that force a group commit before the window closes "
      "(bounds the pending-ack queue and the data at risk per batch).")
_knob("SW_LOCK_DEBUG", "bool", False,
      "Record the cross-thread lock-acquisition graph (util/locks.py) "
      "for deadlock detection; auto-on under pytest.")
_knob("SW_LOCK_GRAPH_DIR", "str", None,
      "Directory where instrumented processes dump their lock graph at "
      "exit for cross-process cycle checks.")

# bench.py drills
_knob("SW_BENCH_TRIALS", "int", 2,
      "Best-of trials per timed bench pass.")
_knob("SW_BENCH_DAT_MB", "int", 4096,
      "Bench volume size in MB for the headline configs.")
_knob("SW_BENCH_SLAB_MB", "int", 8,
      "Bench device slab per shard row in MB.")
_knob("SW_BENCH_INIT_TIMEOUT", "float", 180.0,
      "Seconds to wait for device backend init before falling back.")
_knob("SW_BENCH_INIT_RETRIES", "int", 5,
      "Legacy alias for SW_BENCH_DEVICE_INIT_RETRIES.")
_knob("SW_BENCH_DEVICE_INIT_RETRIES", "int", 5,
      "Device-init attempts before the CPU fallback is recorded.")
_knob("SW_BENCH_INIT_RETRY_TIMEOUT", "float", 120.0,
      "Per-attempt timeout for device-init retries.")
_knob("SW_BENCH_INIT_RETRY_SPACING", "float", 15.0,
      "Base spacing between device-init retries (doubles per attempt).")
_knob("SW_BENCH_INIT_RETRY_MAX_SPACING", "float", 120.0,
      "Cap on the exponential device-init retry spacing.")
_knob("SW_BENCH_DIR", "str", None,
      "Bench working directory (default: a fresh temp dir).")
_knob("SW_BENCH_KEEP", "bool", False,
      "Keep the bench working directory instead of deleting it.")
_knob("SW_BENCH_GEO_MB", "int", 256,
      "Volume MB for the RS-geometry sweep configs.")
_knob("SW_BENCH_SMALL_VOLS", "int", 4,
      "Volumes in the batched small-needle config.")
_knob("SW_BENCH_SMALL_NEEDLES", "int", 8192,
      "4 KB needles per volume in the batched small-needle config.")
_knob("SW_BENCH_CLUSTER_MB", "int", 256,
      "Volume MB for the live-cluster rebuild drill.")
_knob("SW_BENCH_CLUSTER_TPU_MB", "int", 64,
      "Volume MB for the TPU live-cluster rebuild drill.")
_knob("SW_BENCH_CLUSTER_SERVERS", "int", 4,
      "Volume servers in the live-cluster drills.")
_knob("SW_BENCH_CLUSTER_BACKEND", "str", "mesh",
      "EC backend for the live-cluster rebuild drill.")
_knob("SW_BENCH_DRILL_TIMEOUT", "float", 900.0,
      "Subprocess timeout for each cluster drill phase.")
_knob("SW_BENCH_DP_SECONDS", "float", 5.0,
      "Duration of each data-plane saturation pass.")
_knob("SW_BENCH_DP_CONNS", "int", 12,
      "Concurrent connections in the data-plane saturation pass.")
_knob("SW_BENCH_DP_DURABLE_SECONDS", "float", 2.0,
      "Duration of each durable-mode (fsync) data-plane trial; "
      "0 skips the durability trial set.")
_knob("SW_BENCH_DP_DURABLE_CONNS", "int", 128,
      "Concurrent connections in each durable-mode trial (all three "
      "modes share the load shape; group commit needs enough "
      "in-flight writers to accumulate riders per fsync).")
_knob("SW_BENCH_DP_CRASH_RUNS", "int", 3,
      "kill -9 crash-consistency drill runs in the data-plane bench; "
      "0 skips the drill.")
_knob("SW_BENCH_DP_DIR", "str", "",
      "Volume directory handed to the crash-drill child server.")
_knob("SW_BENCH_DP_MASTER", "str", "",
      "Master URL handed to the crash-drill child server.")
_knob("SW_BENCH_DEGRADED_NEEDLES", "int", 24,
      "Needles written for the degraded-read drill.")
_knob("SW_BENCH_DEGRADED_KB", "int", 64,
      "Needle KB for the degraded-read drill.")
_knob("SW_BENCH_DEGRADED_READERS", "int", 8,
      "Concurrent readers in the degraded-read drill.")
_knob("SW_BENCH_DEGRADED_ROUNDS", "int", 3,
      "Read rounds per phase in the degraded-read drill.")
_knob("SW_BENCH_DEGRADED_BACKEND", "str", "numpy",
      "EC backend for the degraded-read drill.")
_knob("SW_BENCH_SCRUB_VOLUMES", "int", 3,
      "EC volumes in the scrub/repair drill.")
_knob("SW_BENCH_SCRUB_NEEDLES", "int", 8,
      "Needles per volume in the scrub/repair drill.")
_knob("SW_BENCH_SCRUB_KB", "int", 64,
      "Needle KB in the scrub/repair drill.")
_knob("SW_BENCH_SCRUB_READERS", "int", 4,
      "Concurrent foreground readers in the scrub/repair drill.")
_knob("SW_BENCH_TIER_MB", "int", 8,
      "Volume size limit in MB for the write-through tiering drill.")
_knob("SW_BENCH_TIER_NEEDLES", "int", 32,
      "Needles written into the demotion-candidate volume.")
_knob("SW_BENCH_TIER_KB", "int", 64,
      "Needle KB in the tiering drill.")
_knob("SW_BENCH_TIER_READERS", "int", 4,
      "Concurrent foreground readers in the tiering drill.")
_knob("SW_BENCH_TIER_WRITERS", "int", 2,
      "Concurrent foreground writers in the tiering drill.")
_knob("SW_BENCH_TIER_RATE_MBPS", "float", 4.0,
      "SW_TIER_RATE_MBPS handed to the drill's tierer; kept below "
      "the unpaced streaming-spread throughput so the cap genuinely "
      "paces the demotion under the foreground load.")
_knob("SW_BENCH_DIFF", "bool", True,
      "Auto-diff each cluster drill record against the latest "
      "BENCH_r*.json via tools/bench_diff.py and exit 2 on >20% "
      "regressions.")

_UNSET = object()
_TRUTHY = ("1", "true", "yes", "on")


def _lookup(name: str, kind: str, fallback):
    knob = KNOBS.get(name)
    if knob is None:
        raise KeyError(
            f"env knob {name} is not registered in util/config.py — "
            f"declare it with _knob() (tools/analyze.py enforces this)")
    if knob.kind != kind:
        raise TypeError(
            f"env knob {name} is registered as {knob.kind}, read as "
            f"{kind}")
    raw = os.environ.get(name)
    default = knob.default if fallback is _UNSET else fallback
    return raw, default


def env_str(name: str, fallback=_UNSET) -> Optional[str]:
    raw, default = _lookup(name, "str", fallback)
    return raw if raw is not None else default


def env_int(name: str, fallback=_UNSET) -> Optional[int]:
    raw, default = _lookup(name, "int", fallback)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_float(name: str, fallback=_UNSET) -> Optional[float]:
    raw, default = _lookup(name, "float", fallback)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bool(name: str, fallback=_UNSET) -> bool:
    raw, default = _lookup(name, "bool", fallback)
    if raw is None:
        return bool(default)
    return raw.strip().lower() in _TRUTHY


def retry_backoff_s(seconds: float) -> float:
    """Internal retry-backoff sleeps route through here so one knob
    (SW_RETRY_BACKOFF_SCALE) can compress them — the tier-1 conftest
    zeroes it; a congested deployment can stretch it."""
    return max(0.0, seconds * env_float("SW_RETRY_BACKOFF_SCALE"))


def env_is_set(name: str) -> bool:
    """Whether the (registered) knob is explicitly set in the
    environment — for override-must-fail-loudly semantics."""
    _lookup(name, KNOBS[name].kind if name in KNOBS else "str", _UNSET)
    return name in os.environ


def env_table() -> str:
    """The README env-knob table, generated from the registry (one
    source of truth; tools/analyze.py fails when the committed copy is
    stale)."""
    rows = ["| Variable | Type | Default | Description |",
            "| --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(
            f"| `{k.name}` | {k.kind} | `{k.default_repr()}` | "
            f"{k.doc} |")
    return "\n".join(rows)


def _flatten(d: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in d.items():
        key = f"{prefix}{k}".lower()
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def find_config_file(name: str,
                     dirs: Optional[List[str]] = None) -> Optional[str]:
    for d in dirs or SEARCH_DIRS:
        for ext in (".toml", ".json"):
            p = os.path.join(d, name + ext)
            if os.path.isfile(p):
                return p
    return None


def _toml_module():
    """stdlib tomllib is 3.11+; on 3.10 fall back to a tomli copy —
    standalone if installed, else the one pip/setuptools vendor (same
    package tomllib was adopted from, identical load() API)."""
    try:
        import tomllib
        return tomllib
    except ImportError:
        pass
    try:
        import tomli
        return tomli
    except ImportError:
        from pip._vendor import tomli
        return tomli


def load_config(name: str, dirs: Optional[List[str]] = None,
                env: Optional[dict] = None) -> Dict[str, object]:
    """Flattened dotted-key config for <name>, {} when no file exists;
    WEED_* env vars always apply on top (a config can be pure env)."""
    cfg: Dict[str, object] = {}
    path = find_config_file(name, dirs)
    if path is not None:
        if path.endswith(".toml"):
            tomllib = _toml_module()
            with open(path, "rb") as f:
                cfg = _flatten(tomllib.load(f))
        else:
            with open(path) as f:
                cfg = _flatten(json.load(f))
    environ = os.environ if env is None else env
    for k, v in environ.items():
        if k.startswith(ENV_PREFIX):
            dotted = k[len(ENV_PREFIX):].lower().replace("_", ".")
            cfg[dotted] = v
    return cfg


def config_get(cfg: Dict[str, object], key: str, default=None):
    """Dotted lookup with underscore tolerance (env vars can't carry
    dots, so WEED_SECURITY_JWT_KEY and [security] jwt_key in TOML must
    land on the same value)."""
    key = key.lower()
    if key in cfg:
        return cfg[key]
    alt = key.replace("_", ".")
    return cfg.get(alt, default)
