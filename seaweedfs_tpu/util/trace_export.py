"""Merged Perfetto trace export (fleet health plane, half three).

A cluster ``ec.rebuild`` leaves its spans shredded across N servers'
in-process trace rings.  This module turns one trace's span dicts
(util.tracing ``Span.to_dict()`` shape) into Chrome trace-event JSON —
the ``{"traceEvents": [...]}`` format Perfetto and chrome://tracing
load directly — and merges per-node exports into one timeline:

  * every span becomes an "X" (complete) event, ``ts``/``dur`` in
    microseconds; each node becomes a Perfetto *process* with a
    ``process_name`` metadata event, and overlapping spans within a
    node spread across *thread* lanes so nothing stacks invisibly;
  * event ``args`` carry the original span/parent ids, node, and
    absolute wall start, so a merger can reconstruct span dicts from a
    node's export losslessly (``spans_from_chrome``);
  * node wall clocks disagree, so the merger estimates one offset per
    node from parent/child span overlap: a child span served by node B
    for a parent on node A must nest inside the parent, which bounds
    ``offset_B - offset_A`` to ``[parent.start - child.start,
    parent.end - child.end]``.  Offsets propagate by BFS from the root
    span's node (pinned at 0), preferring 0 inside the feasible
    interval and clamping to the nearest bound otherwise.

Stdlib only — this sits next to util.tracing and must import nothing
from the rest of the tree.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

CLIENT_NODE = "client"


def _span_end(s: Dict) -> float:
    return (s.get("start") or 0.0) + (s.get("duration_s") or 0.0)


def assign_nodes(spans: Sequence[Dict]) -> Dict[str, str]:
    """span_id -> node name.  Server spans are tagged with their node at
    creation; untagged spans (EC phases, client-side fetch spans)
    inherit the nearest tagged ancestor, and untagged roots — the shell
    process — fall back to "client"."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    out: Dict[str, str] = {}

    def resolve(sid: str, hops: int = 0) -> str:
        if sid in out:
            return out[sid]
        s = by_id.get(sid)
        if s is None:
            return CLIENT_NODE
        node = (s.get("tags") or {}).get("node")
        if not node:
            parent = s.get("parent_id")
            # hop cap guards a malformed parent cycle
            node = (resolve(parent, hops + 1)
                    if parent and hops < 64 else CLIENT_NODE)
        out[sid] = node
        return node

    for sid in by_id:
        resolve(sid)
    return out


def merge_spans(span_lists: Sequence[Sequence[Dict]]) -> List[Dict]:
    """Union per-node span lists, deduplicating by span_id (every node
    of an in-process test cluster shares one ring, so the same span
    arrives N times).  A copy that carries a node tag wins over one
    that doesn't."""
    by_id: Dict[str, Dict] = {}
    extras: List[Dict] = []
    for spans in span_lists:
        for s in spans or ():
            sid = s.get("span_id")
            if not sid:
                extras.append(s)
                continue
            prev = by_id.get(sid)
            if prev is None or (
                    not (prev.get("tags") or {}).get("node")
                    and (s.get("tags") or {}).get("node")):
                by_id[sid] = s
    merged = list(by_id.values()) + extras
    merged.sort(key=lambda s: (s.get("start") or 0.0))
    return merged


def estimate_node_offsets(spans: Sequence[Dict],
                          nodes: Optional[Dict[str, str]] = None
                          ) -> Dict[str, float]:
    """Per-node wall-clock offset (seconds to ADD to that node's
    timestamps) that makes cross-node child spans nest inside their
    parents.  The root span's node anchors the timeline at offset 0."""
    nodes = nodes if nodes is not None else assign_nodes(spans)
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}

    # collect feasible (lo, hi) bounds on offset[child] - offset[parent]
    # per directed node pair
    bounds: Dict[Tuple[str, str], List[float]] = {}
    for s in spans:
        pid = s.get("parent_id")
        if not pid or pid not in by_id:
            continue
        parent = by_id[pid]
        a = nodes.get(parent.get("span_id"), CLIENT_NODE)
        b = nodes.get(s.get("span_id"), CLIENT_NODE)
        if a == b:
            continue
        lo = (parent.get("start") or 0.0) - (s.get("start") or 0.0)
        hi = _span_end(parent) - _span_end(s)
        if hi < lo:     # child outlives parent (async tail): the start
            hi = lo     # constraint is the trustworthy one
        key = (a, b)
        cur = bounds.get(key)
        if cur is None:
            bounds[key] = [lo, hi]
        else:           # intersect; if empty, fall back to the
            cur[0] = max(cur[0], lo)        # tightest-start compromise
            cur[1] = min(cur[1], hi)
            if cur[1] < cur[0]:
                cur[1] = cur[0]

    adjacency: Dict[str, List[Tuple[str, float, float]]] = {}
    for (a, b), (lo, hi) in bounds.items():
        adjacency.setdefault(a, []).append((b, lo, hi))
        adjacency.setdefault(b, []).append((a, -hi, -lo))

    root = next((s for s in sorted(spans,
                                   key=lambda x: x.get("start") or 0.0)
                 if not s.get("parent_id")), None)
    root_node = (nodes.get(root["span_id"], CLIENT_NODE)
                 if root and root.get("span_id") else CLIENT_NODE)

    offsets: Dict[str, float] = {}
    all_nodes = sorted(set(nodes.values()))
    # BFS from the root node, then any still-unvisited component
    for seed in [root_node] + all_nodes:
        if seed in offsets:
            continue
        offsets[seed] = 0.0
        q = deque([seed])
        while q:
            a = q.popleft()
            for b, lo, hi in adjacency.get(a, ()):
                if b in offsets:
                    continue
                base = offsets[a]
                # prefer "no skew" when feasible, else nearest bound
                delta = 0.0 - base
                delta = min(max(delta, lo), hi)
                offsets[b] = base + delta
                q.append(b)
    return offsets


def chrome_trace_events(spans: Sequence[Dict],
                        offsets: Optional[Dict[str, float]] = None,
                        nodes: Optional[Dict[str, str]] = None) -> Dict:
    """Render span dicts as a Chrome trace-event JSON object."""
    spans = [s for s in spans if s.get("start") is not None]
    nodes = nodes if nodes is not None else assign_nodes(spans)
    offsets = offsets or {}

    def adj_start(s: Dict) -> float:
        node = nodes.get(s.get("span_id"), CLIENT_NODE)
        return (s.get("start") or 0.0) + offsets.get(node, 0.0)

    if spans:
        t0 = min(adj_start(s) for s in spans)
    else:
        t0 = 0.0

    node_order = sorted(set(nodes.values()) or {CLIENT_NODE})
    pid_of = {n: i + 1 for i, n in enumerate(node_order)}

    events: List[Dict] = []
    for node in node_order:
        events.append({"ph": "M", "name": "process_name",
                       "pid": pid_of[node], "tid": 0,
                       "args": {"name": node}})

    # greedy lane assignment per node so concurrent spans get their own
    # thread rows
    lanes: Dict[str, List[float]] = {}
    for s in sorted(spans, key=adj_start):
        node = nodes.get(s.get("span_id"), CLIENT_NODE)
        start = adj_start(s)
        dur = s.get("duration_s") or 0.0
        node_lanes = lanes.setdefault(node, [])
        tid = None
        for i, busy_until in enumerate(node_lanes):
            if start >= busy_until - 1e-9:
                tid = i
                node_lanes[i] = start + dur
                break
        if tid is None:
            tid = len(node_lanes)
            node_lanes.append(start + dur)
        events.append({
            "ph": "X",
            "name": s.get("name") or "?",
            "cat": "span",
            "pid": pid_of[node],
            "tid": tid + 1,
            "ts": round((start - t0) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "args": {
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "trace_id": s.get("trace_id"),
                "node": node,
                "start": s.get("start"),
                "duration_s": dur,
                "tags": dict(s.get("tags") or {}),
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spans_from_chrome(obj: Dict) -> List[Dict]:
    """Reconstruct span dicts from a per-node export's args — the
    lossless inverse of chrome_trace_events for merging."""
    spans = []
    for ev in obj.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if not args.get("span_id"):
            continue
        tags = dict(args.get("tags") or {})
        if args.get("node") and "node" not in tags:
            tags["node"] = args["node"]
        spans.append({
            "trace_id": args.get("trace_id"),
            "span_id": args["span_id"],
            "parent_id": args.get("parent_id"),
            "name": ev.get("name"),
            "start": args.get("start"),
            "duration_s": args.get("duration_s"),
            "tags": tags,
        })
    return spans


def merged_chrome_trace(span_lists: Sequence[Sequence[Dict]]) -> Dict:
    """Merge per-node span lists into one skew-normalized Chrome trace."""
    spans = merge_spans(span_lists)
    nodes = assign_nodes(spans)
    offsets = estimate_node_offsets(spans, nodes)
    out = chrome_trace_events(spans, offsets=offsets, nodes=nodes)
    out["metadata"] = {
        "nodes": sorted(set(nodes.values())),
        "clock_offsets_s": {n: round(o, 6)
                            for n, o in sorted(offsets.items())},
        "span_count": len(spans),
    }
    return out
