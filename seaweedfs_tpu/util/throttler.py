"""Write throttler (reference weed/util/throttler.go).

Vacuum/compaction copies gigabytes right next to live reads; the
reference rate-limits those writes with a bytes-per-second budget
(compactionBytePerSecond, weed/storage/volume_vacuum.go:37). Same
shape here: feed `maybe_slowdown(n)` after each write and it sleeps
whenever the running budget goes negative. 0 = unthrottled.
"""

from __future__ import annotations

import time


class WriteThrottler:
    WINDOW = 0.1  # budget granularity, seconds

    def __init__(self, bytes_per_second: int = 0):
        self.bps = int(bytes_per_second)
        self._budget = self.bps * self.WINDOW
        self._last = time.monotonic()

    def maybe_slowdown(self, n: int):
        if self.bps <= 0:
            return
        self._budget -= n
        if self._budget >= 0:
            return
        # refill from elapsed time; sleep off any remaining debt
        now = time.monotonic()
        self._budget += (now - self._last) * self.bps
        self._last = now
        if self._budget < 0:
            debt = -self._budget / self.bps
            slept = min(debt, 2.0)
            time.sleep(slept)
            # the sleep itself must not count as refill time on the
            # next call (that would halve the effective throttle), and
            # debt beyond the 2s cap CARRIES — forgiving it would let a
            # stream of large blobs run at a multiple of the limit
            self._last = time.monotonic()
            self._budget += slept * self.bps
