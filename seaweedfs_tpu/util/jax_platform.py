"""Workarounds for sitecustomize pre-importing jax with JAX_PLATFORMS=axon.

The environment imports jax at interpreter startup and bakes the platform
choice from the env at that moment, so later changes to JAX_PLATFORMS are
ignored unless ``jax.config`` is updated directly — and even that is
silently ignored once any backend has been initialized (jax's
``xla_bridge.backends()`` caches and the config value has no update hook
that clears it). These helpers are the single home for that dance; used by
``bench.py``, ``tests/conftest.py`` and ``__graft_entry__.py``.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def honor_platform_request() -> None:
    """Re-apply the JAX_PLATFORMS env request onto jax.config.

    Only effective before the first device touch of the process; call it
    before any ``jax.devices()`` / array creation. With no request set
    this is free — no jax import (CLI subcommands that never touch a
    device must not pay the multi-second import at startup).
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    jax.config.update("jax_platforms", want)


def set_host_device_count_flag(n: int, flags: Optional[str] = None) -> str:
    """Return XLA_FLAGS with the host-device-count flag forced to ``n``,
    replacing any existing value rather than keeping a stale one."""
    flags = os.environ.get("XLA_FLAGS", "") if flags is None else flags
    flags = re.sub(rf"{_COUNT_FLAG}=\d+", "", flags)
    return (flags.strip() + f" {_COUNT_FLAG}={n}").strip()


def force_cpu_devices(n: int):
    """Try to realize >= n virtual CPU devices in this process.

    Returns the jax device list on success, or None when the process's
    backends were already initialized on another platform (the caller
    should then fall back to a fresh subprocess). The driver env
    (JAX_PLATFORMS / XLA_FLAGS) is restored afterwards so later calls in
    the same process still see the original request.
    """
    old = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    os.environ["XLA_FLAGS"] = set_host_device_count_flag(n)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            devices = jax.devices()
        except Exception:  # noqa: BLE001 - backend init can fail many ways
            return None
        if devices and devices[0].platform == "cpu" and len(devices) >= n:
            return devices
        return None
    finally:
        for key, val in old.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
