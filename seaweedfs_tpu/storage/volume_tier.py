"""Tier move — ship a readonly volume's .dat to a remote backend.

Reference weed/storage/volume_tier.go + server/volume_grpc_tier_upload.go
/ _download.go: the .vif sidecar (reference: protobuf VolumeInfo; here:
JSON) records where the .dat lives; reads become range requests through
storage.backend.RemoteFile while the .idx and needle map stay local.
"""

from __future__ import annotations

import json
import os
import time

from .backend import RemoteFile, get_backend
from .volume import Volume, VolumeError


def vif_path(volume: Volume) -> str:
    return volume.file_name() + ".vif"


def save_volume_info(path: str, info: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f, indent=1)
    os.replace(tmp, path)


def load_volume_info(path: str):
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (ValueError, OSError):
        return None


def upload_dat(volume: Volume, spec: str, keep_local: bool = False) -> dict:
    """Copy the .dat to backend `spec`. The volume must already be
    readonly (the reference's tier.upload freezes it first — same
    discipline here). With keep_local the volume KEEPS serving reads
    from the local .dat and the remote copy is a parked duplicate;
    without it the local .dat is dropped and reads become range
    requests. The transfer itself runs outside volume.lock — the .dat
    is immutable while readonly, and holding the lock for a multi-GB
    WAN upload would stall every read and the heartbeat thread (which
    takes the same lock in size())."""
    with volume.lock:
        if not volume.readonly:
            raise VolumeError(
                f"volume {volume.id} must be readonly before tier upload")
        if isinstance(volume.dat, RemoteFile):
            raise VolumeError(f"volume {volume.id} is already remote")
        backend = get_backend(spec)
        volume.dat.flush()
        size = volume.size()
        key = os.path.basename(volume.dat_path)

    backend.upload_file(volume.dat_path, key)

    with volume.lock:
        if not volume.readonly:
            backend.delete(key)    # un-frozen mid-upload: abandon
            raise VolumeError(
                f"volume {volume.id} became writable during tier upload")
        # same .vif JSON shape the EC module writes ("version" = needle
        # version), plus the remote-tier pointer
        info = {
            "version": volume.version,
            "remote": {
                "backend": spec,
                "key": key,
                "file_size": size,
                "modified_at": int(time.time()),
            },
        }
        save_volume_info(vif_path(volume), info)
        if not keep_local:
            volume.dat.close()
            volume.dat = RemoteFile(backend, key, size)
            os.remove(volume.dat_path)
        return info


def download_dat(volume: Volume, delete_remote: bool = False) -> dict:
    """Bring a remote .dat back to local disk and drop the .vif. The
    network pull lands in a temp file outside volume.lock; only the
    swap is locked.

    A keep_local upload leaves the live .dat next to the .vif — the
    volume never stopped serving from disk, and the remote object is a
    parked duplicate. Un-tiering that volume must NOT pull the parked
    copy over the live file (a racing re-download would clobber the
    .dat another reader holds open); it only drops the .vif pointer
    (and optionally the remote object)."""
    info = load_volume_info(vif_path(volume))
    if not info or "remote" not in info:
        raise VolumeError(f"volume {volume.id} has no remote tier")
    remote = info["remote"]
    backend = get_backend(remote["backend"])

    with volume.lock:
        already_local = (os.path.exists(volume.dat_path)
                         and not isinstance(volume.dat, RemoteFile))
        if already_local:
            size = os.path.getsize(volume.dat_path)
            os.remove(vif_path(volume))
    if already_local:
        if delete_remote:
            backend.delete(remote["key"])
        return {"volume": volume.id, "size": size,
                "already_local": True}

    tmp = volume.dat_path + ".tierdl"
    try:
        got = backend.download_file(remote["key"], tmp)
        if got != remote["file_size"]:
            raise VolumeError(
                f"tier download size mismatch: {got} != "
                f"{remote['file_size']}")
        with volume.lock:
            os.replace(tmp, volume.dat_path)
            volume.dat.close()
            volume.dat = open(volume.dat_path, "r+b")
            os.remove(vif_path(volume))
    finally:
        if os.path.exists(tmp):    # failed pull leaves no junk behind
            os.remove(tmp)
    if delete_remote:
        backend.delete(remote["key"])
    return {"volume": volume.id, "size": got}
