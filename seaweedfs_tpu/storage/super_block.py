"""Volume superblock — first 8 bytes of every .dat file.

Byte-compatible with the reference (weed/storage/super_block/super_block.go):
byte 0 version, byte 1 replica placement, bytes 2-3 TTL, bytes 4-5
compaction revision, bytes 6-7 extra-size (unused here).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .types import CURRENT_VERSION, ReplicaPlacement, TTL

SUPER_BLOCK_SIZE = 8


class InvalidSuperBlock(Exception):
    pass


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compaction_revision: int = 0

    def to_bytes(self) -> bytes:
        return bytes([self.version & 0xFF,
                      self.replica_placement.to_byte()]) \
            + self.ttl.to_bytes() \
            + struct.pack(">H", self.compaction_revision) \
            + b"\x00\x00"

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise InvalidSuperBlock("short superblock")
        version = b[0]
        if version == 0 or version > CURRENT_VERSION:
            raise InvalidSuperBlock(f"unsupported volume version {version}")
        return cls(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=TTL.from_bytes(b[2:4]),
            compaction_revision=struct.unpack(">H", b[4:6])[0],
        )
