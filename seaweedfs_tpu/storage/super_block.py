"""Volume superblock — first 8 bytes of every .dat file.

Byte-compatible with the reference (weed/storage/super_block/super_block.go):
byte 0 version, byte 1 replica placement, bytes 2-3 TTL, bytes 4-5
compaction revision. Byte 6 (unused in the reference) carries volume
flags here: bit 0 marks 5-byte offsets (the reference makes that a
whole-binary build tag, types/offset_5bytes.go + Makefile:15; a
per-volume flag lets 8TB volumes coexist with wire-compatible 32GB
ones). Reference-written volumes have 0 there, so compatibility is
one-way safe.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .types import CURRENT_VERSION, OFFSET_SIZE, OFFSET_SIZE_5, \
    ReplicaPlacement, TTL

SUPER_BLOCK_SIZE = 8

FLAG_5_BYTE_OFFSETS = 0x01


class InvalidSuperBlock(Exception):
    pass


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compaction_revision: int = 0
    flags: int = 0

    @property
    def offset_width(self) -> int:
        return OFFSET_SIZE_5 if self.flags & FLAG_5_BYTE_OFFSETS \
            else OFFSET_SIZE

    def to_bytes(self) -> bytes:
        return bytes([self.version & 0xFF,
                      self.replica_placement.to_byte()]) \
            + self.ttl.to_bytes() \
            + struct.pack(">H", self.compaction_revision) \
            + bytes([self.flags & 0xFF, 0])

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise InvalidSuperBlock("short superblock")
        version = b[0]
        if version == 0 or version > CURRENT_VERSION:
            raise InvalidSuperBlock(f"unsupported volume version {version}")
        return cls(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=TTL.from_bytes(b[2:4]),
            compaction_revision=struct.unpack(">H", b[4:6])[0],
            flags=b[6],
        )
