"""storage — the Haystack-style needle/volume engine.

Disk formats are compatible with the reference (SeaweedFS v1.71):
  .dat  — superblock (8B) + append-only needles (weed/storage/needle)
  .idx  — 16-byte entries: NeedleId(8) Offset(4) Size(4), big-endian
  .vif  — volume info (JSON here; protobuf in the reference)
"""

from .types import (  # noqa: F401
    NEEDLE_ENTRY_SIZE, NEEDLE_PADDING_SIZE, TOMBSTONE_FILE_SIZE,
    NEEDLE_ID_SIZE, OFFSET_SIZE, SIZE_SIZE,
)
from .needle import Needle  # noqa: F401
from .volume import Volume  # noqa: F401
