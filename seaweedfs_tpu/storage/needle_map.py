"""Needle maps — in-memory needleId -> (offset, size) indexes.

The reference offers several variants (weed/storage/needle_map.go):
CompactMap (sectioned sorted arrays), LevelDB, sorted-file, and a btree
MemDb used for EC index sorting. Here:

  * NeedleMap        — dict-backed (Python dicts are compact open-addressing
                       tables; the CompactMap exists in the reference to
                       dodge Go GC overheads that don't apply here), plus
                       the same append-to-.idx write-through discipline
                       (reference needle_map.go:51 baseNeedleMapper).
  * MemDb            — sorted in-memory db for .idx -> .ecx sorting
                       (reference needle_map/memdb.go).

(The sorted-file binary search over 16B records lives with its only
consumer: ec/ec_volume.search_needle_from_sorted_index.)
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional, Tuple

from .types import (NEEDLE_ENTRY_SIZE, OFFSET_SIZE, TOMBSTONE_FILE_SIZE,
                    bytes_to_offset, bytes_to_needle_id, entry_size,
                    needle_id_to_bytes, offset_to_bytes)


def entry_to_bytes(nid: int, offset: int, size: int,
                   offset_width: int = OFFSET_SIZE) -> bytes:
    return needle_id_to_bytes(nid) + offset_to_bytes(offset, offset_width) \
        + struct.pack(">I", size)


def bytes_to_entry(b: bytes) -> Tuple[int, int, int]:
    """Record width implies the offset width (16 -> 4B, 17 -> 5B)."""
    return (bytes_to_needle_id(b[0:8]), bytes_to_offset(b[8:-4]),
            struct.unpack(">I", b[-4:])[0])


class NeedleValue:
    __slots__ = ("offset", "size")

    def __init__(self, offset: int, size: int):
        self.offset = offset
        self.size = size


class NeedleMap:
    """Write-through needle map: in-memory dict + append-only .idx log."""

    def __init__(self, idx_path: Optional[str] = None,
                 offset_width: int = OFFSET_SIZE):
        self._m: dict = {}
        self.idx_path = idx_path
        self.offset_width = offset_width
        self._idx_file = None
        self.file_counter = 0
        self.file_byte_counter = 0
        self.deletion_counter = 0
        self.deletion_byte_counter = 0
        self.maximum_file_key = 0
        if idx_path is not None:
            self._idx_file = open(idx_path, "ab")

    # -- loading -----------------------------------------------------------
    @classmethod
    def load(cls, idx_path: str,
             offset_width: int = OFFSET_SIZE) -> "NeedleMap":
        nm = cls.__new__(cls)
        nm._m = {}
        nm.idx_path = idx_path
        nm.offset_width = offset_width
        nm.file_counter = nm.file_byte_counter = 0
        nm.deletion_counter = nm.deletion_byte_counter = 0
        nm.maximum_file_key = 0
        if os.path.exists(idx_path):
            for nid, offset, size in walk_index_file(idx_path,
                                                     offset_width):
                nm._apply(nid, offset, size)
        nm._idx_file = open(idx_path, "ab")
        return nm

    def _apply(self, nid: int, offset: int, size: int):
        self.maximum_file_key = max(self.maximum_file_key, nid)
        if size != TOMBSTONE_FILE_SIZE and offset != 0:
            old = self._m.get(nid)
            self._m[nid] = NeedleValue(offset, size)
            self.file_counter += 1
            self.file_byte_counter += size
            if old is not None:
                self.deletion_counter += 1
                self.deletion_byte_counter += old.size
        else:
            old = self._m.pop(nid, None)
            if old is not None:
                self.deletion_counter += 1
                self.deletion_byte_counter += old.size

    # -- mutations ---------------------------------------------------------
    def put(self, nid: int, offset: int, size: int):
        self._apply(nid, offset, size)
        if self._idx_file is not None:
            self._idx_file.write(
                entry_to_bytes(nid, offset, size, self.offset_width))
            self._idx_file.flush()

    def delete(self, nid: int):
        """Tombstone: offset 0, size TOMBSTONE (reference appends an entry
        with size=TombstoneFileSize)."""
        old = self._m.pop(nid, None)
        if old is not None:
            self.deletion_counter += 1
            self.deletion_byte_counter += old.size
        if self._idx_file is not None:
            self._idx_file.write(
                entry_to_bytes(nid, 0, TOMBSTONE_FILE_SIZE,
                               self.offset_width))
            self._idx_file.flush()

    def get(self, nid: int) -> Optional[NeedleValue]:
        return self._m.get(nid)

    def __contains__(self, nid: int) -> bool:
        return nid in self._m

    def __len__(self) -> int:
        return len(self._m)

    def items(self) -> Iterator[Tuple[int, NeedleValue]]:
        return iter(self._m.items())

    @property
    def content_size(self) -> int:
        return self.file_byte_counter

    @property
    def deleted_size(self) -> int:
        return self.deletion_byte_counter

    def sync(self):
        """fdatasync the .idx append log — the Python write path's half
        of the SW_PLANE_FSYNC_MODE durability contract (the native
        plane's committer fdatasyncs the .idx it owns the same way)."""
        if self._idx_file is not None:
            os.fdatasync(self._idx_file.fileno())

    def close(self):
        if self._idx_file is not None:
            self._idx_file.close()
            self._idx_file = None


class MemDb:
    """Sorted needle db for building .ecx files (reference memdb.go)."""

    def __init__(self, offset_width: int = OFFSET_SIZE):
        self._m: dict = {}
        self.offset_width = offset_width

    def set(self, nid: int, offset: int, size: int):
        self._m[nid] = (offset, size)

    def delete(self, nid: int):
        self._m.pop(nid, None)

    def get(self, nid: int) -> Optional[Tuple[int, int]]:
        return self._m.get(nid)

    def ascending_visit(self):
        for nid in sorted(self._m):
            offset, size = self._m[nid]
            yield nid, offset, size

    @classmethod
    def load_from_idx(cls, idx_path: str,
                      offset_width: int = OFFSET_SIZE) -> "MemDb":
        db = cls(offset_width)
        for nid, offset, size in walk_index_file(idx_path, offset_width):
            if size != TOMBSTONE_FILE_SIZE and offset != 0:
                db.set(nid, offset, size)
            else:
                db.delete(nid)
        return db

    def save_to_idx(self, path: str):
        with open(path, "wb") as f:
            for nid, offset, size in self.ascending_visit():
                f.write(entry_to_bytes(nid, offset, size,
                                       self.offset_width))


def walk_index_file(idx_path: str, offset_width: int = OFFSET_SIZE):
    """Stream (needle_id, offset, size) from a .idx file — 16B records
    with 4-byte offsets, 17B with 5-byte
    (reference weed/storage/idx/walk.go:14)."""
    rec = entry_size(offset_width)
    with open(idx_path, "rb") as f:
        while True:
            chunk = f.read(rec * 1024)
            if not chunk:
                break
            for i in range(0, len(chunk) - rec + 1, rec):
                yield bytes_to_entry(chunk[i:i + rec])
