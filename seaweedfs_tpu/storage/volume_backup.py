"""Incremental volume sync — follow appends since a timestamp.

Reference weed/storage/volume_backup.go + weed/server/volume_grpc_tail.go:
the .idx is an append log, so for v3 volumes the needles' append-at
timestamps are monotone in index order. Binary-search the .idx for the
last *live* record at-or-before a given timestamp and ship raw .dat bytes
from just after it; tombstone records (whose idx entries carry offset 0
and so cannot be located directly) lie physically after that point and
ship with the stream — replaying an already-applied record is idempotent,
so over-shipping across a tombstone run is safe while under-shipping
would silently lose deletes. The receiver appends the bytes and replays
the appended region into its needle map; a tombstone record (size 0)
replays as a delete, mirroring the tombstones delete_needle appends.
"""

from __future__ import annotations

import os
import struct

from .needle import Needle, get_actual_size, padding_length
from .needle_map import bytes_to_entry
from .super_block import SUPER_BLOCK_SIZE
from .types import NEEDLE_PADDING_SIZE, TOMBSTONE_FILE_SIZE, VERSION3
from .volume import Volume, VolumeError

IDX_ENTRY_SIZE = 16

# server-side default page cap for /admin/volume/tail: an uncapped tail
# of a 30GB volume must not transit RAM in one Response body
DEFAULT_TAIL_PAGE_BYTES = 64 << 20


def walk_records(pread, version: int, start: int, end: int):
    """Yield (header_needle, offset, actual_size) for each raw record in
    [start, end). `pread(offset, size) -> bytes` is the only I/O needed,
    so the same walk serves a live Volume, a bare .dat file, and an
    in-memory blob — the record framing lives in exactly one place.
    Stops at a short tail."""
    offset = start
    while offset + 16 <= end:
        header = pread(offset, 16)
        if len(header) < 16:
            return
        n = Needle.parse_header(header)
        size = 0 if n.size == TOMBSTONE_FILE_SIZE else n.size
        actual = get_actual_size(size, version)
        if offset + actual > end:
            return
        yield n, offset, actual
        offset += actual


def _read_append_at_ns(volume: Volume, dat_offset: int) -> int:
    """append_at_ns of the needle record starting at dat_offset."""
    header = _pread(volume, dat_offset, 16)
    n = Needle.parse_header(header)
    size = 0 if n.size == TOMBSTONE_FILE_SIZE else n.size
    actual = get_actual_size(size, volume.version)
    # v3 record = header + data... + checksum + append_at_ns(8) + padding
    ts_off = dat_offset + actual - padding_length(size, volume.version) - 8
    blob = _pread(volume, ts_off, 8)
    return struct.unpack(">Q", blob)[0]


def _pread(volume: Volume, offset: int, size: int) -> bytes:
    with volume.lock:
        volume.dat.seek(offset)
        return volume.dat.read(size)


def _record_end(volume: Volume, offset: int, idx_size: int) -> int:
    """End offset of the .dat record that an idx entry points at."""
    size = 0 if idx_size == TOMBSTONE_FILE_SIZE else idx_size
    return offset + get_actual_size(size, volume.version)


class _IdxReader:
    """One open .idx handle for a whole search (probes are record-sized
    preads; 16B for 4-byte-offset volumes, 17B for 5-byte)."""

    def __init__(self, volume: Volume):
        from .types import entry_size
        self.rec = entry_size(volume.offset_width)
        self.f = open(volume.idx_path, "rb")
        self.total = os.path.getsize(volume.idx_path) // self.rec

    def entry(self, slot: int):
        self.f.seek(slot * self.rec)
        return bytes_to_entry(self.f.read(self.rec))

    def close(self):
        self.f.close()


def _probe_live_ns(volume: Volume, idx: _IdxReader, slot: int):
    """append_at_ns for idx slot, skipping tombstone entries (offset 0,
    whose .dat position is unknowable) forward to the next live record.
    Returns (ns, slot) or None when only tombstones remain."""
    while slot < idx.total:
        nid, offset, size = idx.entry(slot)
        if offset != 0:
            return _read_append_at_ns(volume, offset), slot
        slot += 1
    return None


def last_append_at_ns(volume: Volume) -> int:
    """Timestamp of the newest record, tombstones included (0 for an
    empty volume). Tombstone idx entries hide their .dat offset, so the
    run of records past the last live one — which is exactly the
    trailing tombstones — is walked forward in the .dat."""
    if volume.version != VERSION3:
        raise VolumeError("append timestamps need a v3 volume")
    idx = _IdxReader(volume)
    try:
        scan_from = SUPER_BLOCK_SIZE
        last_ns = 0
        for slot in range(idx.total - 1, -1, -1):
            nid, offset, size = idx.entry(slot)
            if offset != 0:
                last_ns = _read_append_at_ns(volume, offset)
                scan_from = _record_end(volume, offset, size)
                break
    finally:
        idx.close()
    pread = lambda off, size: _pread(volume, off, size)  # noqa: E731
    for n, offset, actual in walk_records(pread, volume.version,
                                          scan_from, volume.size()):
        last_ns = max(last_ns, _read_append_at_ns(volume, offset))
    return last_ns


def binary_search_append_at_ns(volume: Volume, since_ns: int) -> int:
    """Smallest .dat offset from which every record must be shipped to a
    follower synced through since_ns. This is the end of the last live
    record with append_at_ns <= since_ns — NOT the offset of the first
    newer live record, which would skip tombstone records appended in
    between (deletes would be silently lost).

    Reference volume_backup.go BinarySearchForAppendAtNs over the idx.
    """
    if volume.version != VERSION3:
        raise VolumeError("incremental sync needs a v3 volume")
    idx = _IdxReader(volume)
    try:
        # lo = first slot at/after which every live record is > since_ns
        lo, hi = 0, idx.total
        while lo < hi:
            mid = (lo + hi) // 2
            probe = _probe_live_ns(volume, idx, mid)
            if probe is None or probe[0] > since_ns:
                hi = mid
            else:
                lo = probe[1] + 1
        for slot in range(lo - 1, -1, -1):
            nid, offset, size = idx.entry(slot)
            if offset != 0:
                return _record_end(volume, offset, size)
        return SUPER_BLOCK_SIZE
    finally:
        idx.close()


def read_incremental(volume: Volume, since_ns: int,
                     max_bytes: int = 0) -> bytes:
    """Raw .dat bytes for every record appended after since_ns. A
    max_bytes cap ends on a record boundary so a paginating client can
    always apply what it received and resume from its new tail."""
    start = binary_search_append_at_ns(volume, since_ns)
    end = volume.size()
    if max_bytes and end - start > max_bytes:
        pread = lambda off, size: _pread(volume, off, size)  # noqa: E731
        cap = start
        for n, offset, actual in walk_records(pread, volume.version,
                                              start, end):
            if offset + actual - start > max_bytes:
                if cap == start:
                    # the first pending record alone exceeds the cap:
                    # ship it anyway, or pagination would return an
                    # empty page forever and the follower would silently
                    # stop advancing
                    cap = offset + actual
                break
            cap = offset + actual
        end = cap
    return _pread(volume, start, end - start)


def append_raw_records(volume: Volume, blob: bytes,
                       since_ns: int = None) -> tuple:
    """Receiver side: append raw record bytes and replay them into the
    needle map. Returns (records_applied, cursor_ns) where cursor_ns is
    the newest append-at time seen (the resume point for a paginating
    follower — last_append_at_ns(volume) alone cannot serve as cursor
    because tombstone idx entries hide their timestamps). Records are
    re-parsed (not blindly trusted): a short/garbled tail raises before
    anything is written. Records at/before since_ns (the sender
    over-ships across tombstone runs) are skipped."""
    if volume.readonly:
        raise VolumeError(f"volume {volume.id} is read only")
    if volume.version != VERSION3:
        raise VolumeError("incremental sync needs a v3 volume")
    local_last = last_append_at_ns(volume) if since_ns is None \
        else since_ns
    # parse first so a corrupt stream can't leave a torn tail
    records = []
    pos = 0
    pread = lambda off, size: blob[off:off + size]  # noqa: E731
    for n, offset, actual in walk_records(pread, volume.version,
                                          0, len(blob)):
        records.append(
            (Needle.from_bytes(blob[offset:offset + actual],
                               volume.version), offset, actual))
        pos = offset + actual
    if pos != len(blob):
        raise VolumeError(
            "truncated or garbled incremental record stream")
    cursor = max([local_last] + [n.append_at_ns for n, _, _ in records])
    fresh = [(n, rel, actual) for n, rel, actual in records
             if n.append_at_ns > local_last]
    if not fresh:
        return 0, cursor
    base_rel = fresh[0][1]
    blob = blob[base_rel:]
    with volume.lock:
        volume.dat.seek(0, os.SEEK_END)
        base = volume.dat.tell()
        if base % NEEDLE_PADDING_SIZE:
            base += NEEDLE_PADDING_SIZE - base % NEEDLE_PADDING_SIZE
            volume.dat.truncate(base)
        volume.dat.seek(base)
        volume.dat.write(blob)
        volume.dat.flush()
        for n, rel, actual in fresh:
            if n.size > 0:
                volume.nm.put(n.id, base + rel - base_rel, n.size)
            else:
                volume.nm.delete(n.id)
    return len(fresh), cursor


def rebuild_index(dat_path: str, idx_path: str) -> int:
    """Rebuild .idx from a .dat scan (reference weed/command/fix.go).
    Returns the number of records walked."""
    from .super_block import SuperBlock
    from .needle_map import entry_to_bytes
    with open(dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
        version = sb.version
        f.seek(0, os.SEEK_END)
        end = f.tell()

        def pread(off, size):
            f.seek(off)
            return f.read(size)

        width = sb.offset_width
        count = 0
        tmp = idx_path + ".tmp"
        with open(tmp, "wb") as idx:
            for n, offset, actual in walk_records(pread, version,
                                                  SUPER_BLOCK_SIZE, end):
                if n.size > 0:
                    idx.write(entry_to_bytes(n.id, offset, n.size, width))
                else:
                    idx.write(entry_to_bytes(n.id, 0, TOMBSTONE_FILE_SIZE,
                                             width))
                count += 1
    os.replace(tmp, idx_path)
    return count
