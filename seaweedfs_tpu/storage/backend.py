"""Tiered storage backends — the volume .dat behind an abstraction.

Reference weed/storage/backend/backend.go: `BackendStorageFile` is the
file-like the Volume reads/writes through (local disk by default), and
`BackendStorage` is a remote tier a readonly volume's .dat can be shipped
to (reference s3_backend/) while the .idx stays local and reads become
range requests. Backends are registered from config under dotted keys
like "s3.default" (reference master.toml [storage.backend.s3.default]).

This build ships three:
  * disk  — plain local file (the default data path)
  * dir   — another directory (cold disk / NFS tier); also the test tier
  * s3    — SigV4 client against any S3-compatible endpoint, including
            this framework's own S3 gateway
"""

from __future__ import annotations

import datetime
import hashlib
import io
import os
import shutil
import threading
from ..util.locks import make_lock
import urllib.parse
import urllib.request
from typing import Dict, Optional

EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class BackendError(Exception):
    """`status` carries the HTTP status when the failure was an HTTP
    response (0 otherwise) so callers branch on codes, not message
    text."""

    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = int(status)


# ---------------------------------------------------------------------------
# file-likes a Volume can own as .dat


class MemoryFile(io.BytesIO):
    """RAM-backed .dat (reference backend/memory_map, minus Windows)."""

    def __init__(self, data: bytes = b"", name: str = "<memory>"):
        super().__init__(data)
        self.name = name


class RemoteFile:
    """Read-only .dat living in a remote tier; seek/read are translated
    to range requests. Writes raise — a tiered volume is readonly, which
    Volume enforces before any write path can reach here."""

    def __init__(self, backend: "BackendStorage", key: str, size: int):
        self.backend = backend
        self.key = key
        self._size = size
        self._pos = 0
        self.name = f"{backend.spec()}/{key}"

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = pos
        elif whence == os.SEEK_CUR:
            self._pos += pos
        elif whence == os.SEEK_END:
            self._pos = self._size + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, size: int = -1) -> bytes:
        if size < 0:
            size = self._size - self._pos
        size = max(0, min(size, self._size - self._pos))
        if size == 0:
            return b""
        blob = self.backend.read_range(self.key, self._pos, size)
        self._pos += len(blob)
        return blob

    def write(self, blob: bytes):
        raise BackendError("remote-tier volume is read only")

    def truncate(self, size: int = None):
        raise BackendError("remote-tier volume is read only")

    def flush(self):
        pass

    def close(self):
        pass


# ---------------------------------------------------------------------------
# remote tiers


class BackendStorage:
    """A remote object tier: whole-file upload/download, ranged read."""

    kind = "?"

    def __init__(self, backend_id: str):
        self.id = backend_id

    def spec(self) -> str:
        return f"{self.kind}.{self.id}"

    def upload_file(self, path: str, key: str) -> int:
        raise NotImplementedError

    def download_file(self, key: str, path: str) -> int:
        raise NotImplementedError

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def size(self, key: str) -> int:
        """Size of the stored object; BackendError if it is missing."""
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError


class DirBackend(BackendStorage):
    """A directory as a tier — cold disk, NFS mount, test double."""

    kind = "dir"

    def __init__(self, backend_id: str, path: str):
        super().__init__(backend_id)
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _p(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.path, safe)

    def upload_file(self, path: str, key: str) -> int:
        shutil.copyfile(path, self._p(key))
        return os.path.getsize(self._p(key))

    def download_file(self, key: str, path: str) -> int:
        shutil.copyfile(self._p(key), path)
        return os.path.getsize(path)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def size(self, key: str) -> int:
        try:
            return os.path.getsize(self._p(key))
        except OSError as e:
            raise BackendError(f"{self.spec()}/{key}: {e}",
                               status=404) from None

    def delete(self, key: str):
        p = self._p(key)
        if os.path.exists(p):
            os.remove(p)


class S3Backend(BackendStorage):
    """Minimal SigV4 S3 client (PUT/GET/Range GET/DELETE) — enough to
    park volume .dat files on any S3-compatible store, including this
    framework's own gateway (reference backend/s3_backend uses the AWS
    SDK; the wire behavior here is the same four calls)."""

    kind = "s3"

    def __init__(self, backend_id: str, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        super().__init__(backend_id)
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.startswith("http"):
            self.endpoint = "http://" + self.endpoint
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    # -- signing ----------------------------------------------------------
    def _request(self, method: str, key: str, body=b"",
                 extra_headers: Optional[Dict[str, str]] = None,
                 payload_hash: Optional[str] = None,
                 stream_to: Optional[str] = None,
                 want_headers: bool = False):
        """body may be bytes or a (file_object, length) pair — volume
        .dat files must stream, not transit RAM. With stream_to set the
        response body is written to that path and the return is b''.
        With want_headers the return is the response header dict
        instead of the body (HEAD probes)."""
        from ..s3.auth import authorization_header_v4
        parsed = urllib.parse.urlparse(self.endpoint)
        # sign the path exactly as sent on the wire, including any
        # endpoint path prefix (path-style gateways, local test stores)
        path = (parsed.path.rstrip("/")
                + f"/{self.bucket}/{urllib.parse.quote(key)}")
        url = f"{parsed.scheme}://{parsed.netloc}" + path
        host = parsed.netloc
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        body_file = body_len = None
        if isinstance(body, tuple):
            body_file, body_len = body
        if payload_hash is None:
            if body_file is not None:
                h = hashlib.sha256()
                while True:
                    chunk = body_file.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
                body_file.seek(0)
                payload_hash = h.hexdigest()
            else:
                payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        if body_file is not None:
            headers["content-length"] = str(body_len)
        if extra_headers:
            headers.update({k.lower(): v for k, v in
                            extra_headers.items()})
        headers["Authorization"] = authorization_header_v4(
            method, path, headers, payload_hash, self.access_key,
            self.secret_key, self.region, "s3", amz_date)
        data = body_file if body_file is not None else (body or None)
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                if want_headers:
                    return dict(resp.headers)
                if stream_to is None:
                    return resp.read()
                with open(stream_to, "wb") as out:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            return b""
                        out.write(chunk)
        except urllib.error.HTTPError as e:
            raise BackendError(
                f"{method} {url}: {e.code} "
                f"{e.read().decode('utf-8', 'replace')[:200]}",
                status=e.code) from None
        except urllib.error.URLError as e:
            raise BackendError(f"{method} {url}: {e}") from None
        except OSError as e:
            # mid-stream timeout/reset after headers — urllib raises the
            # raw socket error, not URLError
            raise BackendError(f"{method} {url}: {e}") from None

    # -- tier ops ---------------------------------------------------------
    def upload_file(self, path: str, key: str) -> int:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            self._request("PUT", key, (f, size))
        return size

    def download_file(self, key: str, path: str) -> int:
        self._request("GET", key, payload_hash=EMPTY_SHA256,
                      stream_to=path)
        return os.path.getsize(path)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        return self._request(
            "GET", key, payload_hash=EMPTY_SHA256,
            extra_headers={"Range":
                           f"bytes={offset}-{offset + size - 1}"})

    def size(self, key: str) -> int:
        hdrs = self._request("HEAD", key, payload_hash=EMPTY_SHA256,
                             want_headers=True)
        length = next((v for k, v in hdrs.items()
                       if k.lower() == "content-length"), None)
        if length is None:
            raise BackendError(
                f"HEAD {self.spec()}/{key}: no Content-Length")
        return int(length)

    def delete(self, key: str):
        self._request("DELETE", key, payload_hash=EMPTY_SHA256)


# ---------------------------------------------------------------------------
# registry (reference backend.go InitBackendStorages from config)

_registry: Dict[str, BackendStorage] = {}
_registry_lock = make_lock("backend._registry_lock")

_KINDS = {"dir": DirBackend, "s3": S3Backend}


def configure_backends(cfg: Dict[str, Dict[str, dict]]):
    """cfg = {"s3": {"default": {...kwargs}}, "dir": {"cold": {...}}} —
    the shape of the reference's [storage.backend.<kind>.<id>] TOML."""
    with _registry_lock:
        for kind, ids in cfg.items():
            if kind not in _KINDS:
                raise BackendError(f"unknown backend kind {kind!r}")
            for backend_id, kwargs in ids.items():
                _registry[f"{kind}.{backend_id}"] = \
                    _KINDS[kind](backend_id, **kwargs)


def get_backend(spec: str) -> BackendStorage:
    """spec is '<kind>.<id>', e.g. 's3.default'."""
    with _registry_lock:
        b = _registry.get(spec)
    if b is None:
        raise BackendError(f"backend {spec!r} not configured")
    return b


def clear_backends():
    with _registry_lock:
        _registry.clear()
