"""DiskLocation — one data directory holding volumes and EC shards.

Reference weed/storage/disk_location.go + disk_location_ec.go: scans the
directory on boot, loading every .idx/.dat volume and every .ecx/.ecNN
shard set.
"""

from __future__ import annotations

import os
import re
import threading
from ..util.locks import make_rlock
from typing import Dict, Optional

from ..ec.ec_volume import EcVolume
from .volume import Volume

_VOL_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.idx$")
_ECX_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ecx$")
_EC_SHARD_RE = re.compile(
    r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ec(?P<shard>\d{2})$")


class DiskLocation:
    def __init__(self, directory: str, max_volume_count: int = 7,
                 index_kind: str = "memory"):
        self.directory = os.path.abspath(directory)
        self.max_volume_count = max_volume_count
        self.index_kind = index_kind  # needle-map variant for new loads
        self.volumes: Dict[int, Volume] = {}
        self.ec_volumes: Dict[int, EcVolume] = {}
        self.lock = make_rlock("disk_location.lock")
        os.makedirs(self.directory, exist_ok=True)

    # -- boot scan ---------------------------------------------------------
    def load_existing_volumes(self):
        with self.lock:
            for fname in sorted(os.listdir(self.directory)):
                m = _VOL_RE.match(fname)
                if not m:
                    continue
                vid = int(m.group("vid"))
                collection = m.group("collection") or ""
                base = os.path.join(self.directory, fname[: -len(".idx")])
                # .dat may be absent for a tiered volume whose .vif
                # points at a remote backend
                if not os.path.exists(base + ".dat") and \
                        not os.path.exists(base + ".vif"):
                    continue
                if vid not in self.volumes:
                    try:
                        self.volumes[vid] = Volume(
                            self.directory, collection, vid,
                            index_kind=self.index_kind)
                    except Exception:
                        continue  # quarantine unloadable volumes

    def load_all_ec_shards(self):
        with self.lock:
            shard_sets: Dict[int, tuple] = {}
            for fname in sorted(os.listdir(self.directory)):
                m = _EC_SHARD_RE.match(fname)
                if not m:
                    continue
                vid = int(m.group("vid"))
                shard_sets.setdefault(
                    vid, (m.group("collection") or "", []))[1].append(
                    int(m.group("shard")))
            for vid, (collection, shards) in shard_sets.items():
                base = os.path.join(
                    self.directory,
                    f"{collection}_{vid}" if collection else str(vid))
                if not os.path.exists(base + ".ecx"):
                    continue
                try:
                    ev = EcVolume(self.directory, collection, vid)
                    for sid in sorted(shards):
                        ev.add_shard(sid)
                    self.ec_volumes[vid] = ev
                except Exception:
                    continue

    # -- volume management -------------------------------------------------
    def load_volume(self, vid: int) -> Optional[Volume]:
        """Mount one on-disk volume by id, whatever collection prefixes
        its files — the boot scan's matching and .dat/.vif guard, for a
        single id, entirely under the location lock (so a concurrent
        mount can't double-open and leak the first handle set). Returns
        the (possibly already-mounted) Volume, or None when no loadable
        files exist."""
        with self.lock:
            existing = self.volumes.get(vid)
            if existing is not None:
                return existing
            for fname in sorted(os.listdir(self.directory)):
                m = _VOL_RE.match(fname)
                if not m or int(m.group("vid")) != vid:
                    continue
                base = os.path.join(self.directory, fname[: -len(".idx")])
                if not os.path.exists(base + ".dat") and \
                        not os.path.exists(base + ".vif"):
                    continue  # orphaned .idx: same quarantine as boot
                v = Volume(self.directory, m.group("collection") or "",
                           vid, index_kind=self.index_kind)
                self.volumes[vid] = v
                return v
            return None

    def add_volume(self, collection: str, vid: int, **kwargs) -> Volume:
        with self.lock:
            if vid in self.volumes:
                return self.volumes[vid]
            kwargs.setdefault("index_kind", self.index_kind)
            v = Volume(self.directory, collection, vid, create=True, **kwargs)
            self.volumes[vid] = v
            return v

    def get_volume(self, vid: int) -> Optional[Volume]:
        return self.volumes.get(vid)

    def delete_volume(self, vid: int) -> bool:
        with self.lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            v.destroy()
            return True

    def unload_volume(self, vid: int) -> bool:
        with self.lock:
            v = self.volumes.pop(vid, None)
            if v is None:
                return False
            v.close()
            return True

    def close(self):
        with self.lock:
            for v in self.volumes.values():
                v.close()
            for ev in self.ec_volumes.values():
                ev.close()
            self.volumes.clear()
            self.ec_volumes.clear()
