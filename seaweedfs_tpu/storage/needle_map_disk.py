"""Disk-backed writable needle map (`-index disk`).

The reference's LevelDB needle map (weed/storage/needle_map_leveldb.go:
15-120) lets a volume whose .idx outgrows RAM boot with an on-disk keyed
store: lookups hit the db, puts/deletes write through to both the .idx
log and the db, and a restart reopens the db instead of replaying the
whole index into memory. This is the same design on sqlite3 (stdlib —
the image has no LevelDB), organized as a log + checkpoint:

  * the .idx file stays the durable, append-only source of truth
    (identical bytes to every other map variant);
  * `<base>.ndb` is a sqlite checkpoint of the live needle set plus the
    counters, valid up to a recorded .idx byte watermark;
  * boot replays only the .idx TAIL past the watermark (append-only log
    ⇒ an interrupted session costs a bounded catch-up, not a full
    replay; a truncated/rewritten .idx — vacuum — forces a rebuild).

Memory stays bounded by sqlite's page cache plus one replay batch
(64k records), never by needle count.
"""

from __future__ import annotations

import os
import sqlite3
from typing import Iterator, Optional, Tuple

from .needle_map import NeedleValue, entry_to_bytes
from .types import OFFSET_SIZE, TOMBSTONE_FILE_SIZE, bytes_to_needle_id, \
    bytes_to_offset

_BATCH = 65536          # replay records per batch (bounds replay RAM)
_COMMIT_EVERY = 512     # runtime mutations per durable checkpoint
_IN_CHUNK = 900         # keys per IN (...) probe (portable var limit)

_COUNTER_KEYS = ("file_counter", "file_byte_counter", "deletion_counter",
                 "deletion_byte_counter", "maximum_file_key")


def _s64(nid: int) -> int:
    """uint64 needle id -> sqlite's signed INTEGER domain."""
    return nid - (1 << 64) if nid >= (1 << 63) else nid


def _u64(nid: int) -> int:
    return nid + (1 << 64) if nid < 0 else nid


class _SnapshotCursor:
    """Closeable iterator over a pinned WAL snapshot (items_snapshot).
    Closes the private connection on exhaustion, on close(), or on
    context-manager exit — whichever comes first; close is idempotent.
    __del__ is only the last-resort backstop for leaked handles."""

    def __init__(self, db, cur, first):
        self._db, self._cur, self._row = db, cur, first

    def __iter__(self):
        return self

    def __next__(self):
        row = self._row
        if row is None:
            self.close()
            raise StopIteration
        self._row = self._cur.fetchone()
        return _u64(row[0]), NeedleValue(row[1], row[2])

    def close(self):
        db, self._db = self._db, None
        self._row = None
        if db is not None:
            db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        self.close()


class DiskNeedleMap:
    """sqlite-checkpointed needle map; API-compatible with NeedleMap."""

    kind = "disk"

    def __init__(self, idx_path: str,
                 offset_width: int = OFFSET_SIZE):
        self.idx_path = idx_path
        self.offset_width = offset_width
        self.db_path = os.path.splitext(idx_path)[0] + ".ndb"
        self.file_counter = 0
        self.file_byte_counter = 0
        self.deletion_counter = 0
        self.deletion_byte_counter = 0
        self.maximum_file_key = 0
        self._dirty = 0
        # server handler threads share the map under the volume lock;
        # sqlite's own same-thread assertion must not second-guess that
        self._db = sqlite3.connect(self.db_path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute("CREATE TABLE IF NOT EXISTS needles("
                         "nid INTEGER PRIMARY KEY, off INTEGER, "
                         "size INTEGER)")
        self._db.execute("CREATE TABLE IF NOT EXISTS meta("
                         "key TEXT PRIMARY KEY, value INTEGER)")
        self._catch_up()
        self._idx_file = open(idx_path, "ab")

    @classmethod
    def load(cls, idx_path: str,
             offset_width: int = OFFSET_SIZE) -> "DiskNeedleMap":
        return cls(idx_path, offset_width)

    # -- boot: checkpoint + tail replay ------------------------------------
    def _meta_get(self, key: str, default: int = 0) -> int:
        row = self._db.execute("SELECT value FROM meta WHERE key=?",
                               (key,)).fetchone()
        return default if row is None else int(row[0])

    def _tail_crc(self, end: int, span: int) -> int:
        """crc32 of .idx bytes [end-span, end) — the checkpoint's content
        fingerprint. Size alone can't tell an appended-to .idx from a
        REWRITTEN one that happens to be as long (offline compact/fix
        replace the file under a live checkpoint's feet)."""
        import zlib
        if span <= 0:
            return 0
        with open(self.idx_path, "rb") as f:
            f.seek(end - span)
            return zlib.crc32(f.read(span))

    def _catch_up(self):
        idx_size = os.path.getsize(self.idx_path) \
            if os.path.exists(self.idx_path) else 0
        entry = 12 + self.offset_width
        if idx_size % entry:
            # torn trailing record: TRUNCATE it away (not just skip it)
            # — the append handle writes at the physical end, and a
            # half-record left in place would shift-misframe every
            # later record for all future replays
            idx_size -= idx_size % entry
            with open(self.idx_path, "r+b") as f:
                f.truncate(idx_size)
        watermark = self._meta_get("idx_size", -1)
        stale = watermark < 0 or watermark > idx_size or \
            self._meta_get("offset_width", 0) != self.offset_width
        if not stale and watermark > 0:
            span = self._meta_get("tail_span", 0)
            if span > watermark or \
                    self._meta_get("tail_crc", -1) != \
                    self._tail_crc(watermark, span):
                stale = True          # same-or-longer .idx, new content
        if stale:
            # no checkpoint, the .idx shrank (vacuum rewrote it), the
            # content under the watermark changed (rewritten in place),
            # or the record geometry changed: rebuild from scratch
            self._db.execute("DELETE FROM needles")
            self._db.execute("DELETE FROM meta")
            watermark = 0
        else:
            for k in _COUNTER_KEYS:
                setattr(self, k, self._meta_get(k))
        if watermark < idx_size:
            self._replay_range(watermark, idx_size)
        # _applied = .idx byte position the db state is complete through.
        # The checkpoint watermark must NEVER run ahead of it: the native
        # write lease appends .idx records behind this map's back
        # (volume.py fast_writer bypass), and stamping getsize() would
        # declare those bytes ingested when they never were — silently
        # losing every needle written during the lease.
        self._applied = idx_size
        self._checkpoint(idx_size)

    def _replay_range(self, start: int, end: int):
        entry = 12 + self.offset_width
        with open(self.idx_path, "rb") as f:
            f.seek(start)
            remaining = end - start
            while remaining > 0:
                chunk = f.read(min(remaining, _BATCH * entry))
                if not chunk:
                    break
                remaining -= len(chunk)
                self._apply_batch(chunk)

    def _apply_batch(self, chunk: bytes):
        """Exact counter semantics of NeedleMap._apply, one db probe per
        distinct key per batch instead of one per record."""
        entry = 12 + self.offset_width
        recs = []
        for i in range(0, len(chunk) - entry + 1, entry):
            b = chunk[i:i + entry]
            recs.append((bytes_to_needle_id(b[0:8]),
                         bytes_to_offset(b[8:8 + self.offset_width]),
                         int.from_bytes(b[-4:], "big")))
        # prior state of every key touched by this batch
        keys = list({_s64(nid) for nid, _, _ in recs})
        prior = {}
        for j in range(0, len(keys), _IN_CHUNK):
            part = keys[j:j + _IN_CHUNK]
            q = ",".join("?" * len(part))
            for nid_s, off, size in self._db.execute(
                    f"SELECT nid, off, size FROM needles "
                    f"WHERE nid IN ({q})", part):
                prior[_u64(nid_s)] = (off, size)
        pending = {}                       # nid -> (off,size) or None=dead
        for nid, off, size in recs:
            self.maximum_file_key = max(self.maximum_file_key, nid)
            old = pending[nid] if nid in pending else prior.get(nid)
            if size != TOMBSTONE_FILE_SIZE and off != 0:
                pending[nid] = (off, size)
                self.file_counter += 1
                self.file_byte_counter += size
                if old is not None:
                    self.deletion_counter += 1
                    self.deletion_byte_counter += old[1]
            else:
                pending[nid] = None
                if old is not None:
                    self.deletion_counter += 1
                    self.deletion_byte_counter += old[1]
        self._db.executemany(
            "INSERT INTO needles(nid, off, size) VALUES(?,?,?) "
            "ON CONFLICT(nid) DO UPDATE SET off=excluded.off, "
            "size=excluded.size",
            [(_s64(nid), v[0], v[1]) for nid, v in pending.items()
             if v is not None])
        self._db.executemany(
            "DELETE FROM needles WHERE nid=?",
            [(_s64(nid),) for nid, v in pending.items() if v is None])

    def _checkpoint(self, idx_size: Optional[int] = None):
        if idx_size is None:
            self._idx_file.flush()
            # NOT getsize(): see _applied — externally appended (write
            # lease) records stay past the watermark so the next boot's
            # tail replay ingests them
            idx_size = self._applied
        state = {k: getattr(self, k) for k in _COUNTER_KEYS}
        state["idx_size"] = idx_size
        state["offset_width"] = self.offset_width
        span = min(4096, idx_size)
        state["tail_span"] = span
        state["tail_crc"] = self._tail_crc(idx_size, span)
        self._db.executemany(
            "INSERT INTO meta(key, value) VALUES(?,?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            list(state.items()))
        self._db.commit()
        self._dirty = 0

    # -- mutations (write-through: .idx log + db) --------------------------
    def _maybe_checkpoint(self):
        self._dirty += 1
        if self._dirty >= _COMMIT_EVERY:
            self._checkpoint()

    def _append_entry(self, raw: bytes) -> bool:
        """Append one .idx record; returns True when the caller should
        direct-apply it (the common case). If foreign bytes landed
        between _applied and our record (native lease interleave), the
        whole gap INCLUDING our record is ingested via replay instead —
        exact counters, no double-apply — and False is returned."""
        self._idx_file.write(raw)
        self._idx_file.flush()
        pos = self._idx_file.tell()
        if pos - len(raw) == self._applied:
            self._applied = pos
            return True
        self._replay_range(self._applied, pos)
        self._applied = pos
        return False

    def put(self, nid: int, offset: int, size: int):
        direct = self._append_entry(
            entry_to_bytes(nid, offset, size, self.offset_width))
        if direct:
            old = self.get(nid)
            self.maximum_file_key = max(self.maximum_file_key, nid)
            if size != TOMBSTONE_FILE_SIZE and offset != 0:
                self._db.execute(
                    "INSERT INTO needles(nid, off, size) VALUES(?,?,?) "
                    "ON CONFLICT(nid) DO UPDATE SET off=excluded.off, "
                    "size=excluded.size", (_s64(nid), offset, size))
                self.file_counter += 1
                self.file_byte_counter += size
                if old is not None:
                    self.deletion_counter += 1
                    self.deletion_byte_counter += old.size
            else:
                self._db.execute("DELETE FROM needles WHERE nid=?",
                                 (_s64(nid),))
                if old is not None:
                    self.deletion_counter += 1
                    self.deletion_byte_counter += old.size
        self._maybe_checkpoint()

    def delete(self, nid: int):
        direct = self._append_entry(
            entry_to_bytes(nid, 0, TOMBSTONE_FILE_SIZE,
                           self.offset_width))
        if direct:
            old = self.get(nid)
            if old is not None:
                self.deletion_counter += 1
                self.deletion_byte_counter += old.size
                self._db.execute("DELETE FROM needles WHERE nid=?",
                                 (_s64(nid),))
        self._maybe_checkpoint()

    # -- lookups -----------------------------------------------------------
    def get(self, nid: int) -> Optional[NeedleValue]:
        row = self._db.execute(
            "SELECT off, size FROM needles WHERE nid=?",
            (_s64(nid),)).fetchone()
        return None if row is None else NeedleValue(row[0], row[1])

    def __contains__(self, nid: int) -> bool:
        return self.get(nid) is not None

    def __len__(self) -> int:
        return self._db.execute(
            "SELECT COUNT(*) FROM needles").fetchone()[0]

    def flush(self):
        """Commit pending mutations and advance the checkpoint — public
        hook for callers about to read the db from another connection
        (vacuum's snapshot) or to snapshot the .idx watermark."""
        self._checkpoint()

    def items_snapshot(self,
                       by_offset: bool = False
                       ) -> Iterator[Tuple[int, NeedleValue]]:
        """Stream the live set from a PRIVATE connection (WAL snapshot
        isolation): callers walk millions of needles without
        materializing the index in RAM — the reason this map variant
        exists. Call flush() first so the snapshot includes every
        acknowledged mutation (snapshot_live_items does both).

        by_offset=True adds the ORDER BY the vacuum merge-walk needs
        (a whole-table sort — `off` has no index); order-insensitive
        callers (native-plane bulk load, fsck) stream in PK order
        free of that cost.

        The snapshot is pinned EAGERLY (first row fetched before this
        returns), so a caller holding the volume lock gets a view of
        exactly now — anything committed after the lock releases stays
        out of the snapshot and is replayed by the vacuum makeup diff
        instead of being copied twice.

        The returned cursor closes its connection when exhausted, but a
        caller that stops early (merge-walk break, exception) would
        otherwise pin the WAL until GC — preventing checkpoint
        truncation for the volume's lifetime. close() is explicit and
        idempotent; use the cursor as a context manager (or close() in
        a finally) for a deterministic release."""
        db = sqlite3.connect(self.db_path, check_same_thread=False)
        cur = db.execute("SELECT nid, off, size FROM needles"
                         + (" ORDER BY off" if by_offset else ""))
        first = cur.fetchone()            # pins the WAL read snapshot
        return _SnapshotCursor(db, cur, first)

    def items(self) -> Iterator[Tuple[int, NeedleValue]]:
        # NOT snapshot-consistent: this cursor shares the mutating
        # connection, and sqlite may skip/repeat rows if the table
        # changes mid-iteration — callers needing a stable view under
        # concurrent writes must use items_snapshot() (own connection)
        # via compact_map.snapshot_live_items
        cur = self._db.cursor()
        for nid_s, off, size in cur.execute(
                "SELECT nid, off, size FROM needles ORDER BY nid"):
            yield _u64(nid_s), NeedleValue(off, size)

    @property
    def content_size(self) -> int:
        return self.file_byte_counter

    @property
    def deleted_size(self) -> int:
        return self.deletion_byte_counter

    def close(self):
        if self._idx_file is not None:
            self._checkpoint()
            self._idx_file.close()
            self._idx_file = None
        if self._db is not None:
            self._db.close()
            self._db = None
