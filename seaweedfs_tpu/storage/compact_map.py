"""RAM-bounded needle maps (reference weed/storage/needle_map/).

The dict-backed NeedleMap costs ~100+ bytes of heap per needle; a 30GB
volume of 4KB needles (~7.5M needles) would pin GBs of RAM per volume.
The reference solves this with CompactMap (sectioned sorted arrays,
compact_map.go:10-37) and a sorted-file map backed by disk
(needle_map_sorted_file.go). The numpy-native equivalents here:

  * CompactNeedleMap — three parallel sorted numpy columns
    (nid u8, offset u4, size u4 = 16B/needle) + a small dict overflow
    for recent writes, merged down when it grows. Lookup is a binary
    search (np.searchsorted); bulk load parses the whole .idx in one
    vectorized pass (no per-record Python loop).
  * SortedFileNeedleMap — the same sorted columns written to a .sdx
    sidecar and memory-mapped, so steady-state RAM is page cache only;
    deletes tombstone the mapped record in place (like the reference's
    sorted-file markAsDeleted); new writes go to a dict overflow.

Both share the .idx append-log write-through discipline and the counter
semantics of NeedleMap (file/deletion counters tally events, not live
entries), so Volume can swap them per its -index flag.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from .needle_map import NeedleValue, entry_to_bytes
from .types import (NEEDLE_ENTRY_SIZE, NEEDLE_PADDING_SIZE,
                    TOMBSTONE_FILE_SIZE)

# .idx record layout; "off" is in STORED units (real byte offset / 8,
# reference types/needle_types.go) — converted at the get/put boundary
IDX_DTYPE = np.dtype([("nid", ">u8"), ("off", ">u4"), ("size", ">u4")])
_DELETED = NeedleValue(0, TOMBSTONE_FILE_SIZE)  # overflow tombstone marker


def _replay_idx_vectorized(idx_path: str):
    """One-pass vectorized .idx replay: returns (live_records sorted by
    nid, counters dict). Last event per needle wins; counters match the
    dict map's event-tally semantics exactly:
      deletion_counter = puts - live,  deletion_bytes = put_bytes - live_bytes
    (every non-final put is superseded exactly once; deletes of dead
    needles tally nothing — same as NeedleMap._apply)."""
    counters = {"file_counter": 0, "file_byte_counter": 0,
                "deletion_counter": 0, "deletion_byte_counter": 0,
                "maximum_file_key": 0}
    if not os.path.exists(idx_path) or os.path.getsize(idx_path) == 0:
        return np.empty(0, dtype=IDX_DTYPE), counters
    raw = np.fromfile(idx_path, dtype=np.uint8)
    n = len(raw) // NEEDLE_ENTRY_SIZE
    arr = raw[:n * NEEDLE_ENTRY_SIZE].view(IDX_DTYPE)
    puts = (arr["size"] != TOMBSTONE_FILE_SIZE) & (arr["off"] != 0)
    counters["maximum_file_key"] = int(arr["nid"].max()) if n else 0
    counters["file_counter"] = int(puts.sum())
    counters["file_byte_counter"] = int(arr["size"][puts].sum())
    # last event per nid: first occurrence in the reversed stream
    _, idx_rev = np.unique(arr["nid"][::-1], return_index=True)
    last_idx = n - 1 - idx_rev  # ascending nid order (np.unique sorts)
    live = arr[last_idx][puts[last_idx]]
    counters["deletion_counter"] = \
        counters["file_counter"] - len(live)
    counters["deletion_byte_counter"] = \
        counters["file_byte_counter"] - int(live["size"].sum())
    return live, counters


class _SortedBase:
    """Shared: sorted record array + dict overflow + .idx write-through."""

    MERGE_THRESHOLD = 8192

    def __init__(self, idx_path: Optional[str] = None):
        self._base = np.empty(0, dtype=IDX_DTYPE)
        self._overflow: dict = {}
        self.idx_path = idx_path
        self._idx_file = open(idx_path, "ab") if idx_path else None
        self.file_counter = 0
        self.file_byte_counter = 0
        self.deletion_counter = 0
        self.deletion_byte_counter = 0
        self.maximum_file_key = 0

    # -- lookup ------------------------------------------------------------
    def _base_find(self, nid: int) -> int:
        """Index of nid in the sorted base, or -1."""
        base = self._base
        if len(base) == 0:
            return -1
        i = int(np.searchsorted(base["nid"], nid))
        if i < len(base) and int(base["nid"][i]) == nid:
            return i
        return -1

    def get(self, nid: int) -> Optional[NeedleValue]:
        ov = self._overflow.get(nid)
        if ov is not None:
            return None if ov is _DELETED else ov
        i = self._base_find(nid)
        if i < 0:
            return None
        size = int(self._base["size"][i])
        off = int(self._base["off"][i])
        # off == 0 marks an in-place sorted-file tombstone (size kept for
        # deleted-byte accounting); no live needle sits at stored offset 0
        if size == TOMBSTONE_FILE_SIZE or off == 0:
            return None
        return NeedleValue(off * NEEDLE_PADDING_SIZE, size)

    def _live_mask(self) -> np.ndarray:
        return (self._base["size"] != TOMBSTONE_FILE_SIZE) & \
            (self._base["off"] != 0)

    def __contains__(self, nid: int) -> bool:
        return self.get(nid) is not None

    def __len__(self) -> int:
        # live = unshadowed live base entries + live overflow entries
        base_live = int(self._live_mask().sum()) if len(self._base) else 0
        shadowed = sum(1 for nid in self._overflow if self._base_live(nid))
        live_ov = sum(1 for ov in self._overflow.values()
                      if ov is not _DELETED)
        return base_live - shadowed + live_ov

    def _base_live(self, nid: int) -> bool:
        i = self._base_find(nid)
        return i >= 0 and \
            int(self._base["size"][i]) != TOMBSTONE_FILE_SIZE and \
            int(self._base["off"][i]) != 0

    def items(self) -> Iterator[Tuple[int, NeedleValue]]:
        for rec in self._base:
            nid = int(rec["nid"])
            if nid in self._overflow:
                continue
            size = int(rec["size"])
            off = int(rec["off"])
            if size != TOMBSTONE_FILE_SIZE and off != 0:
                yield nid, NeedleValue(off * NEEDLE_PADDING_SIZE, size)
        for nid, ov in self._overflow.items():
            if ov is not _DELETED:
                yield nid, ov

    # -- mutations ---------------------------------------------------------
    def put(self, nid: int, offset: int, size: int):
        old = self.get(nid)
        self.maximum_file_key = max(self.maximum_file_key, nid)
        self.file_counter += 1
        self.file_byte_counter += size
        if old is not None:
            self.deletion_counter += 1
            self.deletion_byte_counter += old.size
        self._overflow[nid] = NeedleValue(offset, size)
        self._maybe_merge()
        if self._idx_file is not None:
            self._idx_file.write(entry_to_bytes(nid, offset, size))
            self._idx_file.flush()

    def delete(self, nid: int):
        old = self.get(nid)
        if old is not None:
            self.deletion_counter += 1
            self.deletion_byte_counter += old.size
            self._tombstone(nid)
        if self._idx_file is not None:
            self._idx_file.write(entry_to_bytes(nid, 0, TOMBSTONE_FILE_SIZE))
            self._idx_file.flush()

    def _tombstone(self, nid: int):
        self._overflow[nid] = _DELETED
        self._maybe_merge()

    def _maybe_merge(self):
        pass  # CompactNeedleMap folds the overflow down; mmap variant keeps it

    @property
    def content_size(self) -> int:
        return self.file_byte_counter

    @property
    def deleted_size(self) -> int:
        return self.deletion_byte_counter

    def sync(self):
        """fdatasync the .idx append log (SW_PLANE_FSYNC_MODE parity
        with NeedleMap.sync)."""
        if self._idx_file is not None:
            os.fdatasync(self._idx_file.fileno())

    def close(self):
        if self._idx_file is not None:
            self._idx_file.close()
            self._idx_file = None


class CompactNeedleMap(_SortedBase):
    """Sorted-column map, ~16B/needle steady state."""

    kind = "compact"

    @classmethod
    def load(cls, idx_path: str) -> "CompactNeedleMap":
        nm = cls.__new__(cls)
        _SortedBase.__init__(nm, None)
        live, counters = _replay_idx_vectorized(idx_path)
        nm._base = live
        nm.__dict__.update(counters)
        nm.idx_path = idx_path
        nm._idx_file = open(idx_path, "ab")
        return nm

    def _maybe_merge(self):
        if len(self._overflow) < self.MERGE_THRESHOLD:
            return
        keep = np.ones(len(self._base), dtype=bool)
        if len(self._base):
            keep &= self._live_mask()
            ov_keys = np.fromiter(self._overflow.keys(), dtype=np.uint64,
                                  count=len(self._overflow))
            keep &= ~np.isin(self._base["nid"].astype(np.uint64), ov_keys)
        extra = [(nid, ov.offset // NEEDLE_PADDING_SIZE, ov.size)
                 for nid, ov in self._overflow.items() if ov is not _DELETED]
        merged = np.empty(int(keep.sum()) + len(extra), dtype=IDX_DTYPE)
        merged[:int(keep.sum())] = self._base[keep]
        for j, (nid, off, size) in enumerate(extra):
            merged[int(keep.sum()) + j] = (nid, off, size)
        merged.sort(order="nid")
        self._base = merged
        self._overflow = {}

    @property
    def index_nbytes(self) -> int:
        """Steady-state footprint of the index arrays (diagnostics)."""
        return self._base.nbytes


class SortedFileNeedleMap(_SortedBase):
    """Binary search over an mmap'd .sdx sidecar; RAM = page cache.

    Freshness protocol: a ``.sdx.meta`` sidecar records the .idx byte
    size the .sdx covers plus the counters. On load, if the .idx hasn't
    grown past that watermark the .sdx is mmap'd as-is — no replay, no
    rewrite (the large-readonly-volume fast path). Otherwise one
    vectorized .idx replay regenerates it. Deletes tombstone the mapped
    record in place by zeroing its offset (size stays for deleted-byte
    accounting) and advance the watermark, so a delete-only session
    still reloads without a replay. New writes live in the dict
    overflow and invalidate the meta — the map is meant for
    rarely-written (readonly/EC-bound) volumes.
    """

    kind = "sortedfile"

    @classmethod
    def load(cls, idx_path: str) -> "SortedFileNeedleMap":
        import json
        nm = cls.__new__(cls)
        _SortedBase.__init__(nm, None)
        sdx_path = os.path.splitext(idx_path)[0] + ".sdx"
        meta_path = sdx_path + ".meta"
        nm.idx_path = idx_path
        nm.sdx_path = sdx_path
        nm.meta_path = meta_path
        idx_size = os.path.getsize(idx_path) \
            if os.path.exists(idx_path) else 0
        meta = None
        if os.path.exists(meta_path) and os.path.exists(sdx_path):
            try:
                with open(meta_path) as f:
                    candidate = json.load(f)
                if candidate.get("idx_size") == idx_size:
                    meta = candidate
            except (ValueError, OSError):
                meta = None
        if meta is not None:  # fast path: mmap the existing sidecar
            for k in ("file_counter", "file_byte_counter",
                      "deletion_counter", "deletion_byte_counter",
                      "maximum_file_key"):
                setattr(nm, k, int(meta.get(k, 0)))
        else:
            live, counters = _replay_idx_vectorized(idx_path)
            nm.__dict__.update(counters)
            live.tofile(sdx_path)
        if os.path.getsize(sdx_path) if os.path.exists(sdx_path) else 0:
            nm._base = np.memmap(sdx_path, dtype=IDX_DTYPE, mode="r+")
        else:
            nm._base = np.empty(0, dtype=IDX_DTYPE)
        nm._idx_file = open(idx_path, "ab")
        nm._save_meta()
        return nm

    def _save_meta(self):
        """Valid only while every mutation since is reflected in the
        .sdx itself (i.e. the overflow is empty)."""
        import json
        if self._overflow:
            if os.path.exists(self.meta_path):
                os.remove(self.meta_path)
            return
        if isinstance(self._base, np.memmap):
            # the watermark asserts the .sdx covers the .idx — in-place
            # tombstones must be durable BEFORE the meta says so, or a
            # crash resurrects the needle on the no-replay fast path
            self._base.flush()
        self._idx_file.flush()
        state = {"idx_size": os.path.getsize(self.idx_path),
                 "file_counter": self.file_counter,
                 "file_byte_counter": self.file_byte_counter,
                 "deletion_counter": self.deletion_counter,
                 "deletion_byte_counter": self.deletion_byte_counter,
                 "maximum_file_key": self.maximum_file_key}
        with open(self.meta_path, "w") as f:
            json.dump(state, f)

    def _tombstone(self, nid: int):
        i = self._base_find(nid)
        if i >= 0 and isinstance(self._base, np.memmap):
            self._base["off"][i] = 0  # in-place on disk; size kept
            self._overflow.pop(nid, None)
        else:
            self._overflow[nid] = _DELETED

    def delete(self, nid: int):
        super().delete(nid)
        self._save_meta()  # advance the watermark past the tombstone

    def close(self):
        if isinstance(self._base, np.memmap):
            self._base.flush()
        if self._idx_file is not None:
            self._save_meta()
        super().close()


NEEDLE_MAP_KINDS = {"memory", "compact", "sortedfile", "disk"}


class SnapshotItems:
    """Uniform closeable handle over a live-set snapshot: either the
    disk map's private-connection cursor or a plain in-memory list.
    Iterate it directly, or use as a context manager / call close() in
    a finally so the sqlite WAL snapshot connection is released the
    moment the walk ends rather than at GC (a pinned snapshot blocks
    checkpoint truncation for as long as it lives)."""

    def __init__(self, items):
        self._items = items

    def __iter__(self):
        return iter(self._items)

    def close(self):
        close = getattr(self._items, "close", None)
        self._items = ()
        if close is not None:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def snapshot_live_items(nm, by_offset: bool = False) -> SnapshotItems:
    """Consistent live-set snapshot of ANY needle-map variant; the
    caller must hold the volume lock across this call. Disk maps
    flush pending state then stream from a pinned private-connection
    snapshot (RAM-bounded — flush-before-read is mandatory and lives
    HERE so no caller can forget it); in-memory maps list-copy.
    by_offset orders by .dat offset (the vacuum merge-walk's need);
    leave it False where order doesn't matter — for the disk map that
    skips a whole-table sort. Close the returned handle (context
    manager or try/finally) when done."""
    snap = getattr(nm, "items_snapshot", None)
    if snap is not None:
        nm.flush()
        return SnapshotItems(snap(by_offset=by_offset))
    items = list(nm.items())
    if by_offset:
        items.sort(key=lambda kv: kv[1].offset)
    return SnapshotItems(items)


def load_needle_map(idx_path: str, kind: str = "memory",
                    offset_width: int = 4):
    """Factory selecting the needle-map variant, like the reference's
    volume -index flag (memory | compact | sortedfile | disk —
    the last mirroring -index leveldb, needle_map_leveldb.go:15-120).

    5-byte-offset volumes (17B .idx records) use the dict map unless
    the disk map was asked for: the numpy fast paths here are wired for
    the 16B layout, and the disk map is exactly the variant meant for
    volumes too big to hold an in-RAM index.
    """
    if kind == "disk":
        from .needle_map_disk import DiskNeedleMap
        return DiskNeedleMap.load(idx_path, offset_width)
    if offset_width != 4:
        from .needle_map import NeedleMap
        return NeedleMap.load(idx_path, offset_width)
    if kind == "memory":
        from .needle_map import NeedleMap
        return NeedleMap.load(idx_path)
    if kind == "compact":
        return CompactNeedleMap.load(idx_path)
    if kind == "sortedfile":
        return SortedFileNeedleMap.load(idx_path)
    raise ValueError(f"unknown needle map kind {kind!r} "
                     f"(want one of {sorted(NEEDLE_MAP_KINDS)})")
