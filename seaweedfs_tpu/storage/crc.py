"""CRC32-C (Castagnoli) needle checksums.

The reference checksums needle payloads with Castagnoli CRC32 and stores the
"masked" value ((crc>>15 | crc<<17) + 0xa282ead8 — reference
weed/storage/needle/crc.go:25). Hot path uses the native library's
slicing-by-8 implementation; falls back to a pure-Python table loop.
"""

from __future__ import annotations

import ctypes

_POLY = 0x82F63B78


def _build_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _build_table()


def _crc32c_py(crc: int, data: bytes) -> int:
    c = crc ^ 0xFFFFFFFF
    t = _TABLE
    for b in data:
        c = t[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    try:
        from ..ops.rs_native import _load
        lib = _load()
        if lib is not None:
            # c_char_p passes Python bytes zero-copy (the C side only
            # reads); avoids a full payload memcpy per checksum
            lib.sw_crc32c.argtypes = [ctypes.c_uint32,
                                      ctypes.c_char_p,
                                      ctypes.c_longlong]
            lib.sw_crc32c.restype = ctypes.c_uint32
            _native = lib
        else:
            _native = False
    except Exception:
        _native = False
    return _native


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load_native()
    if lib:
        return lib.sw_crc32c(crc, bytes(data), len(data))
    return _crc32c_py(crc, data)


def masked_value(crc: int) -> int:
    """The value actually stored on disk (reference crc.go:25)."""
    crc &= 0xFFFFFFFF
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def needle_checksum(data: bytes) -> int:
    return masked_value(crc32c(data))
