"""Core storage types and on-disk encodings.

Wire/disk compatible with the reference (SeaweedFS v1.71):
  * big-endian integers (reference weed/util/bytes.go)
  * index entry: NeedleId(8) + Offset(4) + Size(4) = 16 bytes
    (reference weed/storage/types/needle_types.go:27)
  * offsets stored divided by 8 (needle padding unit) -> 32GB max volume
    with 4-byte offsets (reference types/offset_4bytes.go)
  * tombstone size = 0xFFFFFFFF
  * TTL: count byte + unit byte (reference needle/volume_ttl.go)
  * replica placement: one byte, decimal digits DC/rack/server
    (reference super_block/replica_placement.go)
  * file id string: "<vid>,<key+cookie hex, leading zero bytes stripped>"
    (reference needle/file_id.go:64-72)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE   # 16
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8
TOMBSTONE_FILE_SIZE = 0xFFFFFFFF
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB (4B offsets * 8)

# 5-byte offsets (reference types/offset_5bytes.go, a build tag there):
# here a per-volume property carried in the superblock, widening .idx
# entries to 17 bytes and the max volume to 8TB
OFFSET_SIZE_5 = 5
MAX_POSSIBLE_VOLUME_SIZE_5 = (1 << 40) * 8  # 8TB


def entry_size(offset_width: int = OFFSET_SIZE) -> int:
    """.idx record width for a volume's offset width (16 or 17)."""
    return NEEDLE_ID_SIZE + offset_width + SIZE_SIZE


def max_volume_size(offset_width: int = OFFSET_SIZE) -> int:
    return MAX_POSSIBLE_VOLUME_SIZE_5 if offset_width == OFFSET_SIZE_5 \
        else MAX_POSSIBLE_VOLUME_SIZE

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


def needle_id_to_bytes(nid: int) -> bytes:
    return struct.pack(">Q", nid)


def bytes_to_needle_id(b: bytes) -> int:
    return struct.unpack(">Q", b[:8])[0]


def offset_to_bytes(offset: int, offset_width: int = OFFSET_SIZE) -> bytes:
    """offset is the real byte offset; stored /8 in 4 or 5 big-endian
    bytes (reference offset_4bytes.go / offset_5bytes.go)."""
    if offset % NEEDLE_PADDING_SIZE:
        raise ValueError(f"offset {offset} not {NEEDLE_PADDING_SIZE}B aligned")
    stored = offset // NEEDLE_PADDING_SIZE
    if stored >> (8 * offset_width):
        raise ValueError(
            f"offset {offset} exceeds {offset_width}-byte addressing")
    return stored.to_bytes(offset_width, "big")


def bytes_to_offset(b: bytes) -> int:
    """Width inferred from the slice length (4 or 5 bytes)."""
    return int.from_bytes(b, "big") * NEEDLE_PADDING_SIZE


def format_needle_id_cookie(key: int, cookie: int) -> str:
    raw = struct.pack(">QI", key, cookie)
    stripped = raw.lstrip(b"\x00")
    if not stripped:
        stripped = b"\x00"
    return stripped.hex()


def parse_key_hash(key_hash: str) -> tuple:
    """'<key_hex><cookie_hex>' -> (key, cookie). Last 8 hex chars are the
    cookie (reference needle.go:118-140 ParsePath/ParseKeyHash)."""
    if len(key_hash) <= 8 or len(key_hash) > 24:
        raise ValueError(f"invalid key-cookie string {key_hash!r}")
    raw = bytes.fromhex(key_hash.zfill(len(key_hash) + len(key_hash) % 2))
    key = int.from_bytes(raw[:-4], "big")
    cookie = int.from_bytes(raw[-4:], "big")
    return key, cookie


def parse_file_id(fid: str) -> tuple:
    """'3,01637037d6' -> (volume_id, key, cookie). A '_<n>' suffix is
    the batch-assign convention (reference needle.ParsePath /
    common.go: ?count=N assigns hand out one fid and clients append
    _1.._N-1, meaning key+n with the same cookie)."""
    sep = "," if "," in fid else "/"
    if sep not in fid:
        raise ValueError(f"invalid fid {fid!r}")
    vid_s, key_hash = fid.split(sep, 1)
    key_hash = key_hash.strip()
    delta = 0
    if "_" in key_hash:
        key_hash, delta_s = key_hash.split("_", 1)
        # 18-digit cap (matches the C++ parser): an unbounded delta
        # could push the key past 2^64 and blow up serialization with
        # a struct.error instead of a clean invalid-fid rejection
        if not delta_s.isdigit() or len(delta_s) > 18:
            raise ValueError(f"invalid fid delta in {fid!r}")
        delta = int(delta_s)
    key, cookie = parse_key_hash(key_hash)
    key += delta
    if key >> 64:
        raise ValueError(f"fid key overflows 64 bits in {fid!r}")
    return int(vid_s), key, cookie


def format_file_id(vid: int, key: int, cookie: int) -> str:
    return f"{vid},{format_needle_id_cookie(key, cookie)}"


# ---------------------------------------------------------------------------
# TTL
# ---------------------------------------------------------------------------

TTL_EMPTY = 0
TTL_MINUTE = 1
TTL_HOUR = 2
TTL_DAY = 3
TTL_WEEK = 4
TTL_MONTH = 5
TTL_YEAR = 6

_UNIT_CHARS = {TTL_MINUTE: "m", TTL_HOUR: "h", TTL_DAY: "d",
               TTL_WEEK: "w", TTL_MONTH: "M", TTL_YEAR: "y"}
_CHAR_UNITS = {v: k for k, v in _UNIT_CHARS.items()}
_UNIT_MINUTES = {TTL_EMPTY: 0, TTL_MINUTE: 1, TTL_HOUR: 60, TTL_DAY: 24 * 60,
                 TTL_WEEK: 7 * 24 * 60, TTL_MONTH: 31 * 24 * 60,
                 TTL_YEAR: 365 * 24 * 60}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = TTL_EMPTY

    @classmethod
    def parse(cls, s: str) -> "TTL":
        s = (s or "").strip()
        if not s:
            return cls()
        unit_ch = s[-1]
        if unit_ch.isdigit():
            count, unit = int(s), TTL_MINUTE
        else:
            count, unit = int(s[:-1] or 0), _CHAR_UNITS.get(unit_ch)
            if unit is None:
                raise ValueError(f"invalid TTL unit {unit_ch!r}")
        return cls(count, unit)

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if len(b) < 2 or (b[0] == 0 and b[1] == 0):
            return cls()
        return cls(b[0], b[1])

    @classmethod
    def from_uint32(cls, v: int) -> "TTL":
        return cls.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    @property
    def minutes(self) -> int:
        return self.count * _UNIT_MINUTES.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0 or self.unit == TTL_EMPTY:
            return ""
        return f"{self.count}{_UNIT_CHARS[self.unit]}"


# ---------------------------------------------------------------------------
# Replica placement ("xyz": x=other DCs, y=other racks, z=same rack)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ReplicaPlacement:
    diff_data_center: int = 0
    diff_rack: int = 0
    same_rack: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").strip() or "000"
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"invalid replica placement {s!r}")
        return cls(int(s[0]), int(s[1]), int(s[2]))

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(b // 100, (b // 10) % 10, b % 10)

    def to_byte(self) -> int:
        return self.diff_data_center * 100 + self.diff_rack * 10 + self.same_rack

    @property
    def copy_count(self) -> int:
        return self.diff_data_center + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_data_center}{self.diff_rack}{self.same_rack}"
