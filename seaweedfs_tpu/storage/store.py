"""Store — a volume server's aggregate of disk locations.

Reference weed/storage/store.go: owns volumes + EC volumes across
directories, assembles heartbeats for the master, routes reads/writes to
volumes, and hosts the EC lifecycle operations (generate/mount/rebuild).
"""

from __future__ import annotations

import os
import threading
from ..util.locks import make_rlock
from typing import Dict, List, Optional

from ..ec import encoder as ec_encoder
from ..ec.constants import DATA_SHARDS, TOTAL_SHARDS, to_ext
from ..ec.ec_volume import EcVolume, ec_offset_width, rebuild_ecx_file
from ..ops.codec import ReedSolomonCodec
from .disk_location import DiskLocation
from .needle import Needle
from .types import TTL, ReplicaPlacement
from .volume import Volume, VolumeError, volume_file_prefix


class Store:
    def __init__(self, directories: List[str], max_volume_counts=None,
                 ip: str = "127.0.0.1", port: int = 8080,
                 public_url: str = "", data_center: str = "",
                 rack: str = "", codec: Optional[ReedSolomonCodec] = None,
                 index_kind: str = "memory"):
        if isinstance(directories, str):
            directories = [directories]
        max_volume_counts = max_volume_counts or [7] * len(directories)
        self.locations = [DiskLocation(d, m, index_kind=index_kind)
                          for d, m in zip(directories, max_volume_counts)]
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.data_center = data_center
        self.rack = rack
        self.codec = codec
        # fired after any volume create/delete or EC shard mount/unmount
        # (reference store.go:40-64 NewVolumesChan/DeletedVolumesChan/
        # NewEcShardsChan/DeletedEcShardsChan): lets the volume server
        # push a heartbeat delta immediately instead of waiting a pulse.
        self.on_change = None
        # fired with (vid, mounted_shard_ids) after mount_ec_shards
        # registers shards: the degraded-read engine drops its cached
        # reconstructions of them — a shard back on disk (e.g. after
        # rebuild) must be served from disk, not from the slab LRU.
        self.on_ec_mount = None
        self.lock = make_rlock("store.lock")
        for loc in self.locations:
            loc.load_existing_volumes()
            loc.load_all_ec_shards()

    # -- lookup ------------------------------------------------------------
    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.get_volume(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def find_free_location(self) -> Optional[DiskLocation]:
        """Location with a free slot; EC shards count as 1/10 volume
        (reference store.go:99-112)."""
        best, best_free = None, 0.0
        for loc in self.locations:
            ec_shards = sum(len(ev.shards) for ev in loc.ec_volumes.values())
            free = loc.max_volume_count - len(loc.volumes) - ec_shards / 10.0
            if free >= 1 and free > best_free:
                best, best_free = loc, free
        return best

    # -- volume lifecycle --------------------------------------------------
    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "000", ttl: str = "") -> Volume:
        if self.find_volume(vid) is not None:
            return self.find_volume(vid)
        loc = self.find_free_location()
        if loc is None:
            raise VolumeError("no free volume slots")
        v = loc.add_volume(
            collection, vid,
            replica_placement=ReplicaPlacement.parse(replication),
            ttl=TTL.parse(ttl))
        self._changed()
        return v

    def delete_volume(self, vid: int) -> bool:
        for loc in self.locations:
            if loc.delete_volume(vid):
                self._changed()
                return True
        return False

    def _changed(self):
        cb = self.on_change
        if cb is not None:
            cb()

    def mark_volume_readonly(self, vid: int,
                             readonly: bool = True) -> Optional[bool]:
        """Set the flag; returns the PREVIOUS readonly state, or None
        when the volume is absent — orchestrators restore exactly the
        prior state on failure."""
        v = self.find_volume(vid)
        if v is None:
            return None
        was, v.readonly = v.readonly, readonly
        return was

    # -- data path ---------------------------------------------------------
    def write_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.write_needle(n)

    def read_needle(self, vid: int, n: Needle) -> Needle:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.read_needle(n)

    def read_needle_flags(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.read_needle_flags(n)

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.delete_needle(n)

    # -- EC lifecycle (reference volume_grpc_erasure_coding.go) ------------
    def _encode_layout(self):
        """(layout name, plan, window) for NEW ec volumes, from
        SW_EC_LAYOUT. Unsupported geometries (m < 2) raise rather than
        silently downgrading an operator's explicit piggyback choice."""
        from ..ec import layout as ec_layout
        from ..ec.constants import (LARGE_BLOCK_SIZE, PARITY_SHARDS,
                                    SMALL_BLOCK_SIZE)
        from ..ops import codec as ops_codec
        from ..util import config as _config
        name = (_config.env_str("SW_EC_LAYOUT") or
                ec_layout.LAYOUT_FLAT).lower()
        if name == ec_layout.LAYOUT_FLAT:
            return ec_layout.LAYOUT_FLAT, None, None
        if name != ec_layout.LAYOUT_PIGGYBACK:
            raise VolumeError(f"unknown SW_EC_LAYOUT {name!r}")
        k = self.codec.k if self.codec is not None else DATA_SHARDS
        m = (self.codec.m if self.codec is not None else PARITY_SHARDS)
        if not ops_codec.piggyback_supported(k, m):
            raise VolumeError(
                f"SW_EC_LAYOUT=piggyback unsupported for RS({k},{m})")
        from ..ops.codec import get_codec
        codec = self.codec or get_codec(k, m)
        pplan, window = ec_encoder.piggyback_geometry(
            codec, None, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE)
        return ec_layout.LAYOUT_PIGGYBACK, pplan, window

    def _volume_layout(self, base):
        """Resolve an existing volume's on-disk layout from its
        sidecars (ec/layout.volume_layout): the routing predicate for
        every layout-sensitive path below."""
        from ..ec import layout as ec_layout
        from .types import entry_size
        k = self.codec.k if self.codec is not None else DATA_SHARDS
        try:
            width = ec_offset_width(base)
        except Exception:  # noqa: BLE001 - no sidecars at all: flat
            width = 4
        return ec_layout.volume_layout(base, k,
                                       record_size=entry_size(width))

    def _write_layout_sidecars(self, base, v, layout, pplan, window):
        """Record the volume metadata AND layout in one .vif/.ecx-tag
        write (ec/layout). offset_width must ride along: a shard
        receiver holding only parity shards has no .ec00 superblock to
        infer the .ecx record width from."""
        from ..ec import layout as ec_layout
        from .types import entry_size
        ec_layout.write_layout_sidecars(
            base, layout,
            window=window,
            pairs=(pplan.npairs if pplan is not None else None),
            record_size=entry_size(v.offset_width),
            version=v.version, offset_width=v.offset_width)

    def generate_ec_shards(self, vid: int, collection: str = "") -> str:
        """Volume .dat/.idx -> .ec00-13 + .ecx + .vif on the same disk.
        SW_EC_LAYOUT picks the parity layout for the new shards; the
        choice is stamped into the sidecars so every later reader
        (scrub, degraded reads, rebuild) routes by the volume, not the
        environment."""
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        if not v.readonly:
            raise VolumeError(f"volume {vid} must be readonly for ec encode")
        base = v.file_name()
        layout, pplan, window = self._encode_layout()
        from ..util import tracing
        with tracing.span("ec.encode.local", volume=vid, layout=layout):
            ec_encoder.write_sorted_file_from_idx(base)
            ec_encoder.write_ec_files(base, codec=self.codec,
                                      layout=layout)
        self._write_layout_sidecars(base, v, layout, pplan, window)
        return base

    def generate_ec_shards_streaming(self, vid: int, collection: str = "",
                                     assignment: Dict[int, str] = None,
                                     spares: List[str] = None,
                                     window: Optional[int] = None,
                                     stats: dict = None,
                                     rate_mbps: float = 0.0):
        """Streaming encode+spread: encode the readonly volume and push
        each shard's slab ranges to its assigned holder while later
        slabs are still encoding (ec/spread.py). ``assignment`` maps
        shard id -> holder url; shards assigned to this server (or
        unassigned) are written locally. Returns ``(base, final)``
        where ``final`` is the post-failover placement ({sid: url, ''
        for local}). On ANY failure every holder's ``.part`` stage is
        aborted and local outputs removed — no partial shards survive.

        Only the shards this server keeps (plus .ecx/.vif) touch its
        disk; remote-bound shards stream straight from the encode.
        ``rate_mbps`` > 0 paces the producer so a background demotion
        cannot saturate the network foreground reads share."""
        from ..ec import spread
        from ..stats.metrics import observe_transport
        from ..util import tracing
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        if not v.readonly:
            raise VolumeError(f"volume {vid} must be readonly for ec encode")
        base = v.file_name()
        assignment = {int(s): u for s, u in (assignment or {}).items()}
        sstats = spread.SpreadStats()
        total = self.codec.total if self.codec is not None else TOTAL_SHARDS
        # same slab policy as the streaming gather: shrink the stripe
        # so even a near-slab-sized shard gives the spread several
        # stripes to overlap with the encode (slab only batches device
        # columns — shard bytes are invariant under it)
        from ..ec.gather import auto_slab
        slab = auto_slab(ec_encoder.ec_shard_base_size(
            os.path.getsize(base + ".dat")))
        layout, pplan, pb_window = self._encode_layout()
        with tracing.span("ec.encode.stream", volume=vid,
                          layout=layout) as root:
            ec_encoder.write_sorted_file_from_idx(base)
            sink = spread.StripedSpreadSink(
                vid, base, assignment, total, collection=collection,
                local_url=self.public_url, spares=spares,
                window=window, stats=sstats, parent_span=root,
                rate_mbps=rate_mbps)
            try:
                ec_encoder.write_ec_files_spread(
                    base, sink, codec=self.codec, slab=slab, stats=stats,
                    layout=layout)
            except BaseException:
                # the sink already aborted every holder's stage; drop
                # anything the local fast path finalized plus the index
                for i in range(total):
                    for p in (base + to_ext(i), base + to_ext(i) + ".part"):
                        try:
                            os.remove(p)
                        except OSError:
                            pass
                try:
                    os.remove(base + ".ecx")
                except OSError:
                    pass
                raise
            self._write_layout_sidecars(base, v, layout, pplan, pb_window)
        observe_transport("push", sstats, window=sink.window)
        return base, sink.assignment()

    def mount_ec_shards(self, vid: int, collection: str,
                        shard_ids: List[int]) -> List[int]:
        mounted = []
        for loc in self.locations:
            base = volume_file_prefix(loc.directory, collection, vid)
            if not os.path.exists(base + ".ecx"):
                continue
            ev = loc.ec_volumes.get(vid)
            created = ev is None
            if created:
                ev = EcVolume(loc.directory, collection, vid)
            for sid in shard_ids:
                if os.path.exists(base + to_ext(sid)) and ev.add_shard(sid):
                    mounted.append(sid)
            if created:
                # never leave a shard-less EcVolume registered — it would
                # shadow the replica-redirect path for reads
                if ev.shards:
                    loc.ec_volumes[vid] = ev
                else:
                    ev.close()
            break
        if mounted:
            cb = self.on_ec_mount
            if cb is not None:
                cb(vid, mounted)
            self._changed()
        return mounted

    def unmount_ec_shards(self, vid: int, shard_ids: List[int]) -> List[int]:
        ev = self.find_ec_volume(vid)
        if ev is None:
            return []
        out = []
        for sid in shard_ids:
            shard = ev.delete_shard(sid)
            if shard is not None:
                shard.close()
                out.append(sid)
        if not ev.shards:
            for loc in self.locations:
                if loc.ec_volumes.get(vid) is ev:
                    loc.ec_volumes.pop(vid)
            ev.close()
        if out:
            self._changed()
        return out

    def rebuild_ec_shards(self, vid: int, collection: str = "",
                          stats: dict = None) -> List[int]:
        """``stats``, when given, receives the rebuild's dispatch
        telemetry (rebuild_ec_files fills it) for the admin endpoint /
        bench counters."""
        import time as _time
        from ..util import tracing
        for loc in self.locations:
            base = volume_file_prefix(loc.directory, collection, vid)
            if os.path.exists(base + ".ecx"):
                li = self._volume_layout(base)
                with tracing.span("ec.rebuild.local", volume=vid,
                                  layout=li.layout):
                    rebuilt = ec_encoder.rebuild_ec_files(
                        base, codec=self.codec, stats=stats,
                        layout=(li if li.piggyback else None))
                    from ..ec.decoder import read_ec_volume_superblock
                    t0 = _time.perf_counter()
                    rebuild_ecx_file(
                        base, read_ec_volume_superblock(base).offset_width)
                    ecx_s = _time.perf_counter() - t0
                    tracing.record_span("write", ecx_s, op="ec.rebuild.ecx")
                    if stats is not None and "phases" in stats:
                        stats["phases"]["write"] = round(
                            stats["phases"].get("write", 0.0) + ecx_s, 6)
                return rebuilt
        raise VolumeError(f"ec volume {vid} not found")

    def rebuild_ec_shards_streaming(self, vid: int, collection: str = "",
                                    sources: Dict[int, List[str]] = None,
                                    stats: dict = None,
                                    slab: Optional[int] = None,
                                    window: Optional[int] = None,
                                    hedge_ms: Optional[float] = None,
                                    repair: str = "auto"
                                    ) -> List[int]:
        """Rebuild missing shards by streaming slab ranges of remote
        survivors straight into the decode — no whole-shard copies on
        this server's disks, before, during, or after. ``sources`` maps
        shard id -> holder urls for survivors NOT local to this store;
        shards already here are read from disk. Only the KB-scale index
        sidecars (.ecx/.vif/.ecj) are copied whole.

        ``repair`` picks the single-shard repair strategy: ``trace``
        gathers per-survivor projected symbols over
        ``/admin/ec/shard_repair_read`` (sub-k*slab network bytes, see
        ops/codec.repair_plan), ``piggyback`` gathers half-plane
        sub-chunk streams over ``/admin/ec/shard_plane_read``
        ((k+1)/2k of the baseline, piggyback-layout volumes only),
        ``full`` is the full streaming decode, ``auto`` (default)
        routes by the volume's layout — piggyback repair on coupled
        layouts, trace on flat — and falls back to the layout's full
        decode bit-identically for multi-shard loss, no-gain
        geometries, uncoupled shards, or holders that predate the
        repair routes. Forcing ``trace`` on a piggyback volume (or
        ``piggyback`` on flat) is an error: the modes read parity bytes
        the other layout does not have."""
        import time as _time
        from ..ec import gather
        from ..util import tracing
        sources = {int(s): list(urls) for s, urls in
                   (sources or {}).items() if urls}
        holders: List[str] = []
        for urls in sources.values():
            for u in urls:
                if u not in holders:
                    holders.append(u)
        # prefer a location that already has volume files; else the
        # freest one — the rebuilt shards and index live there
        loc = None
        for cand in self.locations:
            base = volume_file_prefix(cand.directory, collection, vid)
            if os.path.exists(base + ".ecx") or any(
                    os.path.exists(base + to_ext(i))
                    for i in range(TOTAL_SHARDS)):
                loc = cand
                break
        if loc is None:
            loc = self.find_free_location() or self.locations[0]
        base = volume_file_prefix(loc.directory, collection, vid)
        k = self.codec.k if self.codec is not None else DATA_SHARDS
        total = self.codec.total if self.codec is not None \
            else TOTAL_SHARDS
        with tracing.span("ec.rebuild.stream", volume=vid) as root:
            if holders:
                gather.fetch_index_files(base, holders)
            local = [os.path.exists(base + to_ext(i))
                     for i in range(total)]
            present = [local[i] or i in sources for i in range(total)]
            missing = [i for i, p in enumerate(present) if not p]
            if not missing:
                return []
            if sum(present) < k:
                raise VolumeError(
                    f"cannot rebuild {vid}: only {sum(present)} of "
                    f"{total} shards reachable")
            mode = (repair or "auto").lower()
            if mode not in ("auto", "trace", "piggyback", "full"):
                raise VolumeError(f"unknown repair mode {mode!r}")
            # sidecars are local now (fetched above when remote): the
            # volume's layout routes every path below
            li = self._volume_layout(base)
            if mode == "trace" and li.piggyback:
                raise VolumeError(
                    "-repair trace: volume has the piggyback layout "
                    "(trace masks read flat parity bytes); use "
                    "piggyback, auto or full")
            if mode == "piggyback" and not li.piggyback:
                raise VolumeError(
                    "-repair piggyback: volume has the flat layout "
                    "(no coupled parity planes); use trace, auto or "
                    "full")
            # one wire probe per (vid, sid) for this whole rebuild, no
            # matter how many paths need a size below
            size_cache = gather.ShardSizeCache()

            def sized(candidates) -> int:
                sz = None
                for i in candidates:
                    if local[i]:
                        s = os.path.getsize(base + to_ext(i))
                        if sz is None:
                            sz = s
                        elif sz != s:
                            raise VolumeError(
                                "surviving shards differ in size")
                if sz is not None:
                    return sz
                last = None
                for i in candidates:
                    if i in sources:
                        try:
                            return size_cache.get(vid, i, sources[i])
                        except Exception as e:  # noqa: BLE001
                            last = e
                raise last if last is not None else VolumeError(
                    f"cannot size shards of volume {vid}")

            rebuilt = None
            if mode != "full":
                if li.piggyback:
                    rebuilt = self._rebuild_streaming_piggyback(
                        vid, base, local, present, missing, sources,
                        sized, stats, slab, window, hedge_ms, root,
                        mode, li)
                else:
                    rebuilt = self._rebuild_streaming_trace(
                        vid, base, local, present, missing, sources,
                        sized, stats, slab, window, hedge_ms, root,
                        mode)
            if rebuilt is None and li.piggyback:
                # full coupled decode: readers follow the decode
                # plan's src order (surviving data, then just enough
                # parities), stripes clamp to sub-chunk windows
                from ..ops import codec as ops_codec
                src, _, _ = ops_codec.piggyback_decode_plan(
                    k, self.codec.m if self.codec is not None
                    else total - k,
                    tuple(bool(p) for p in present),
                    matrix_kind=(self.codec.matrix_kind
                                 if self.codec is not None
                                 else "vandermonde"),
                    matrix=(self.codec.matrix
                            if self.codec is not None else None),
                    pairs=li.pairs)
                gstats = gather.GatherStats()
                readers = []
                for i in src:
                    if local[i]:
                        readers.append(gather.LocalShardReader(
                            base + to_ext(i), gstats))
                    else:
                        readers.append(gather.RemoteShardReader(
                            vid, i, sources[i], gstats,
                            hedge_ms=hedge_ms))
                shard_size = sized(src)
                eff_slab = slab or gather.auto_slab(
                    shard_size, default=ec_encoder.DEFAULT_SLAB)
                eff_slab = max(li.window,
                               eff_slab - eff_slab % li.window)
                source = gather.StripedGatherSource(
                    readers, shard_size, slab=eff_slab,
                    window=window, stats=gstats, parent_span=root)
                rebuilt = \
                    ec_encoder.rebuild_ec_files_streaming_piggyback(
                        base, present, missing, source, li,
                        codec=self.codec, slab=eff_slab, stats=stats)
                from ..stats.metrics import observe_transport
                observe_transport("pull", gstats, window=source.window)
                if stats is not None:
                    stats["repair_mode"] = "full"
            elif rebuilt is None:
                gather_present = self._health_survivor_mask(
                    present, local, sources, k, stats)
                src = [i for i, p in enumerate(gather_present) if p][:k]
                gstats = gather.GatherStats()
                readers = []
                for i in src:
                    if local[i]:
                        readers.append(gather.LocalShardReader(
                            base + to_ext(i), gstats))
                    else:
                        readers.append(gather.RemoteShardReader(
                            vid, i, sources[i], gstats,
                            hedge_ms=hedge_ms))
                shard_size = sized(src)
                eff_slab = slab or gather.auto_slab(
                    shard_size, default=ec_encoder.DEFAULT_SLAB)
                source = gather.StripedGatherSource(
                    readers, shard_size, slab=eff_slab,
                    window=window, stats=gstats, parent_span=root)
                rebuilt = ec_encoder.rebuild_ec_files_streaming(
                    base, gather_present, missing, source,
                    codec=self.codec, slab=eff_slab, stats=stats)
                from ..stats.metrics import observe_transport
                observe_transport("pull", gstats, window=source.window)
                if stats is not None:
                    stats["repair_mode"] = "full"
            t0 = _time.perf_counter()
            rebuild_ecx_file(base, ec_offset_width(base))
            ecx_s = _time.perf_counter() - t0
            tracing.record_span("write", ecx_s, op="ec.rebuild.ecx")
            if stats is not None and "phases" in stats:
                stats["phases"]["write"] = round(
                    stats["phases"].get("write", 0.0) + ecx_s, 6)
        return rebuilt

    @staticmethod
    def _health_survivor_mask(present, local, sources, k, stats):
        """Health-aware survivor selection for the full streaming
        gather. With more than k survivors reachable and
        SW_EC_HEALTH_ROUTING=1, the surplus shards are dropped from the
        decode plan worst-holder-first (local shards score a perfect
        1.0), so a slow or erroring holder is demoted out of the gather
        entirely when healthier survivors can cover the k. Decoding
        from any k survivors is exact, so the rebuilt bytes are
        bit-identical regardless of which surplus shards are masked.
        Ties drop the highest shard ids, matching the un-routed
        first-k selection."""
        from ..stats import health as _health
        survivors = [i for i, p in enumerate(present) if p]
        surplus = len(survivors) - k
        if surplus <= 0 or not _health.routing_enabled():
            return present

        def shard_score(i):
            if local[i] or not sources.get(i):
                return 1.0
            return max(_health.BOARD.score(u) for u in sources[i])

        masked = list(present)
        drop_order = sorted(survivors,
                            key=lambda i: (shard_score(i), -i))
        demoted = sorted(drop_order[:surplus])
        for i in demoted:
            masked[i] = False
        if stats is not None:
            stats["health_demoted_shards"] = demoted
        return masked

    def _rebuild_streaming_piggyback(self, vid, base, local, present,
                                     missing, sources, sized, stats,
                                     slab, window, hedge_ms, root, mode,
                                     li):
        """Attempt the half-plane piggyback repair; returns the rebuilt
        shard list or None to signal 'use the full coupled decode
        instead'. Forced mode ('piggyback') converts every fallback
        into an error; 'auto' records the reason in stats and lets the
        caller fall through bit-identically."""
        from ..ec import decoder as ec_decoder
        from ..ec import gather
        from ..ops import codec as ops_codec
        from ..server.http_util import HttpError

        def bail(reason: str):
            if mode == "piggyback":
                raise VolumeError(f"-repair piggyback: {reason}")
            if stats is not None:
                stats["repair_fallback"] = reason
            return None

        if len(missing) != 1:
            return bail(
                f"{len(missing)} shards lost, piggyback repairs one")
        lost = missing[0]
        k = self.codec.k if self.codec is not None else DATA_SHARDS
        m = (self.codec.m if self.codec is not None
             else TOTAL_SHARDS - DATA_SHARDS)
        try:
            pplan = ops_codec.piggyback_plan(
                k, m,
                matrix_kind=(self.codec.matrix_kind
                             if self.codec is not None else "vandermonde"),
                matrix=(self.codec.matrix
                        if self.codec is not None else None),
                pairs=li.pairs)
        except ValueError as e:
            return bail(f"no piggyback scheme: {e}")
        if lost >= pplan.coupled:
            return bail(f"shard {lost} not coupled "
                        f"(coupled prefix is 0..{pplan.coupled - 1})")
        par = [k + j for j in range(m) if present[k + j]]
        if len(par) < 2:
            return bail(f"{len(par)} surviving parities, plane repair "
                        f"needs 2")
        if any(not present[i] for i in range(k) if i != lost):
            return bail("a data helper is unreachable")
        try:
            rplan = ops_codec.piggyback_repair_plan(
                k, m, lost, parity_sids=tuple(par[:2]),
                matrix_kind=pplan.matrix_kind,
                matrix=(self.codec.matrix
                        if self.codec is not None else None),
                pairs=li.pairs)
        except ValueError as e:
            return bail(f"no repair plan: {e}")
        shard_size = sized(rplan.helpers)
        if shard_size % li.window:
            return bail(
                f"shard size {shard_size} not aligned to sidecar "
                f"window {li.window}")
        gstats = gather.GatherStats()
        readers = []
        for i in rplan.helpers:
            if local[i]:
                readers.append(gather.LocalPlaneReader(
                    base + to_ext(i), li.alpha, li.window,
                    rplan.plane_bit, rplan.plane_side, gstats))
            else:
                readers.append(gather.RemotePlaneReader(
                    vid, i, sources[i], li.alpha, li.window,
                    rplan.plane_bit, rplan.plane_side, gstats,
                    hedge_ms=hedge_ms))
        eff_slab = slab or gather.auto_slab(
            shard_size, default=ec_encoder.DEFAULT_SLAB)
        source = gather.PlaneGatherSource(
            readers, shard_size, rplan, li.window, slab=eff_slab,
            gather_window=window, stats=gstats, parent_span=root)
        rstats: dict = {}
        try:
            rebuilt = ec_decoder.rebuild_ec_file_piggyback(
                base, lost, source, rplan, li.window, codec=self.codec,
                slab=source.slab, stats=rstats)
        except HttpError as e:
            if e.status in (404, 405, 501):
                # a holder predates /admin/ec/shard_plane_read (or
                # never had the shard): the repair output was already
                # cleaned up, rerun as a full coupled decode
                return bail(f"holder refused plane read ({e.status})")
            raise
        from ..stats.metrics import observe_transport
        observe_transport("pull", gstats, window=source.window)
        if stats is not None:
            stats.update(rstats)
        return rebuilt

    def _rebuild_streaming_trace(self, vid, base, local, present,
                                 missing, sources, sized, stats, slab,
                                 window, hedge_ms, root, mode):
        """Attempt the trace-repair path; returns the rebuilt shard list
        or None to signal 'use the full streaming gather instead'.
        Forced mode ('trace') converts every fallback into an error;
        'auto' records the reason in stats and lets the caller fall
        through bit-identically."""
        from ..ec import decoder as ec_decoder
        from ..ec import gather
        from ..ops import codec as ops_codec
        from ..server.http_util import HttpError

        def bail(reason: str):
            if mode == "trace":
                raise VolumeError(f"-repair trace: {reason}")
            if stats is not None:
                stats["repair_fallback"] = reason
            return None

        if len(missing) != 1:
            return bail(f"{len(missing)} shards lost, trace repairs one")
        lost = missing[0]
        k = self.codec.k if self.codec is not None else DATA_SHARDS
        m = (self.codec.m if self.codec is not None
             else TOTAL_SHARDS - DATA_SHARDS)
        helpers = [i for i, p in enumerate(present) if p and i != lost]
        try:
            plan = ops_codec.repair_plan(
                k, m, lost, survivors=helpers,
                matrix_kind=(self.codec.matrix_kind
                             if self.codec is not None else "vandermonde"),
                matrix=(self.codec.matrix
                        if self.codec is not None else None))
        except ValueError as e:
            return bail(f"no repair scheme: {e}")
        if mode == "auto" and plan.frac >= 1.0:
            return bail(f"no trace gain (frac={plan.frac:.3f})")
        shard_size = sized(plan.helpers)
        gstats = gather.GatherStats()
        readers = []
        for i in plan.helpers:
            if local[i]:
                readers.append(gather.LocalRepairReader(
                    base + to_ext(i), plan.masks[i], gstats))
            else:
                readers.append(gather.RemoteRepairReader(
                    vid, i, sources[i], plan.masks[i], gstats,
                    hedge_ms=hedge_ms))
        eff_slab = slab or gather.auto_slab(
            shard_size, default=ec_encoder.DEFAULT_SLAB)
        source = gather.RepairGatherSource(
            readers, shard_size, plan, slab=eff_slab,
            window=window, stats=gstats, parent_span=root)
        rstats: dict = {}
        try:
            rebuilt = ec_decoder.rebuild_ec_file_repair(
                base, lost, source, plan, codec=self.codec,
                slab=eff_slab, stats=rstats)
        except HttpError as e:
            if e.status in (404, 405, 501):
                # a holder predates /admin/ec/shard_repair_read (or
                # never had the shard): the repair output was already
                # cleaned up, rerun as a plain streaming gather
                return bail(f"holder refused repair read ({e.status})")
            raise
        from ..stats.metrics import observe_transport
        observe_transport("pull", gstats, window=source.window)
        if stats is not None:
            stats.update(rstats)
        return rebuilt

    # -- heartbeat (reference store.go:193-247 CollectHeartbeat) -----------
    def collect_heartbeat(self) -> dict:
        volumes = []
        ec_shards: Dict[int, int] = {}
        ec_collections: Dict[int, str] = {}
        max_file_key = 0
        max_volume_count = 0
        for loc in self.locations:
            max_volume_count += loc.max_volume_count
            for vid, v in list(loc.volumes.items()):
                max_file_key = max(max_file_key, v.max_file_key())
                volumes.append({
                    "id": vid,
                    "collection": v.collection,
                    "size": v.size(),
                    "file_count": v.file_count(),
                    "delete_count": v.deleted_count(),
                    "deleted_byte_count": v.deleted_size(),
                    "read_only": v.readonly,
                    "replica_placement":
                        str(v.super_block.replica_placement),
                    "ttl": v.super_block.ttl.to_uint32(),
                    "version": v.version,
                    "compact_revision": v.super_block.compaction_revision,
                    "modified_at": v.last_modified,
                })
            for vid, ev in loc.ec_volumes.items():
                bits = 0
                for sid in ev.shard_ids():
                    bits |= 1 << sid
                ec_shards[vid] = bits
                ec_collections[vid] = ev.collection
        return {
            "ip": self.ip, "port": self.port, "public_url": self.public_url,
            "data_center": self.data_center, "rack": self.rack,
            "max_volume_count": max_volume_count,
            "max_file_key": max_file_key,
            "volumes": volumes,
            "ec_shards": ec_shards,
            "ec_collections": ec_collections,
        }

    def status(self) -> dict:
        hb = self.collect_heartbeat()
        hb["directories"] = [loc.directory for loc in self.locations]
        return hb

    def close(self):
        for loc in self.locations:
            loc.close()
