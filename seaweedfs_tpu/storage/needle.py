"""Needle — one stored blob + metadata (Facebook Haystack record).

Disk layout is byte-compatible with the reference
(weed/storage/needle/needle_read_write.go):

  header (16B): Cookie(4) NeedleId(8) Size(4), big-endian
  v1 body:      Data[Size] CRC(4) padding
  v2 body:      DataSize(4) Data Flags(1) [NameSize(1) Name] [MimeSize(1)
                Mime] [LastModified(5)] [TTL(2)] [PairsSize(2) Pairs]
                CRC(4) padding          (body present only when DataSize>0;
                                         Size covers body w/o CRC/padding)
  v3 body:      v2 body + AppendAtNs(8) between CRC and padding

  padding: to the next multiple of 8 of (16 + Size + 4 [+ 8]); the
  reference's PaddingLength never returns 0 — a fully aligned needle still
  gets 8 bytes of padding (needle_read_write.go:287-293) — reproduced here.

  CRC is Castagnoli over Data only, stored masked (crc.py).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from . import crc as crc_mod
from .types import (
    COOKIE_SIZE, CURRENT_VERSION, NEEDLE_CHECKSUM_SIZE, NEEDLE_HEADER_SIZE,
    NEEDLE_ID_SIZE, NEEDLE_PADDING_SIZE, TIMESTAMP_SIZE, TTL, VERSION1,
    VERSION2, VERSION3, format_needle_id_cookie,
)

FLAG_GZIP = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED_DATE = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2


def padding_length(needle_size: int, version: int) -> int:
    base = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    if version == VERSION3:
        base += TIMESTAMP_SIZE
    return NEEDLE_PADDING_SIZE - (base % NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    extra = TIMESTAMP_SIZE if version == VERSION3 else 0
    return (needle_size + NEEDLE_CHECKSUM_SIZE + extra
            + padding_length(needle_size, version))


def get_actual_size(size: int, version: int) -> int:
    return NEEDLE_HEADER_SIZE + needle_body_length(size, version)


class CorruptNeedle(Exception):
    pass


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0            # Size field as stored in header/index
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    last_modified: int = 0   # unix seconds (5 bytes on disk)
    ttl: TTL = field(default_factory=TTL)
    pairs: bytes = b""       # serialized extended attributes
    checksum: int = 0
    append_at_ns: int = 0

    # -- flag helpers ------------------------------------------------------
    def _flag(self, bit: int) -> bool:
        return bool(self.flags & bit)

    def has_name(self): return self._flag(FLAG_HAS_NAME)
    def has_mime(self): return self._flag(FLAG_HAS_MIME)
    def has_last_modified(self): return self._flag(FLAG_HAS_LAST_MODIFIED_DATE)
    def has_ttl(self): return self._flag(FLAG_HAS_TTL)
    def has_pairs(self): return self._flag(FLAG_HAS_PAIRS)
    def is_gzipped(self): return self._flag(FLAG_GZIP)
    def is_chunk_manifest(self): return self._flag(FLAG_IS_CHUNK_MANIFEST)

    def set_name(self, name: bytes):
        self.name = name[:255]
        self.flags |= FLAG_HAS_NAME

    def set_mime(self, mime: bytes):
        self.mime = mime[:255]
        self.flags |= FLAG_HAS_MIME

    def set_last_modified(self, ts: int = 0):
        self.last_modified = ts or int(time.time())
        self.flags |= FLAG_HAS_LAST_MODIFIED_DATE

    def set_ttl(self, ttl: TTL):
        if ttl.to_uint32():
            self.ttl = ttl
            self.flags |= FLAG_HAS_TTL

    def set_pairs(self, pairs: bytes):
        self.pairs = pairs
        self.flags |= FLAG_HAS_PAIRS

    def set_gzipped(self):
        self.flags |= FLAG_GZIP

    def set_is_chunk_manifest(self):
        """Mark the payload as a chunk-manifest JSON (reference
        needle_read_write.go:22 FlagIsChunkManifest): readers resolve it
        to the chunk needles it lists, deletes cascade to them."""
        self.flags |= FLAG_IS_CHUNK_MANIFEST

    @property
    def etag(self) -> str:
        return struct.pack(">I", self.checksum).hex()

    def fid_suffix(self) -> str:
        return format_needle_id_cookie(self.id, self.cookie)

    # -- serialization -----------------------------------------------------
    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        self.checksum = crc_mod.needle_checksum(self.data)
        if version == VERSION1:
            self.size = len(self.data)
            out = bytearray()
            out += struct.pack(">IQI", self.cookie, self.id, self.size)
            out += self.data
            out += struct.pack(">I", self.checksum)
            out += b"\x00" * padding_length(self.size, version)
            return bytes(out)
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        body = bytearray()
        if len(self.data) > 0:
            body += struct.pack(">I", len(self.data))
            body += self.data
            body.append(self.flags & 0xFF)
            if self.has_name():
                name = self.name[:255]
                body.append(len(name))
                body += name
            if self.has_mime():
                mime = self.mime[:255]
                body.append(len(mime))
                body += mime
            if self.has_last_modified():
                body += struct.pack(">Q", self.last_modified)[
                    8 - LAST_MODIFIED_BYTES_LENGTH:]
            if self.has_ttl():
                body += self.ttl.to_bytes()
            if self.has_pairs():
                body += struct.pack(">H", len(self.pairs))
                body += self.pairs
        self.size = len(body)

        out = bytearray()
        out += struct.pack(">IQI", self.cookie, self.id, self.size)
        out += body
        out += struct.pack(">I", self.checksum)
        if version == VERSION3:
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\x00" * padding_length(self.size, version)
        return bytes(out)

    @classmethod
    def parse_header(cls, blob: bytes) -> "Needle":
        cookie, nid, size = struct.unpack(">IQI", blob[:NEEDLE_HEADER_SIZE])
        return cls(cookie=cookie, id=nid, size=size)

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = CURRENT_VERSION,
                   expected_size: int = None,
                   verify_crc: bool = True) -> "Needle":
        """Hydrate from a full needle blob (header..padding).

        verify_crc=False skips the whole-payload checksum — for callers
        that only need metadata fields (e.g. vacuum's TTL check reads
        last_modified and must not pay a full CRC per live needle)."""
        n = cls.parse_header(blob)
        if expected_size is not None and n.size != expected_size:
            raise CorruptNeedle(
                f"needle {n.id}: size {n.size} != index size {expected_size}")
        size = n.size
        if version == VERSION1:
            n.data = blob[NEEDLE_HEADER_SIZE:NEEDLE_HEADER_SIZE + size]
        elif version in (VERSION2, VERSION3):
            n._parse_body_v2(blob[NEEDLE_HEADER_SIZE:NEEDLE_HEADER_SIZE + size])
        else:
            raise ValueError(f"unsupported needle version {version}")
        if size > 0:
            stored = struct.unpack(
                ">I", blob[NEEDLE_HEADER_SIZE + size:
                           NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE])[0]
            if verify_crc:
                actual = crc_mod.needle_checksum(n.data)
                if stored != actual:
                    raise CorruptNeedle(f"needle {n.id}: CRC mismatch")
            n.checksum = stored
        if version == VERSION3:
            ts_off = NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE
            n.append_at_ns = struct.unpack(
                ">Q", blob[ts_off:ts_off + TIMESTAMP_SIZE])[0]
        return n

    def _parse_body_v2(self, b: bytes):
        idx, ln = 0, len(b)
        if idx < ln:
            if idx + 4 > ln:
                raise CorruptNeedle("truncated data-size field")
            data_size = struct.unpack(">I", b[idx:idx + 4])[0]
            idx += 4
            if data_size + idx >= ln:  # flags byte must follow the data
                raise CorruptNeedle("data size out of range")
            self.data = b[idx:idx + data_size]
            idx += data_size
            self.flags = b[idx]
            idx += 1
        if idx < ln and self.has_name():
            nsize = b[idx]
            idx += 1
            self.name = b[idx:idx + nsize]
            idx += nsize
        if idx < ln and self.has_mime():
            msize = b[idx]
            idx += 1
            self.mime = b[idx:idx + msize]
            idx += msize
        if idx < ln and self.has_last_modified():
            self.last_modified = int.from_bytes(
                b[idx:idx + LAST_MODIFIED_BYTES_LENGTH], "big")
            idx += LAST_MODIFIED_BYTES_LENGTH
        if idx < ln and self.has_ttl():
            self.ttl = TTL.from_bytes(b[idx:idx + TTL_BYTES_LENGTH])
            idx += TTL_BYTES_LENGTH
        if idx < ln and self.has_pairs():
            psize = struct.unpack(">H", b[idx:idx + 2])[0]
            idx += 2
            self.pairs = b[idx:idx + psize]
            idx += psize
